"""The fused server pipeline step: deli ticketing + merge-tree apply +
summary-length reduction in one jit program — the device half of a
partition lambda (reference Deli -> Scriptorium/Scribe stage fusion,
SURVEY.md §2.6.3 pipeline parallelism).

The ticketing output FEEDS the apply: each op's assigned sequence number and
msn replace the packed columns, and ops the sequencer rejected (nack) or
dropped (duplicate) are turned into NOOPs before the merge-tree sees them —
the document state can only contain what the sequencer admitted.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

from ..mergetree import kernel
from ..mergetree.oppack import OpKind
from ..mergetree.pallas_ops import summary_lengths
from . import ticket_kernel as tk


def make_full_step(sp_shards: int = 1, fused_apply: bool = False):
    """Build the fused pipeline step for a given sequence-parallel factor:
    with sp_shards > 1 the merge kernel's visibility prefix sums use the
    two-level collective-scan formulation (kernel._cumsum_sp), so a
    capacity axis sharded over 'sp' resolves positions with shard-local
    cumsums + a tiny cross-shard offset exchange instead of a serialized
    full-axis scan (SURVEY.md §5 long-context mapping).

    fused_apply=True routes the merge apply through the VMEM-resident
    Pallas kernel (mergetree/pallas_apply.py — one HBM read+write for the
    whole op stream). fused_apply with sp_shards > 1 composes the SAME
    fused formulation with sequence-axis sharding (mergetree/fused_sp.py):
    per-shard lane tiles with two-level collective prefix sums, so long
    documents and the flagship kernel are no longer mutually exclusive.

    Naming note: this flag is the capacity-gated KERNEL experiment bench
    drives (stamped `fused_apply_kernel_exp` in BENCH records since
    round 8). The PRODUCTION serving path's `fused_apply` stamp means
    something stronger — the sequencer actually ran scanned multi-window
    serving bursts (serve_step.serve_burst,
    docs/serving_pipeline.md R8)."""

    def full_step(tstate, mstate, raw, ops):
        """(ticket_state, merge_state, RawOps, PackedOps) ->
        (ticket_state, merge_state, Ticketed, per-doc visible length)."""
        tstate, ticketed = tk._scan_tickets(tstate, raw, batched=True)
        admitted = ticketed.seq > 0
        ops2 = ops._replace(
            kind=jnp.where(admitted, ops.kind, OpKind.NOOP),
            seq=jnp.where(admitted, ticketed.seq, ops.seq),
            msn=jnp.where(admitted, ticketed.min_seq, ops.msn),
        )
        from ..mergetree.pallas_apply import FUSED_MAX_CAPACITY
        if fused_apply and sp_shards > 1:
            from ..mergetree.fused_sp import _fused_sp_body
            mstate = _fused_sp_body(mstate, ops2, sp_shards)
        elif fused_apply and mstate.capacity <= FUSED_MAX_CAPACITY:
            from ..mergetree.pallas_apply import apply_ops_fused_pallas
            mstate = apply_ops_fused_pallas(mstate, ops2)
        else:
            # Very large capacities exceed the fused kernel's VMEM budget;
            # the scan×vmap kernel covers them.
            mstate = kernel._scan_ops(mstate, ops2, batched=True,
                                      sp_shards=sp_shards)
        # Summary-length reduction: fused Pallas pass on TPU, jnp elsewhere
        # (mergetree/pallas_ops.py; semantics == visibility(s, s.seq, ...)).
        total_len = summary_lengths(mstate)
        return tstate, mstate, ticketed, total_len

    return full_step


full_step = make_full_step(1)
