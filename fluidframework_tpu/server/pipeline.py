"""The fused server pipeline step: deli ticketing + merge-tree apply +
summary-length reduction in one jit program — the device half of a
partition lambda (reference Deli -> Scriptorium/Scribe stage fusion,
SURVEY.md §2.6.3 pipeline parallelism)."""

from __future__ import annotations

import jax

from ..mergetree import kernel
from . import ticket_kernel as tk


def full_step(tstate, mstate, raw, ops):
    """(ticket_state, merge_state, RawOps, PackedOps) ->
    (ticket_state, merge_state, per-op seqs [B, T], per-doc visible length)."""
    tstate, ticketed = tk._scan_tickets(tstate, raw, batched=True)
    mstate = kernel._scan_ops(mstate, ops, batched=True)
    total_len = jax.vmap(
        lambda s: kernel.visibility(s, s.seq, -2)[1].sum())(mstate)
    return tstate, mstate, ticketed.seq, total_len
