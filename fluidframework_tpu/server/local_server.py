"""LocalServer: the full ordering service in one process.

Capability parity with reference local-server's LocalDeltaConnectionServer
(localDeltaConnectionServer.ts:59) + memory-orderer's LocalOrderer
(localOrderer.ts:87-260): the *real* Deli/Scriptorium/Scribe/Broadcaster/
Copier lambdas run over the in-memory MessageLog ("LocalKafka"), fronted by
an Alfred-shaped connection API — the contract point for the local driver
and the test backbone (SURVEY.md §4.4).

Message flow (reference docker-compose pipeline):
  Connection.submit -> boxcar -> 'rawdeltas' topic
  DeliLambda: ticket -> 'deltas' topic (+ nacks straight to the socket)
  ScriptoriumLambda -> deltas collection (catch-up queries)
  ScribeLambda -> summary commits + summaryAck/Nack back through 'rawdeltas'
  BroadcasterLambda -> connected Connection listeners
"""

from __future__ import annotations

import itertools
import threading
import uuid
from typing import Any, Callable, Dict, List, Optional

from ..core.events import TypedEventEmitter
from ..telemetry import tracing
from ..telemetry.counters import record_swallow
from ..protocol.messages import (
    Boxcar,
    DocumentMessage,
    MessageType,
    Nack,
    NackContent,
    NACK_NOT_WRITER,
    NACK_SERVICE_UNAVAILABLE,
    NACK_THROTTLED,
    NACK_TOO_LARGE,
    SequencedDocumentMessage,
    SignalMessage,
    op_size,
)
from ..telemetry.counters import increment
from . import admission as admission_mod
from .admission import AdmissionController, admission_from_config
from .database import DatabaseManager
from .lambdas import (
    BroadcasterLambda,
    CopierLambda,
    DeliLambda,
    ScribeLambda,
    ScriptoriumLambda,
)
from .lambdas.scriptorium import delta_key, query_deltas
from .log import MessageLog, make_message_log
from .partition import (LambdaRunner, OverlappedLambdaRunner,
                        PartitionManager)
from .sharding import SequencerShardSet
from .storage import Historian

RAW_TOPIC = "rawdeltas"
DELTAS_TOPIC = "deltas"


class _TokenBucket:
    """Per-connection op-rate limiter (reference alfred throttler):
    refills at `rate` ops/s up to `burst`; take() returns 0.0 when
    admitted or the seconds to wait (the 429 retryAfter)."""

    def __init__(self, rate: float, burst: float):
        import time as _time
        self.rate = rate
        self.burst = burst
        self.tokens = burst
        self._now = _time.monotonic
        self.last = self._now()

    def take(self, n: int = 1) -> float:
        """Debt model: admitted whenever at least one token is available,
        going negative for n > balance — a boxcar'd resubmit batch larger
        than the burst must still be admittable EVENTUALLY (batches are
        atomic and cannot split), it just pays the debt in future waits.
        Classic take-n-or-nack would livelock such a batch forever."""
        now = self._now()
        self.tokens = min(self.burst,
                          self.tokens + (now - self.last) * self.rate)
        self.last = now
        if self.tokens >= 1.0:
            self.tokens -= n
            return 0.0
        return (1.0 - self.tokens) / self.rate


class Connection(TypedEventEmitter):
    """A client's delta connection (the "websocket"). Events: "op"
    (SequencedDocumentMessage), "nack" (Nack), "signal" (SignalMessage),
    "disconnect"."""

    def __init__(self, server: "LocalServer", tenant_id: str,
                 document_id: str, client_id: str, details: Optional[dict]):
        super().__init__()
        self.server = server
        self.tenant_id = tenant_id
        self.document_id = document_id
        self.client_id = client_id
        self.details = details or {}
        # "read" connections observe the room (ops + signals) without ever
        # entering the quorum or the MSN calculation (reference read/write
        # connection modes: only writers order a join op).
        self.mode = self.details.get("mode", "write")
        # Throttle bucket is per DOCUMENT and lives on the server (the
        # reference alfred throttler keys tenant+document): a client
        # cannot mint a fresh budget by reconnecting.
        self.bucket = server._throttle_bucket(document_id)
        self.connected = True

    def submit(self, messages: List[DocumentMessage]) -> None:
        if not self.connected:
            raise ConnectionError("connection closed")
        if self.mode == "read":
            self.emit("nack", Nack(
                messages[0] if messages else None, -1,
                NackContent(NACK_NOT_WRITER,
                            "read connections cannot submit ops")))
            return
        # Op-size ceiling at the front door (reference alfred
        # maxMessageSize): oversized content nacks 413 before entering the
        # pipeline — the ONE choke point both sequencer paths sit behind,
        # off the partition-lambda hot path. Clients chunk far below it.
        limit = self.server.max_op_bytes
        if limit:
            for msg in messages:
                if op_size(msg) > limit:
                    self.emit("nack", Nack(
                        msg, -1, NackContent(
                            NACK_TOO_LARGE,
                            f"op exceeds {limit} bytes")))
                    return
        # Overload admission (server/admission.py): the GLOBAL gate —
        # occupancy-driven throttle/shed/degrade with a server-computed
        # retry_after — sits before the per-document token bucket (a
        # local rate nit is pointless to evaluate on traffic the process
        # cannot absorb at all).
        adm = self.server.admission
        # The document's home partition (sharded ingest tier): admission
        # applies its per-partition fairness bound on top of the global
        # ladder, so one hot partition throttles without starving
        # siblings. None on a single-partition core (no gate to apply).
        part = self.server.ingest_partition(self.document_id)
        if adm is not None and messages:
            ctx = tracing.first_message_context(messages)
            # The whole batch rides ONE boxcar record — the unit
            # raw_backlog() polls — so it enters the controller's queue
            # accounting as records=1 while credits/counters see the op
            # count.
            decision = adm.admit(
                self.tenant_id, kind=admission_mod.CLASS_OP,
                count=len(messages), records=1,
                partition=part,
                trace_id=getattr(ctx, "trace_id", None))
            if not decision.admitted:
                code = NACK_SERVICE_UNAVAILABLE \
                    if decision.state == admission_mod.DEGRADE \
                    else NACK_THROTTLED
                self.emit("nack", Nack(
                    messages[0] if messages else None, -1,
                    NackContent(code,
                                f"admission {decision.state}: "
                                f"{decision.reason}",
                                retry_after_s=decision.retry_after_s)))
                return
        if self.bucket is not None:
            wait = self.bucket.take(len(messages))
            if wait > 0:
                # Reference alfred throttler: nack 429 with retryAfter;
                # the client backs off and resubmits. The admitted batch
                # never reaches the queue — retract it so the phantom
                # record doesn't read as drained at the next observe.
                if adm is not None and messages:
                    adm.retract(len(messages), records=1, partition=part,
                                tenant=self.tenant_id)
                self.emit("nack", Nack(
                    messages[0] if messages else None, -1,
                    NackContent(NACK_THROTTLED, "op rate limit",
                                retry_after_s=wait)))
                return
        # The ingest span parents on the first stamped op in the batch;
        # with auto_pump the whole pipeline pump (deli ticket, serving
        # flush, fan-out) nests under it on this thread.
        with tracing.span("server.ingest",
                          parent=tracing.first_message_context(messages),
                          document=self.document_id):
            # The home partition computed for the admission gate above
            # rides through so the produce path never hashes twice.
            self.server._submit_boxcar(Boxcar(
                tenant_id=self.tenant_id, document_id=self.document_id,
                client_id=self.client_id, contents=list(messages)),
                partition=part)

    def submit_signal(self, content: Any) -> None:
        """Transient broadcast: the signal fans out to every connection in
        the document's room (submitter included) WITHOUT touching the
        sequencer or the log — client-relative ordering only (reference
        alfred submitSignal, lambdas/src/alfred/index.ts:305-328)."""
        if not self.connected:
            raise ConnectionError("connection closed")
        adm = self.server.admission
        if adm is not None:
            # Signals are the FIRST class shed under pressure (transient
            # presence traffic, cheap to regenerate): dropped silently —
            # a fire-and-forget channel has no retry contract.
            decision = adm.admit(self.tenant_id,
                                 kind=admission_mod.CLASS_SIGNAL)
            if not decision.admitted:
                return
        self.server._broadcast_signal(self.document_id, SignalMessage(
            client_id=self.client_id, content=content))

    def disconnect(self) -> None:
        if not self.connected:
            return
        self.connected = False
        self.server._client_leave(self)
        self.emit("disconnect")


class LocalServer:
    """One in-process ordering + storage service (single tenant scope per
    instance is fine; tenant_id still namespaces storage)."""

    def __init__(self, tenant_id: str = "local", partitions: int = 1,
                 auto_pump: bool = True,
                 native_log: Optional[bool] = False,
                 db: Optional[DatabaseManager] = None,
                 historian: Optional[Historian] = None,
                 config=None, overlapped: bool = False,
                 admission: Optional[AdmissionController] = None):
        """native_log: False = pure-Python broker (default, the LocalKafka
        role); True = the C++ engine (requires the toolchain); None = auto.

        admission: an overload controller to share (alfred passes ONE
        across every tenant core so credits fair-share between tenants);
        None constructs a per-core controller unless config disables it
        (admission.enabled=false).

        db/historian: pass shared instances to make this core one node of a
        cluster over common durable services (the reference's Mongo + git);
        deli/scribe then resume from any checkpoints already present —
        the multi-node takeover path (server/nodes.py).

        overlapped: pump the lambda stages concurrently (OverlappedLambda
        Runner — sequencing batch N+1 while batch N's persistence flushes);
        the serial runner stays the deterministic default."""
        self.tenant_id = tenant_id
        self.auto_pump = auto_pump
        self.overlapped = overlapped
        # Front-door op-size ceiling (alfred.maxMessageSize; 0 disables).
        self.max_op_bytes = 1024 * 1024
        # Per-connection op-rate throttling (reference alfred throttler):
        # disabled unless configured — in-process tests and benches hammer
        # ops by design.
        self.throttle_ops_per_s = 0.0
        self.throttle_burst = 0.0
        self._throttle_buckets: Dict[str, _TokenBucket] = {}
        if config is not None:
            self.max_op_bytes = int(config.get(
                "alfred.maxMessageSize", self.max_op_bytes))
            self.throttle_ops_per_s = float(config.get(
                "alfred.throttling.opsPerSecond", 0))
            self.throttle_burst = float(config.get(
                "alfred.throttling.burst", 0))
            if self.throttle_ops_per_s and self.throttle_burst <= 0:
                # An explicit burst of 0 with a live rate would nack every
                # op forever (empty bucket can never refill past 0):
                # treat non-positive as "derive a sane default".
                self.throttle_burst = max(self.throttle_ops_per_s * 2, 10)
        self.log = make_message_log(default_partitions=partitions,
                                    native=native_log)
        self.db = db if db is not None else DatabaseManager()
        self.historian = historian if historian is not None else Historian()
        self.deltas = self.db.collection("deltas", unique_key=delta_key)
        self.raw_deltas = self.db.collection("rawdeltas")
        self.deli_checkpoints = self.db.collection("deliCheckpoints")
        self.scribe_checkpoints = self.db.collection("scribeCheckpoints")
        self._connections: Dict[str, List[Connection]] = {}
        # Fired after scribe validates + commits a summary (advancing the
        # ref): (document_id, commit_sha). The historian cache tier hooks
        # in here for write-through invalidation + warm prefetch
        # (server/historian.py; alfred registers the notifier).
        self.summary_commit_listeners: List[Callable[[str, str], None]] = []
        # Broadcaster room membership lives here (not in the lambda) so it
        # survives lambda crash-restarts; the lambda reads it by reference.
        self._rooms: Dict[str, List] = {}
        # Sharded broadcast fan-out (docs/read_path.md): 0 = inline
        # (deterministic, the pump delivers synchronously — the default
        # every in-process test relies on); N > 0 = doc-hash-sharded
        # worker threads with bounded per-shard queues, so a reconnect
        # avalanche or one hot document cannot serialize every
        # subscriber through a single pump thread.
        self.broadcaster_shards = 0
        self.broadcaster_queue_limit = 1024
        if config is not None:
            self.broadcaster_shards = int(config.get(
                "broadcaster.shards", 0))
            self.broadcaster_queue_limit = int(config.get(
                "broadcaster.queueLimit", self.broadcaster_queue_limit))
        self.broadcasters: List[BroadcasterLambda] = []
        # Read-path catch-up artifacts (server/readpath.py): populated by
        # TpuLocalServer (artifacts are materialized from device lanes);
        # the scalar pipeline serves None and clients tail-replay.
        self.catchup = None
        # Fired on every artifact publish: (tenant_id, document_id,
        # artifact) — an external historian tier hooks in here the same
        # way summary_commit_listeners feeds cache invalidation.
        self.catchup_listeners: List[Callable[[str, str, dict], None]] = []
        # Signal fan-out rooms: transient messages never enter the log, so
        # they get their own listener lists (reference: socket.io room emit
        # straight from alfred, no Kafka hop).
        self._signal_rooms: Dict[str, List] = {}
        self._client_counter = itertools.count(1)
        self._pump_lock = threading.RLock()
        # Optional pre-pump gate (multi-node fencing): called before the
        # lambdas run; returning False aborts the pump — the node lost its
        # reservation and must not sequence another op (server/nodes.py).
        self.pump_gate: Optional[Callable[[], bool]] = None

        # Ensure topics exist before wiring consumers.
        self.log.topic(RAW_TOPIC)
        self.log.topic(DELTAS_TOPIC)

        self.runner = (OverlappedLambdaRunner() if overlapped
                       else LambdaRunner())
        # Per-service config (the reference's nconf slice per lambda,
        # services-core/src/lambdas.ts:56). Batched deli checkpointing
        # requires the pump's eager offset commit OFF so the replay window
        # matches the saved state.
        self.config = config
        # The sequencing stage lives in the sharded ingest tier
        # (server/sharding.py): one sequencer lambda per raw-topic
        # partition, restart-stable md5 document routing, per-partition
        # checkpoint scoping, batched cross-partition acks, and the
        # per-partition pump accounting the monitor and the ingest bench
        # read. Partition state is the TIER's, not this class's — the
        # decoupling refactor the ROADMAP's million-ops item counts.
        self.ingest = self._build_ingest_tier()
        self._deli_mgr = self.runner.add(self.ingest.manager)
        self._copier_mgr = self.runner.add(PartitionManager(
            self.log, "copier", RAW_TOPIC,
            lambda ctx: CopierLambda(ctx, self.raw_deltas), offload=True))
        self._scriptorium_mgr = self.runner.add(PartitionManager(
            self.log, "scriptorium", DELTAS_TOPIC,
            lambda ctx: ScriptoriumLambda(ctx, self.deltas), offload=True))
        self._scribe_mgr = self.runner.add(PartitionManager(
            self.log, "scribe", DELTAS_TOPIC,
            lambda ctx: ScribeLambda(ctx, self.historian, tenant_id,
                                     send_system=self._send_system,
                                     checkpoints=self.scribe_checkpoints,
                                     fresh_log=True,
                                     on_commit=self._on_summary_commit),
            offload=True))
        self._broadcaster_mgr = self.runner.add(PartitionManager(
            self.log, "broadcaster", DELTAS_TOPIC,
            self._build_broadcaster))

        # Overload admission (server/admission.py): the occupancy-driven
        # front door every Connection.submit/submit_signal passes. A
        # shared controller (alfred) or a per-core one from config;
        # admission.enabled=false opts a core out entirely.
        self.admission = admission if admission is not None \
            else admission_from_config(config)
        if self.admission is not None:
            self._wire_admission()

    # -- internal wiring ---------------------------------------------------
    def _build_broadcaster(self, ctx) -> BroadcasterLambda:
        # A crash-restart (PartitionPump.restart closes the old lambda,
        # then re-invokes this factory) must not leave the superseded
        # instance in the registry: the occupancy feed, drain_broadcast,
        # and the monitor probe would sum dead shards forever.
        self.broadcasters = [b for b in self.broadcasters if not b.closed]
        lam = BroadcasterLambda(ctx, rooms=self._rooms,
                                shards=self.broadcaster_shards,
                                queue_limit=self.broadcaster_queue_limit)
        self.broadcasters.append(lam)
        return lam

    def broadcast_queue_depth(self) -> int:
        """Total fan-out backlog across every broadcaster shard (0 in
        inline mode) — the read tier's occupancy feed for admission."""
        return sum(lam.queue_depth() for lam in self.broadcasters)

    def drain_broadcast(self, timeout: float = 10.0) -> bool:
        """Block until every sharded fan-out queue is empty (inline mode
        returns immediately). Tests and benches that need delivered-after-
        pump semantics under sharding call this where they used to rely
        on the pump's synchronous fan-out."""
        ok = True
        for lam in self.broadcasters:
            ok = lam.drain(timeout) and ok
        return ok

    def raw_backlog(self) -> int:
        """Raw-topic ingest backlog: messages appended but not yet
        consumed by the sequencing stage (per partition: end offset minus
        the deli group's committed offset). Counts broker records
        (boxcars), the unit the partition pumps drain in — the admission
        controller's primary occupancy feed. Multi-partition audit
        (docs/ingest_sharding.md): a submit batch is ONE boxcar on ONE
        partition, so `admit(count=N, records=1)` stays calibrated
        against this sum for any partition count — per-partition feeds
        go through the controller's SEPARATE partition channel and are
        never added into the global depth (that double-count would
        re-introduce the PR 6 phantom-drain inflation, N-fold)."""
        return self.ingest.raw_backlog()

    def raw_backlog_by_partition(self) -> Dict[int, int]:
        """Per-partition record backlog (monitor watch_partitions)."""
        return self.ingest.raw_backlog_by_partition()

    def ingest_partition(self, document_id: str) -> Optional[int]:
        """A document's home partition, or None on a single-partition
        core (admission then skips the per-partition fairness gate)."""
        if self.ingest.partitions <= 1:
            return None
        return self.ingest.partition_for(document_id)

    def rebalance_document(self, document_id: str, target: int) -> int:
        """Live-rebalance one document's sequencing to ``target`` with
        no fleet drain (server/sharding.py rebalance_doc): routing-epoch
        bump + handoff marker on the raw topic itself. Returns the new
        routing epoch. Per-doc emit order is provably identical across
        the move (docs/ingest_sharding.md)."""
        epoch = self.ingest.rebalance_doc(document_id, target)
        if self.auto_pump:
            self.pump()
        return epoch

    def _wire_admission(self) -> None:
        adm = self.admission
        adm.add_source(f"core:{self.tenant_id}",
                       queue_depth=self.raw_backlog)
        if self.ingest.partitions > 1:
            # Per-partition occupancy feeds the fairness gate only (see
            # raw_backlog docstring for why they must not join the
            # global sum).
            self.ingest.register_admission(adm, self.tenant_id)
        if self.broadcaster_shards:
            # The read tier's occupancy feed: a fan-out backlog (reconnect
            # avalanche, hot-document room) pressures the same admission
            # ladder the write side does, so ingest throttles before the
            # shard queues have to shed.
            adm.add_source(f"broadcast:{self.tenant_id}",
                           queue_depth=self.broadcast_queue_depth)
        # DEGRADE survival mode: pause the archival pumps (copier raw
        # persistence, scribe summaries) so every cycle goes to draining
        # the sequencer. Their consumer offsets hold their place in the
        # log; on de-escalation they resume and replay the gap — work is
        # deferred, never lost. Deli/broadcaster stay live (they ARE the
        # drain) and scriptorium keeps catch-up queries truthful.
        def pause() -> None:
            for mgr in (self._copier_mgr, self._scribe_mgr):
                for pump in mgr.pumps.values():
                    pump.pause()

        def resume() -> None:
            for mgr in (self._copier_mgr, self._scribe_mgr):
                for pump in mgr.pumps.values():
                    pump.resume()

        adm.add_degrade_hooks(pause, resume)

    def _build_ingest_tier(self) -> SequencerShardSet:
        """The sequencing stage as a sharded tier (server/sharding.py):
        one lambda per raw-topic partition via _sequencer_factory
        (scalar DeliLambda here; TpuLocalServer overrides with the
        device-batched TpuSequencerLambda)."""
        return SequencerShardSet(
            self.log, RAW_TOPIC, "deli", self._sequencer_factory,
            checkpoints=self.deli_checkpoints,
            auto_commit=self._sequencer_auto_commit())

    def _sequencer_factory(self, ctx, checkpoints):
        return DeliLambda(ctx, emit=self._emit_sequenced,
                          nack=self._emit_nack,
                          checkpoints=checkpoints,
                          fresh_log=True,
                          config=self.config,
                          send_system=self._send_system)

    def _sequencer_auto_commit(self) -> bool:
        deli_batched = bool(self.config is not None and int(
            self.config.get("deli.checkpointBatchSize", 1)) > 1)
        return not deli_batched

    def _emit_sequenced(self, doc_id: str,
                        sequenced: SequencedDocumentMessage) -> None:
        # Explicit-partition produce through the shared md5 router: the
        # deltas topic mirrors the raw topic's partitioning, so every
        # downstream per-partition consumer (scriptorium/scribe/
        # broadcaster pumps) inherits the ingest tier's document homes
        # instead of the broker's own key hash. BASE routing on purpose:
        # a live rebalance re-homes only the RAW (sequencing-input)
        # side; the document's output stream never changes partitions,
        # so per-doc delivery order stays total across a handoff.
        self.log.send_to(DELTAS_TOPIC,
                         self.ingest.delta_partition_for(doc_id),
                         doc_id, (doc_id, sequenced))

    def _emit_nack(self, doc_id: str, client_id: str, nack: Nack) -> None:
        for conn in self._connections.get(doc_id, []):
            if conn.client_id == client_id and conn.connected:
                conn.emit("nack", nack)

    def _on_summary_commit(self, doc_id: str, commit_sha: str) -> None:
        for listener in list(self.summary_commit_listeners):
            try:
                listener(doc_id, commit_sha)
            except Exception:  # noqa: BLE001 — observers never break scribe
                # Swallowed by design (a historian invalidation hook must
                # not fail the commit) but counted: a climbing rate means
                # the cache tier is no longer invalidating.
                record_swallow("server.summary_commit_listener")

    def _send_system(self, doc_id: str, message: DocumentMessage) -> None:
        self.log.send_to(RAW_TOPIC, self.ingest.partition_for(doc_id),
                         doc_id, Boxcar(
            tenant_id=self.tenant_id, document_id=doc_id, client_id=None,
            contents=[message]))

    def _submit_boxcar(self, boxcar: Boxcar,
                       partition: Optional[int] = None) -> None:
        # Explicit md5-routed produce (server/routing.py): the document's
        # home partition is the tier's decision, never the broker's key
        # hash — restart-stable and shared with the broadcaster shards.
        # Callers that already routed (the admission gate) pass the home
        # through; None recomputes (free on a single-partition core).
        if partition is None:
            partition = self.ingest.partition_for(boxcar.document_id)
        self.log.send_to(RAW_TOPIC, partition,
                         boxcar.document_id, boxcar)
        if self.auto_pump:
            self.pump()

    def _broadcast_signal(self, document_id: str,
                          signal: SignalMessage) -> None:
        for listener in list(self._signal_rooms.get(document_id, [])):
            listener(signal)

    def _throttle_bucket(self, document_id: str) -> Optional[_TokenBucket]:
        if not self.throttle_ops_per_s:
            return None
        bucket = self._throttle_buckets.get(document_id)
        if bucket is None:
            bucket = _TokenBucket(self.throttle_ops_per_s,
                                  self.throttle_burst)
            self._throttle_buckets[document_id] = bucket
        return bucket

    # -- the Alfred surface (connect/disconnect, catch-up, storage) --------
    def connect(self, document_id: str,
                details: Optional[dict] = None) -> Connection:
        # Globally unique id, not a per-core counter: after a multi-node
        # takeover a new core must never reissue an id that appears in the
        # document's history (a late loader would mistake those historical
        # ops for its own and corrupt pending-state/merge-tree visibility).
        client_id = (f"client-{next(self._client_counter)}-"
                     f"{uuid.uuid4().hex[:8]}")
        conn = Connection(self, self.tenant_id, document_id, client_id,
                          details)
        self._connections.setdefault(document_id, []).append(conn)
        # Broadcaster room subscription (removed again at disconnect).
        conn._room_listener = \
            lambda msg, c=conn: c.connected and c.emit("op", msg)
        self._rooms.setdefault(document_id, []).append(conn._room_listener)
        conn._signal_listener = \
            lambda sig, c=conn: c.connected and c.emit("signal", sig)
        self._signal_rooms.setdefault(document_id, []).append(
            conn._signal_listener)
        # Join op through the sequencer (alfred connect_document) — for
        # WRITERS only: readers never enter the quorum or the MSN window.
        if conn.mode != "read":
            import json
            self._send_system(document_id, DocumentMessage(
                client_sequence_number=0, reference_sequence_number=-1,
                type=MessageType.CLIENT_JOIN,
                data=json.dumps({"clientId": client_id,
                                 "detail": conn.details})))
        if self.auto_pump:
            self.pump()
        return conn

    def _client_leave(self, conn: Connection) -> None:
        import json
        room = self._connections.get(conn.document_id, [])
        if conn in room:
            room.remove(conn)
        listeners = self._rooms.get(conn.document_id, [])
        if conn._room_listener in listeners:
            listeners.remove(conn._room_listener)
        sig_listeners = self._signal_rooms.get(conn.document_id, [])
        if conn._signal_listener in sig_listeners:
            sig_listeners.remove(conn._signal_listener)
        if conn.mode == "read":
            return  # never joined; nothing to sequence
        self._send_system(conn.document_id, DocumentMessage(
            client_sequence_number=0, reference_sequence_number=-1,
            type=MessageType.CLIENT_LEAVE,
            data=json.dumps({"clientId": conn.client_id})))
        if self.auto_pump:
            self.pump()

    def get_deltas(self, document_id: str, from_seq: int = 0,
                   to_seq: Optional[int] = None) -> List[dict]:
        """Catch-up range query (alfred delta REST API over the scriptorium
        collection): ops with from_seq < seq <= to_seq, ordered."""
        return query_deltas(self.deltas, document_id, from_seq, to_seq)

    def storage(self, document_id: str):
        return self.historian.store(self.tenant_id, document_id)

    def get_catchup(self, document_id: str) -> Optional[dict]:
        """Read-path catch-up artifact for a document, or None (the
        scalar pipeline materializes no device lanes — clients take the
        tail-replay fallback; TpuLocalServer overrides)."""
        return None

    def pump(self) -> int:
        """Drive every lambda stage to quiescence (synchronous pipeline)."""
        if self.overlapped:
            # Stage workers can re-enter pump (a broadcaster listener
            # submitting an op -> auto_pump): never block on the active
            # pump — its round loop runs until quiescence, so the newly
            # queued message is drained by the pump already in flight.
            if not self._pump_lock.acquire(blocking=False):
                return 0
            try:
                if self.pump_gate is not None and not self.pump_gate():
                    return 0
                return self.runner.pump()
            finally:
                self._pump_lock.release()
        with self._pump_lock:
            if self.pump_gate is not None and not self.pump_gate():
                return 0
            return self.runner.pump()

    # -- introspection ----------------------------------------------------
    def sequence_number(self, document_id: str) -> int:
        # "state" in d: skip handed-off tombstones (a live-rebalanced
        # document leaves one on its old partition's scoped view).
        row = self.deli_checkpoints.find_one(
            lambda d: d.get("documentId") == document_id and "state" in d)
        return row["state"]["sequenceNumber"] if row else 0


class TpuLocalServer(LocalServer):
    """LocalServer whose sequencing stage is the DEVICE pipeline: boxcars
    drain into [B, T] tensors and sequence through ticket_kernel.
    sequence_batched_strict, with admitted merge-tree ops applied to
    device-resident segment tables (server/tpu_sequencer.py) — the
    TPU-batched partition lambda of the north star on the real serving
    path. Scriptorium/Scribe/Broadcaster/Copier are unchanged (host I/O).

    mesh: an optional jax.sharding.Mesh — the sequencer's ticket lanes
    and merge/LWW channel lanes shard over its 'dp' axis (multi-chip
    serving; parallel/mesh.py).

    paged_lanes: store merge segment rows in the refcounted page pool
    (per-doc page tables, gather-by-page-id applies) instead of the
    capacity-bucket grid — document growth appends pages, no
    promote/fold/rescue (docs/paged_memory.md). Single-chip only.
    """

    def __init__(self, *args, mesh=None, paged_lanes=False, **kwargs):
        self.mesh = mesh
        self.paged_lanes = paged_lanes
        super().__init__(*args, **kwargs)
        # Read-path catch-up artifacts (server/readpath.py): ON by
        # default — the cache is empty until a refresh or a read-miss
        # triggers one, so pure write workloads never pay for it.
        from .readpath import CatchupCache
        enabled = True
        if self.config is not None:
            enabled = bool(self.config.get("catchup.enabled", True))
        # partition_of routes the catchup/adopted watermark stamps to the
        # document's ingest home (telemetry/watermarks.py).
        self.catchup = CatchupCache(
            partition_of=lambda doc: self.ingest.partition_for(doc)) \
            if enabled else None

    def _build_ingest_tier(self) -> SequencerShardSet:
        self.tpu_sequencers = []
        return super()._build_ingest_tier()

    def _sequencer_factory(self, ctx, checkpoints):
        from .tpu_sequencer import TpuSequencerLambda

        lam = TpuSequencerLambda(
            ctx, emit=self._emit_sequenced, nack=self._emit_nack,
            checkpoints=checkpoints, deltas=self.deltas,
            fresh_log=True, mesh=getattr(self, "mesh", None),
            # Snapshot seeding: lanes for channels whose base content
            # shipped in the attach/client summary bootstrap from the
            # historian instead of overflowing on their first op.
            storage=lambda doc_id: self.historian.read_summary(
                self.tenant_id, doc_id),
            config=self.config,
            send_system=self._send_system,
            paged_lanes=getattr(self, "paged_lanes", False))
        self.tpu_sequencers.append(lam)
        return lam

    def _sequencer_auto_commit(self) -> bool:
        # Off: offsets commit only at the lambda's flush checkpoint, so
        # a crash replays the whole unflushed window.
        return False

    def sequencer(self):
        """The live TpuSequencerLambda of partition 0 — THE sequencer on
        a single-partition core (the default every in-process test
        drives). On a sharded core, document-scoped paths must route via
        sequencer_for()/the tier instead; this accessor stays for
        whole-process introspection that treats partition 0 as
        representative."""
        return self.ingest.live(0)

    def sequencer_for(self, document_id: str):
        """The live sequencer lambda owning a document's home partition
        (== sequencer() on a single-partition core)."""
        return self.ingest.sequencer_for(document_id)

    def _wire_admission(self) -> None:
        super()._wire_admission()
        # The device pipeline's occupancy hints: staged ops count toward
        # queue depth; the in-flight ring's fill feeds the (damped)
        # utilization term. Resolved through the tier's live() so a
        # crash-restarted lambda keeps feeding the controller; one
        # source per partition so a sharded core's staged work counts
        # exactly once.
        for p in range(self.ingest.partitions):
            name = f"ring:{self.tenant_id}" if self.ingest.partitions == 1 \
                else f"ring:{self.tenant_id}:p{p}"
            self.admission.add_source(
                name,
                hints=lambda p=p: self.ingest.live(p).occupancy_hints())

    def sequence_number(self, document_id: str) -> int:
        return self.sequencer_for(document_id).document_seq(document_id)

    # -- read-path catch-up artifacts (server/readpath.py) -----------------
    def refresh_catchup(self, only_docs: Optional[set] = None) -> dict:
        """One read-tier refresh epoch: join the sequencer's batched
        channel extraction (ONE device dispatch per bucket for every
        dirty document together) with the scribe's protocol checkpoints
        and publish per-doc artifacts. A document whose scribe replica
        has not caught up to the sequencer (DEGRADE pauses scribe) skips
        this epoch — its previous artifact stays served (stale-but-
        correct: adoption + residue replay) and the publish retries next
        refresh. Serialized against the pump (artifact consistency needs
        the lanes at a flush boundary)."""
        from .readpath import build_artifact

        if self.catchup is None:
            return {"published": 0, "skipped": 0, "refreshed": 0}
        with self._pump_lock:
            # One epoch spans every partition's sequencer: documents are
            # partition-disjoint (md5 homes), so the per-partition bodies
            # merge without collision; each publish advances the OWNING
            # lambda's watermark.
            bodies: Dict[str, dict] = {}
            owner: Dict[str, Any] = {}
            for seq_lambda in self.ingest.sequencers():
                for doc_id, body in seq_lambda.catchup_snapshot(
                        only_docs).items():
                    bodies[doc_id] = body
                    owner[doc_id] = seq_lambda
            if not bodies:
                return {"published": 0, "skipped": 0, "refreshed": 0}
            # One scan of the checkpoint collection for the whole epoch
            # (a per-doc find_one would make the epoch O(dirty x docs)).
            by_doc = {row["documentId"]: row
                      for row in self.scribe_checkpoints.find(
                          lambda d: d.get("documentId") in bodies)}
            published = skipped = 0
            for doc_id, body in bodies.items():
                row = by_doc.get(doc_id)
                if row is None \
                        or int(row["sequenceNumber"]) != body["seq"]:
                    skipped += 1
                    increment("catchup.publish_skipped")
                    continue
                sha = self.historian.store(
                    self.tenant_id, doc_id).get_ref("main")
                artifact = build_artifact(
                    body, row["minimumSequenceNumber"], row["quorum"], sha)
                if self.catchup.publish(self.tenant_id, doc_id, artifact):
                    published += 1
                    owner[doc_id].catchup_mark_published(doc_id,
                                                         body["gen"])
                    for listener in list(self.catchup_listeners):
                        try:
                            listener(self.tenant_id, doc_id, artifact)
                        except Exception:  # noqa: BLE001 — observers never break the refresh
                            record_swallow("server.catchup_listener")
            return {"published": published, "skipped": skipped,
                    "refreshed": len(bodies)}

    def get_catchup(self, document_id: str) -> Optional[dict]:
        """The serving side of `summary + delta in one round trip`: the
        freshest catch-up artifact for a document, refreshing it first
        when it is absent or trails the head (cost: one single-doc
        refresh per document per epoch, amortized over every client that
        connects before the next flush dirties it)."""
        if self.catchup is None:
            return None
        with self._pump_lock:
            head = self.sequencer_for(document_id).document_seq(
                document_id)
            art_seq = self.catchup.peek_seq(self.tenant_id, document_id)
            if art_seq is None or art_seq < head:
                self.refresh_catchup(only_docs={document_id})
            return self.catchup.get(self.tenant_id, document_id,
                                    head_seq=head)

    def write_materialized_snapshots(self, ref: str = "materialized",
                                     incremental: bool = True
                                     ) -> Dict[str, str]:
        """Commit the server-materialized chunked snapshots to git storage
        under their own ref (per doc): the server-side summarization path —
        no client summarizer involved (reference Scribe writes CLIENT
        summaries, scribe/lambda.ts:162; this writes the sequencer's own
        device state). Returns {document_id: commit_sha}.

        incremental=True (the default): only channels DIRTY since the last
        write extract + upload; clean channels serialize as SummaryHandles
        into the doc's previous materialized commit, and documents with no
        dirty channels skip the write entirely — extraction compute, D2H
        traffic, and blob uploads all scale with the changed set
        (reference trackState/SummaryTracker, server-side)."""
        out: Dict[str, str] = {}
        # Per-partition sequencers hold disjoint document sets (md5
        # homes), so the per-sequencer maps merge without collision.
        for seq in self.ingest.sequencers():
            out.update(self._write_materialized_for(seq, ref, incremental))
        return out

    def _write_materialized_for(self, seq, ref: str,
                                incremental: bool) -> Dict[str, str]:
        import json as _json

        from ..protocol.summary import SummaryHandle, SummaryTree

        from .tpu_sequencer import lane_base_key

        seq.drain()
        merge_keys = set(seq.merge.where)
        lww_keys = set(seq.lww.where)
        all_keys = merge_keys | lww_keys
        # Matrix sub-lanes (axis merge lanes + cell store) version and
        # persist ATOMICALLY under their base channel key: a dirty row
        # axis must re-extract the cols/cells too, or the composed
        # snapshot would silently drop the unextracted parts.
        base_of = {k: (lane_base_key(k) or k) for k in all_keys}
        display_keys = set(base_of.values())

        prev_sha: Dict[str, Optional[str]] = {}
        for doc_id in {k[0] for k in all_keys}:
            prev_sha[doc_id] = self.historian.store(
                self.tenant_id, doc_id).get_ref(ref) if incremental \
                else None

        # Dirty = change generation advanced past what THIS ref last
        # wrote (per-ref: writes to another ref must not mark channels
        # clean here).
        gen_now: Dict[tuple, int] = dict(seq.merge.change_gen)
        gen_now.update(seq.lww.change_gen)
        # The watermark map lives ON the sequencer lambda: a crash-restart
        # replaces the lambda (fresh generation counters starting at 0),
        # and comparing new counters against a previous instance's high
        # watermarks would silently treat every post-restart edit as
        # clean.
        seen_by_ref = getattr(seq, "_materialized_gen", None)
        if seen_by_ref is None:
            seen_by_ref = seq._materialized_gen = {}
        ref_seen: Dict[tuple, int] = seen_by_ref.setdefault(ref, {})
        gen_display: Dict[tuple, int] = {}
        for k in all_keys:
            gen_display[base_of[k]] = max(gen_display.get(base_of[k], 0),
                                          gen_now.get(k, 0))
        if incremental:
            dirty = {dk for dk in display_keys
                     if gen_display.get(dk, 0) > ref_seen.get(dk, 0)}
            # Docs without a previous commit have nothing to point handles
            # at: extract them fully.
            full_docs = {d for d, sha in prev_sha.items() if sha is None}
            want_display = {dk for dk in display_keys
                            if dk in dirty or dk[0] in full_docs}
        else:
            want_display = display_keys
        want = {k for k in all_keys if base_of[k] in want_display}
        write_docs = {k[0] for k in want}

        snaps = seq.summarize_documents(only=want)

        by_doc: Dict[str, SummaryTree] = {}
        for (doc_id, store_id, channel_id), snap in snaps.items():
            root = by_doc.setdefault(doc_id, SummaryTree())
            store_node = root.entries.get(store_id)
            if store_node is None:
                store_node = root.add_tree(store_id)
            node = store_node.add_tree(channel_id)
            if snap["header"].get("kind") == "directory":
                # Composed directory channel in the EXACT summarize_core
                # layout (dds/directory.py load_core reads the nested
                # tree from the "header" blob — a different blob name
                # would load as an empty directory).
                node.add_blob("header", _json.dumps(snap["directory"],
                                                    sort_keys=True))
                continue
            node.add_blob("header", _json.dumps(snap["header"]))
            if "chunks" in snap:  # merge-tree channel: chunked body
                for i, chunk in enumerate(snap["chunks"]):
                    node.add_blob(f"chunk_{i}", _json.dumps(chunk))
            elif snap["header"].get("kind") == "matrix":
                # Composed matrix channel: axis snapshots + cell map in
                # the dds/matrix.py load_core blob layout.
                node.add_blob("rows", _json.dumps(snap["rows"]))
                node.add_blob("cols", _json.dumps(snap["cols"]))
                node.add_blob("cells", _json.dumps(snap["cells"],
                                                   sort_keys=True))
            else:  # LWW channel: entries + counter in one blob
                node.add_blob("lww", _json.dumps(
                    {"entries": snap["entries"],
                     "counter": snap["counter"]}, sort_keys=True))
        # Clean channels of written docs ride as handles into the doc's
        # previous materialized commit (same tree position).
        for (doc_id, store_id, channel_id) in display_keys - want_display:
            if doc_id not in write_docs:
                continue
            root = by_doc.setdefault(doc_id, SummaryTree())
            store_node = root.entries.get(store_id)
            if store_node is None:
                store_node = root.add_tree(store_id)
            store_node.entries[channel_id] = SummaryHandle("/")

        out: Dict[str, str] = {}
        for doc_id, tree in by_doc.items():
            gstore = self.historian.store(self.tenant_id, doc_id)
            # The sequencer's own state is authoritative (no client-proposal
            # validation cycle to wait for): advance the ref directly.
            out[doc_id] = gstore.write_summary(
                tree, ref=ref, message="server-materialized snapshot",
                base_commit=prev_sha.get(doc_id), advance_ref=True)
        # Unchanged docs keep their previous commit in the returned map.
        for doc_id, sha in prev_sha.items():
            if doc_id not in out and sha is not None:
                out[doc_id] = sha
        # Only the channels actually persisted become clean FOR THIS REF,
        # at the generation captured before extraction.
        for dk in want_display:
            ref_seen[dk] = gen_display.get(dk, 0)
        return out
