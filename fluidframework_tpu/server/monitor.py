"""Service monitor: health + metrics + the observability surface.

Capability parity with reference server/service-monitor (the ops stub) and
the IMetricClient surface (services-core/src/metricClient.ts): collects
counters from registered probes (documents resident, sequence numbers,
partition checkpoint lag, op throughput), serves them as JSON over
`/health` and `/metrics`, and keeps a rolling sample window for rate
computation.

Observability additions (docs/observability.md):

  /trace         drain the tracing flight recorder as Chrome trace-event
                 JSON (open in perfetto / chrome://tracing)
  /metrics.prom  Prometheus text exposition: process counters + the
                 per-stage latency histograms (bucket lines carry
                 trace-id exemplars) + the compile ledger's per-symbol
                 gauges
  /profile       capture a bounded jax.profiler trace window on demand
                 (?ms=<window>, capped) and return where it landed
  SLO            a declared serving-flush latency budget (default
                 p99 <= 2x p50 over the rolling window) evaluated on
                 every /health; a breach flips /health to 503 with the
                 measured numbers in the `slo` detail

/health additionally carries the compile ledger (telemetry/
compile_ledger.py — per-symbol compiles, cumulative compile ms,
warm-vs-cold calls, jit-cache occupancy) and the device telemetry
snapshot (telemetry/device_stats.py — the device.* / host.* counter
pairs whose exact reconciliation obs-smoke gates).
"""

from __future__ import annotations

import json
import threading
import time
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Callable, Dict, List, Optional, Tuple
from urllib.parse import parse_qs

from ..telemetry import counters as process_counters
from ..telemetry import device_stats
from ..telemetry import tracing
from ..telemetry import watermarks
from ..telemetry.compile_ledger import (install_jax_listener,
                                        ledger as compile_ledger)
from ..telemetry.counters import nearest_rank


class MetricClient:
    """Programmatic metric sink (reference IMetricClient.writeLatencyMetric
    shape): named counters + latency samples with simple aggregation."""

    def __init__(self, window: int = 512):
        self.counters: Dict[str, float] = {}
        self.latencies: Dict[str, List[float]] = {}
        self.window = window
        self._lock = threading.Lock()

    def increment(self, name: str, by: float = 1.0) -> None:
        with self._lock:
            self.counters[name] = self.counters.get(name, 0.0) + by

    def write_latency(self, name: str, ms: float) -> None:
        with self._lock:
            samples = self.latencies.setdefault(name, [])
            samples.append(ms)
            if len(samples) > self.window:
                del samples[:len(samples) - self.window]

    def snapshot(self) -> dict:
        with self._lock:
            out: dict = {"counters": dict(self.counters), "latencies": {}}
            for name, samples in self.latencies.items():
                if not samples:
                    continue
                ordered = sorted(samples)
                # Shared nearest-rank percentiles (telemetry/counters.py):
                # the previous inline math used the upper-median index for
                # p50 and a truncation-based index for p99, both of which
                # misquote small windows (p99 of 100 samples returned the
                # max; p50 of 2 returned the larger).
                out["latencies"][name] = {
                    "count": len(samples),
                    "p50": nearest_rank(ordered, 0.50),
                    "p99": nearest_rank(ordered, 0.99),
                    "max": ordered[-1],
                }
            return out


class SloPolicy:
    """A declared latency budget over one stage histogram's rolling
    window (VERDICT #8: a budget the surface can ENFORCE, not just
    report). Default: the serving flush must hold p99 <= ratio * p50 —
    the tail-spread budget the p99/p50=3.5x open item is graded
    against."""

    def __init__(self, stage: str = "serving.flush",
                 p99_over_p50: float = 2.0, min_samples: int = 64):
        self.stage = stage
        self.p99_over_p50 = float(p99_over_p50)
        # Below min_samples the window's p99 is dominated by compile /
        # warmup transients; the verdict reports "not evaluated" (ok).
        self.min_samples = int(min_samples)

    @property
    def budget(self) -> str:
        """Human-readable budget — the single source for every surface
        that quotes it (health, /metrics.prom, bench records)."""
        return f"p99 <= {self.p99_over_p50:g} * p50"

    def check(self, p50: float, p99: float) -> bool:
        """Grade an externally measured (p50, p99) pair against this
        budget (bench records use this so they can never diverge from
        the /health verdict)."""
        return p50 <= 0 or p99 <= self.p99_over_p50 * p50

    def evaluate(self) -> dict:
        window = process_counters.latency_window(self.stage)
        ordered = sorted(window)
        out = {
            "stage": self.stage,
            "budget": self.budget,
            "samples": len(ordered),
            "evaluated": len(ordered) >= self.min_samples,
            "ok": True,
        }
        if not ordered:
            return out
        p50 = nearest_rank(ordered, 0.50)
        p99 = nearest_rank(ordered, 0.99)
        out["p50Ms"] = round(p50, 3)
        out["p99Ms"] = round(p99, 3)
        out["ratio"] = round(p99 / p50, 3) if p50 > 0 else 0.0
        if out["evaluated"] and p50 > 0:
            out["ok"] = p99 <= self.p99_over_p50 * p50
        return out


class ServiceMonitor:
    """Aggregates probes (name -> callable returning a dict) and serves
    them. Probes run at request time, so readings are live."""

    def __init__(self, host: str = "127.0.0.1", port: int = 0,
                 metrics: Optional[MetricClient] = None,
                 slo: Optional[SloPolicy] = None,
                 enforce_slo: bool = True,
                 burn: Optional[object] = None):
        self.metrics = metrics or MetricClient()
        self.slo = slo or SloPolicy()
        # Optional multi-window burn-rate engine (telemetry/slo.py
        # BurnRateEngine): its verdict rides /health as `burnRate`,
        # report-only at the worker level — fleet-level enforcement
        # belongs to the observatory, which sees every worker.
        self.burn = burn
        # enforce_slo=False keeps the verdict in /health without letting
        # a breach flip the status code (report-only rollout mode).
        self.enforce_slo = enforce_slo
        self.probes: Dict[str, Callable[[], dict]] = {}
        # Guards the probe registry + the admission handle: watch_*()
        # registration happens on the operator thread while the HTTP
        # request threads iterate probes for /health — an unguarded dict
        # grows mid-iteration and the request thread dies with
        # RuntimeError (fluidlint SHARED_STATE_NO_LOCK).
        self._probes_lock = threading.Lock()
        self._admission = None
        self.started_at = time.time()
        service = self

        class Handler(BaseHTTPRequestHandler):
            protocol_version = "HTTP/1.1"

            def log_message(self, fmt, *args):  # quiet
                pass

            def do_GET(self):
                service._route(self)

        self._httpd = ThreadingHTTPServer((host, port), Handler)
        self._httpd.daemon_threads = True
        self.host, self.port = self._httpd.server_address
        self._thread: Optional[threading.Thread] = None
        # Ground-truth backend-compile time (compile.backend_ms) via
        # jax.monitoring, where the running jax exposes the hook —
        # best-effort and idempotent.
        install_jax_listener()

    def add_probe(self, name: str, probe: Callable[[], dict]) -> None:
        with self._probes_lock:
            self.probes[name] = probe

    def watch_local_server(self, name: str, server) -> None:
        """Convenience probe over a LocalServer pipeline core."""

        def probe() -> dict:
            docs = sorted(getattr(server, "_connections", {}))
            return {"documents": docs, "connections":
                    {d: len(c) for d, c in
                     getattr(server, "_connections", {}).items()}}

        self.add_probe(name, probe)

    def watch_historian(self, name: str, historian) -> None:
        """Probe over a historian cache tier (server/historian.py
        HistorianTier or HistorianService): hit/miss/bytes/evictions
        counters plus hit rate, live at request time."""
        self.add_probe(name, historian.stats)

    def watch_admission(self, name: str, controller) -> None:
        """Surface an AdmissionController (server/admission.py): its
        full status block — ladder state, pressure, queue depth vs
        limit, per-tenant credits — rides every /health payload, and
        /metrics.prom gains a live fluid_admission_level gauge. The
        admission.* process counters and the admission.retry_wait_ms
        histogram (bucket lines carry trace-id exemplars) already flow
        through the standard exposition. NOT registered as a probe:
        health() renders the block from `_admission` directly, and a
        probe would compute the same status (controller lock + tenant
        serialization) a second time only to discard it — status() is
        pure introspection with no failure mode worth a checks entry."""
        del name  # kept for call-site symmetry with the other watchers
        with self._probes_lock:
            self._admission = controller

    def watch_summaries(self, name: str, merge_store) -> None:
        """Probe over a MergeLaneStore's incremental-summarization state:
        dirty lane count (channels past their summarize epoch), cached
        blob count, and the summarize.* process counters rolled into a
        blob-cache hit rate — the health-report view of the dirty-epoch
        extraction path."""

        def probe() -> dict:
            snap = process_counters.snapshot()
            hits = snap.get("summarize.blob_cache.hits", 0.0)
            misses = snap.get("summarize.blob_cache.misses", 0.0)
            return {
                "dirtyLanes": len(merge_store.dirty_keys()),
                "cachedBlobs": merge_store.cached_blob_count(),
                "blobCacheHitRate": hits / max(hits + misses, 1.0),
                "extractMs": snap.get("summarize.extract_ms", 0.0),
                "dirtyDocs": snap.get("summarize.dirty_docs", 0.0),
                "bytesD2H": snap.get("summarize.bytes_d2h", 0.0),
                "wireRefetches": snap.get("summarize.wire_refetch", 0.0),
            }

        self.add_probe(name, probe)

    def watch_readpath(self, name: str, server) -> None:
        """Probe over the read tier (docs/read_path.md): the catch-up
        artifact cache (hit/miss/stale rates, artifact count/bytes) and
        the sharded broadcaster fan-out (per-shard queue depths — also
        refreshed into the broadcaster.queue_depth.shard<i> gauges every
        probe, so /metrics.prom carries them — shed and delivered
        counts). Works against a LocalServer (catchup + broadcasters
        attributes) or anything duck-shaped like one; either half may be
        absent (scalar pipeline, inline fan-out)."""

        def probe() -> dict:
            out: dict = {}
            cache = getattr(server, "catchup", None)
            if cache is not None:
                out["catchup"] = cache.stats()
            shards = []
            shed = delivered = 0
            for lam in getattr(server, "broadcasters", []):
                st = lam.stats()
                shards.extend(st["queueDepths"])
                shed += st["shed"]
                delivered += st["delivered"]
            out["broadcaster"] = {
                "shards": len(shards),
                "queueDepths": shards,
                "queueDepth": sum(shards),
                "shed": shed,
                "delivered": delivered,
            }
            snap = process_counters.snapshot()
            out["deltaHits"] = snap.get("catchup.delta_hit", 0.0)
            out["deltaMisses"] = snap.get("catchup.delta_miss", 0.0)
            out["deltaStale"] = snap.get("catchup.delta_stale", 0.0)
            out["refreshDispatches"] = snap.get(
                "catchup.refresh_dispatches", 0.0)
            out["clientAdoptions"] = snap.get("catchup.client.adopted", 0.0)
            return out

        self.add_probe(name, probe)

    def watch_partitions(self, name: str, server) -> None:
        """Probe over the sharded ingest tier (server/sharding.py,
        docs/ingest_sharding.md): per-partition committed offset / end
        offset / record lag, the owning sequencer's staged work, and the
        pump accounting (records drained, busy seconds, restarts). Each
        probe also refreshes per-partition lag/depth gauges — through
        the PR 12 `bounded()` cardinality guard — so /metrics.prom
        carries `fluid_ingest_partition_lag_p<i>` without per-partition
        label cardinality ever growing unbounded."""

        def probe() -> dict:
            tier = getattr(server, "ingest", None)
            if tier is None:
                return {"partitions": []}
            # Pull-model watermark refresh (telemetry/watermarks.py):
            # raw_end/raw_ingested/ticketed advance at probe time so the
            # fluid_lag_* gauges track the live tier with zero op cost.
            refresh = getattr(tier, "refresh_watermarks", None)
            if refresh is not None:
                refresh()
            rows = tier.partition_stats()
            for row in rows:
                p = row["partition"]
                process_counters.gauge(
                    process_counters.bounded("ingest.partition_lag",
                                             f"p{p}"), row["lag"])
                process_counters.gauge(
                    process_counters.bounded("ingest.partition_committed",
                                             f"p{p}"),
                    row["committedOffset"])
                if "stagedOps" in row:
                    process_counters.gauge(
                        process_counters.bounded("ingest.partition_staged",
                                                 f"p{p}"),
                        row["stagedOps"])
            total_lag = sum(r["lag"] for r in rows)
            hottest = max(rows, key=lambda r: r["lag"])["partition"] \
                if rows else None
            return {"partitions": rows, "totalLag": total_lag,
                    "hottest": hottest,
                    "router": {"scheme": "md5",
                               "partitions": tier.partitions}}

        self.add_probe(name, probe)

    def watch_durable(self, name: str, log) -> None:
        """Probe over a durable broker engine (server/durable.py
        DurableMessageLog): group-commit backlog, segment count, and
        torn-tail truncation. The group-commit COUNTERS
        (fluid_durable_fsyncs_total — per-topic split through the PR 12
        `bounded()` cardinality guard —, fluid_durable_batch_bytes,
        fluid_stage_latency_ms{stage="durable.group_commit"}) flow
        through telemetry/counters.py on the op path; this probe adds
        the gauges a scrape can't derive from counters."""

        def probe() -> dict:
            stats_fn = getattr(log, "durable_stats", None)
            if stats_fn is None:
                return {"available": False}
            stats = stats_fn()
            process_counters.gauge("durable.pending_appends",
                                   stats.get("pendingAppends", 0))
            process_counters.gauge("durable.torn_bytes_truncated",
                                   stats.get("tornBytesTruncated", 0))
            process_counters.gauge("durable.segments",
                                   stats.get("segments", 0))
            process_counters.gauge("durable.partitions",
                                   stats.get("partitions", 0))
            stats["available"] = True
            return stats

        self.add_probe(name, probe)

    def watch_capacity(self, name: str, source) -> None:
        """Probe over the last fleet-scale capacity soak (capacity/,
        docs/capacity.md): loads the stamped record — `source` is a
        BENCH_E2E_LAST.json path or a callable returning the record
        dict — and surfaces the graded capacity figure plus the
        binding-bottleneck attribution in /health. Each probe also
        refreshes per-tier pressure gauges through the PR 12 `bounded()`
        cardinality guard, so /metrics.prom carries
        `fluid_capacity_tier_pressure_<tier>` with the tier set fixed by
        the soak, never growing per-label. A host that has never run the
        soak (missing/unreadable record) reports {"available": False}
        without failing health."""

        def probe() -> dict:
            rec = None
            if callable(source):
                rec = source()
            else:
                try:
                    with open(source, "r", encoding="utf-8") as fh:
                        rec = json.load(fh)
                except (OSError, ValueError):
                    process_counters.record_swallow(
                        "monitor.capacity_record")
            if not isinstance(rec, dict):
                return {"available": False}
            cap = rec.get("capacity") or {}
            soak = rec.get("final_run") or {}
            # The at-fail pressure ranking is what named the bottleneck;
            # a bare SoakResult dict (no grade wrapper) falls back to
            # its own tier pressures.
            pressures = (dict(cap.get("pressure_ranking") or [])
                         or dict(soak.get("tier_pressures")
                                 or rec.get("tier_pressures") or {}))
            out = {
                "available": True,
                "ok": rec.get("ok"),
                "backend": rec.get("backend"),
                "capacityMult": (rec.get("grade") or {}).get(
                    "capacity_mult"),
                "offeredOpsPerSec": cap.get("offered_ops_per_sec"),
                "sustainedOpsPerSec": (cap.get("sustained_ops_per_sec")
                                       or soak.get("sustained_ops_per_sec")
                                       or rec.get("sustained_ops_per_sec")),
                "readersPerSec": cap.get("readers_per_sec"),
                "bottleneck": (cap.get("bottleneck")
                               or (max(pressures, key=pressures.get)
                                   if pressures else None)),
                "tierPressures": {t: round(float(v), 4)
                                  for t, v in pressures.items()},
            }
            if out["sustainedOpsPerSec"] is not None:
                process_counters.gauge("capacity.sustained_ops_per_sec",
                                       float(out["sustainedOpsPerSec"]))
            for tier, value in pressures.items():
                process_counters.gauge(
                    process_counters.bounded("capacity.tier_pressure",
                                             tier), float(value))
            return out

        self.add_probe(name, probe)

    # -- lifecycle ----------------------------------------------------------
    def start(self) -> "ServiceMonitor":
        self._thread = threading.Thread(target=self._httpd.serve_forever,
                                        name="monitor", daemon=True)
        self._thread.start()
        return self

    def stop(self) -> None:
        self._httpd.shutdown()
        self._httpd.server_close()
        if self._thread:
            self._thread.join(timeout=5)

    @property
    def url(self) -> str:
        return f"http://{self.host}:{self.port}"

    # -- views --------------------------------------------------------------
    def health(self) -> dict:
        checks: Dict[str, Tuple[bool, str]] = {}
        # Snapshot under the lock, run probes outside it: a probe may
        # be arbitrarily slow (it reads live server state) and must not
        # serialize concurrent /health requests or registration.
        with self._probes_lock:
            probes = list(self.probes.items())
            admission_ctl = self._admission
        for name, probe in probes:
            try:
                probe()
                checks[name] = (True, "ok")
            except Exception as exc:  # noqa: BLE001 — probe crash = unhealthy
                checks[name] = (False, repr(exc))
        slo = self.slo.evaluate()
        slo_ok = slo["ok"] or not self.enforce_slo
        admission = (admission_ctl.status()
                     if admission_ctl is not None else None)
        # Freshen the fluid_lag_* gauges so the counters snapshot below
        # (and any scrape racing it) reads current watermark deltas.
        watermarks.export_gauges()
        return {"ok": all(ok for ok, _ in checks.values()) and slo_ok,
                # Overload-control state (server/admission.py): a DEGRADE
                # reading here with /health still 200 is deliberate — the
                # process is protecting itself, not failing; orchestrators
                # must not restart it for shedding load.
                "admission": admission,
                "uptimeS": time.time() - self.started_at,
                # Process-wide counters ride on every health report: the
                # swallowed.* rates (fluidlint CC rules' runtime side) and
                # kernel.retrace_count (the RETRACE_HAZARD cross-check).
                "counters": process_counters.snapshot(),
                # The compile/dispatch observatory: per-symbol compiles,
                # cumulative compile ms, warm/cold split, cache-key
                # occupancy (telemetry/compile_ledger.py).
                "compileLedger": compile_ledger.snapshot(),
                # Device telemetry planes + their host mirrors; a non-
                # None `deviceReconcile` names the slots that disagree.
                "deviceStats": device_stats.snapshot(),
                "deviceReconcile": device_stats.reconcile(),
                # The declared-budget verdict (503-with-detail on breach).
                "slo": slo,
                # Per-tier watermark/lag pipeline (telemetry/
                # watermarks.py): raw tier marks + per-edge consumer lag
                # — the observatory's /fleet/lag merges these per worker.
                "watermarks": watermarks.snapshot(),
                # Multi-window burn-rate verdict when an engine is wired
                # (report-only here; the observatory enforces fleet-wide).
                "burnRate": (self.burn.evaluate()
                             if self.burn is not None else None),
                "stageLatencies": process_counters.latency_snapshot(),
                "checks": {n: {"ok": ok, "detail": d}
                           for n, (ok, d) in checks.items()}}

    def report(self) -> dict:
        out = {"metrics": self.metrics.snapshot(),
               "counters": process_counters.snapshot(),
               "stageLatencies": process_counters.latency_snapshot(),
               "probes": {}}
        with self._probes_lock:
            probes = list(self.probes.items())
        for name, probe in probes:
            try:
                out["probes"][name] = probe()
            except Exception as exc:  # noqa: BLE001
                out["probes"][name] = {"error": repr(exc)}
        return out

    # -- Prometheus exposition ----------------------------------------------
    @staticmethod
    def _prom_name(name: str) -> str:
        out = []
        for ch in name:
            out.append(ch if ch.isalnum() or ch == "_" else "_")
        sanitized = "".join(out)
        if sanitized and sanitized[0].isdigit():
            sanitized = "_" + sanitized
        return "fluid_" + sanitized

    @staticmethod
    def _prom_label(value) -> str:
        """Label-VALUE escaping per the exposition format: backslash,
        double-quote, and newline must be escaped inside the quotes —
        a stage or symbol name containing any of them otherwise
        produces a line no conformant parser accepts."""
        return (str(value).replace("\\", "\\\\").replace('"', '\\"')
                .replace("\n", "\\n"))

    def prometheus(self) -> str:
        """Prometheus/OpenMetrics-style text exposition: every process
        counter as a gauge sample with HELP/TYPE metadata, every stage
        latency histogram with cumulative bucket lines (le in
        milliseconds) — bucket lines carry the last trace id observed in
        that bucket as an exemplar, so a latency spike on a dashboard
        links straight to its flight-recorder trace. Label values are
        escaped per the exposition grammar."""
        esc = self._prom_label
        # Freshen the fluid_lag_* surface so a scrape reads current
        # watermark deltas rather than the last /health's.
        watermarks.export_gauges()
        lines: List[str] = []
        for name, value in sorted(process_counters.snapshot().items()):
            metric = self._prom_name(name)
            lines.append(f"# HELP {metric} process counter {esc(name)}")
            lines.append(f"# TYPE {metric} gauge")
            lines.append(f"{metric} {value:g}")
        for name, value in sorted(self.metrics.snapshot()
                                  ["counters"].items()):
            metric = self._prom_name("metric." + name)
            lines.append(f"# HELP {metric} metric client counter "
                         f"{esc(name)}")
            lines.append(f"# TYPE {metric} gauge")
            lines.append(f"{metric} {value:g}")
        hists = process_counters.histogram_export()
        if hists:
            lines.append("# HELP fluid_stage_latency_ms per-stage "
                         "latency histogram (milliseconds)")
            lines.append("# TYPE fluid_stage_latency_ms histogram")
        for name in sorted(hists):
            h = hists[name]
            stage = esc(name)
            for le, cum, exemplar in h["buckets"]:
                le_s = "+Inf" if le == float("inf") else f"{le:g}"
                line = (f'fluid_stage_latency_ms_bucket'
                        f'{{stage="{stage}",le="{le_s}"}} {cum}')
                if exemplar is not None:
                    trace_id, value = exemplar
                    line += (f' # {{trace_id="{esc(trace_id)}"}} '
                             f'{value:g}')
                lines.append(line)
            lines.append(f'fluid_stage_latency_ms_sum{{stage="{stage}"}} '
                         f'{h["sum"]:g}')
            lines.append(f'fluid_stage_latency_ms_count{{stage="{stage}"}} '
                         f'{h["count"]}')
        # Compile/dispatch observatory: per-symbol gauges. Symbol
        # cardinality is the fixed probe/watch set (no per-tenant/doc
        # labels), so this block never needs the cardinality guard.
        led = compile_ledger.snapshot()
        if led["symbols"]:
            for metric in ("compiles", "compile_ms", "cache_size",
                           "retraces"):
                lines.append(f"# HELP fluid_compile_{metric} compile "
                             f"ledger per-symbol {metric}")
                lines.append(f"# TYPE fluid_compile_{metric} gauge")
                src = {"compiles": "compiles", "compile_ms": "compileMs",
                       "cache_size": "cacheSize",
                       "retraces": "retraces"}[metric]
                for name, sym in led["symbols"].items():
                    lines.append(
                        f'fluid_compile_{metric}{{symbol="{esc(name)}"}} '
                        f'{sym[src]:g}')
            lines.append("# HELP fluid_compile_total_ms cumulative "
                         "process compile milliseconds")
            lines.append("# TYPE fluid_compile_total_ms gauge")
            lines.append(
                f'fluid_compile_total_ms {led["totals"]["compileMs"]:g}')
        slo = self.slo.evaluate()
        lines.append("# HELP fluid_slo_ok declared latency budget "
                     "verdict (1 ok / 0 breach)")
        lines.append("# TYPE fluid_slo_ok gauge")
        lines.append(f'fluid_slo_ok{{stage="{esc(slo["stage"])}"}} '
                     f'{1 if slo["ok"] else 0}')
        if self.burn is not None:
            burn = self.burn.evaluate()
            lines.append("# HELP fluid_slo_burn_breach multi-window "
                         "burn-rate breach per objective (1 breach)")
            lines.append("# TYPE fluid_slo_burn_breach gauge")
            for name, obj in sorted(burn["objectives"].items()):
                lines.append(
                    f'fluid_slo_burn_breach{{objective="{esc(name)}"}} '
                    f'{1 if obj["breach"] else 0}')
        with self._probes_lock:
            admission_ctl = self._admission
        if admission_ctl is not None:
            st = admission_ctl.status()
            lines.append("# HELP fluid_admission_level overload ladder "
                         "level (0 accept .. 3 shed)")
            lines.append("# TYPE fluid_admission_level gauge")
            lines.append(
                f'fluid_admission_level{{state="{esc(st["state"])}"}} '
                f'{st["level"]}')
        # OpenMetrics terminator — exemplars are OpenMetrics syntax, so
        # the exposition declares (and terminates as) OpenMetrics rather
        # than the 0.0.4 text format, whose parsers reject the '# {...}'
        # tail after a sample value.
        lines.append("# EOF")
        return "\n".join(lines) + "\n"

    # -- on-demand profiler capture -----------------------------------------
    # One capture at a time; the window is bounded so a stray request can
    # never wedge a request thread for long or leave the profiler running.
    _PROFILE_MAX_MS = 5000.0
    _profile_lock = threading.Lock()

    def profile(self, ms: float = 200.0) -> dict:
        """Capture a bounded jax.profiler trace window into a fresh
        directory and return where it landed (open with TensorBoard or
        perfetto). Returns {"ok": False, ...} — never raises — when jax
        or its profiler is unavailable, or a capture is already
        running."""
        import os
        import tempfile

        ms = max(10.0, min(float(ms), self._PROFILE_MAX_MS))
        if not self._profile_lock.acquire(blocking=False):
            return {"ok": False, "error": "profile capture already "
                                          "in progress"}
        try:
            import jax

            out_dir = tempfile.mkdtemp(prefix="fluid_profile_")
            jax.profiler.start_trace(out_dir)
            try:
                time.sleep(ms / 1000.0)
            finally:
                jax.profiler.stop_trace()
            files = []
            for root, _dirs, names in os.walk(out_dir):
                for name in names:
                    files.append(os.path.relpath(
                        os.path.join(root, name), out_dir))
            return {"ok": True, "dir": out_dir, "durationMs": ms,
                    "files": sorted(files)}
        except Exception as exc:  # noqa: BLE001 — surface, never crash the monitor
            return {"ok": False, "error": repr(exc)}
        finally:
            self._profile_lock.release()

    def _route(self, handler) -> None:
        path, _, query = handler.path.partition("?")
        if path == "/healthz":  # k8s-style alias
            path = "/health"
        content_type = "application/json"
        if path == "/health":
            payload, status = self.health(), 200
            if not payload["ok"]:
                status = 503
            body = json.dumps(payload).encode()
        elif path == "/metrics":
            body = json.dumps(self.report()).encode()
            status = 200
        elif path == "/metrics.prom":
            body = self.prometheus().encode()
            status = 200
            content_type = ("application/openmetrics-text; "
                            "version=1.0.0; charset=utf-8")
        elif path == "/trace":
            # Drain: each capture window starts fresh (the flight
            # recorder bounds memory, not retention policy).
            body = json.dumps(tracing.chrome_trace(
                tracing.recorder.drain())).encode()
            status = 200
        elif path == "/profile":
            params = parse_qs(query)
            try:
                ms = float(params.get("ms", ["200"])[0])
            except ValueError:
                ms = 200.0
            payload = self.profile(ms)
            body = json.dumps(payload).encode()
            status = 200 if payload["ok"] else 503
        else:
            body = json.dumps({"error": f"no route {path}"}).encode()
            status = 404
        handler.send_response(status)
        handler.send_header("Content-Type", content_type)
        handler.send_header("Content-Length", str(len(body)))
        handler.end_headers()
        handler.wfile.write(body)
