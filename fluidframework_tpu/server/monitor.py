"""Service monitor: health + metrics over the ordering service.

Capability parity with reference server/service-monitor (the ops stub) and
the IMetricClient surface (services-core/src/metricClient.ts): collects
counters from registered probes (documents resident, sequence numbers,
partition checkpoint lag, op throughput), serves them as JSON over
`/health` and `/metrics`, and keeps a rolling sample window for rate
computation.
"""

from __future__ import annotations

import json
import threading
import time
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Callable, Dict, List, Optional, Tuple

from ..telemetry import counters as process_counters


class MetricClient:
    """Programmatic metric sink (reference IMetricClient.writeLatencyMetric
    shape): named counters + latency samples with simple aggregation."""

    def __init__(self, window: int = 512):
        self.counters: Dict[str, float] = {}
        self.latencies: Dict[str, List[float]] = {}
        self.window = window
        self._lock = threading.Lock()

    def increment(self, name: str, by: float = 1.0) -> None:
        with self._lock:
            self.counters[name] = self.counters.get(name, 0.0) + by

    def write_latency(self, name: str, ms: float) -> None:
        with self._lock:
            samples = self.latencies.setdefault(name, [])
            samples.append(ms)
            if len(samples) > self.window:
                del samples[:len(samples) - self.window]

    def snapshot(self) -> dict:
        with self._lock:
            out: dict = {"counters": dict(self.counters), "latencies": {}}
            for name, samples in self.latencies.items():
                if not samples:
                    continue
                ordered = sorted(samples)
                out["latencies"][name] = {
                    "count": len(samples),
                    "p50": ordered[len(ordered) // 2],
                    "p99": ordered[min(len(ordered) - 1,
                                       int(len(ordered) * 0.99))],
                    "max": ordered[-1],
                }
            return out


class ServiceMonitor:
    """Aggregates probes (name -> callable returning a dict) and serves
    them. Probes run at request time, so readings are live."""

    def __init__(self, host: str = "127.0.0.1", port: int = 0,
                 metrics: Optional[MetricClient] = None):
        self.metrics = metrics or MetricClient()
        self.probes: Dict[str, Callable[[], dict]] = {}
        self.started_at = time.time()
        service = self

        class Handler(BaseHTTPRequestHandler):
            protocol_version = "HTTP/1.1"

            def log_message(self, fmt, *args):  # quiet
                pass

            def do_GET(self):
                service._route(self)

        self._httpd = ThreadingHTTPServer((host, port), Handler)
        self._httpd.daemon_threads = True
        self.host, self.port = self._httpd.server_address
        self._thread: Optional[threading.Thread] = None

    def add_probe(self, name: str, probe: Callable[[], dict]) -> None:
        self.probes[name] = probe

    def watch_local_server(self, name: str, server) -> None:
        """Convenience probe over a LocalServer pipeline core."""

        def probe() -> dict:
            docs = sorted(getattr(server, "_connections", {}))
            return {"documents": docs, "connections":
                    {d: len(c) for d, c in
                     getattr(server, "_connections", {}).items()}}

        self.add_probe(name, probe)

    def watch_historian(self, name: str, historian) -> None:
        """Probe over a historian cache tier (server/historian.py
        HistorianTier or HistorianService): hit/miss/bytes/evictions
        counters plus hit rate, live at request time."""
        self.add_probe(name, historian.stats)

    def watch_summaries(self, name: str, merge_store) -> None:
        """Probe over a MergeLaneStore's incremental-summarization state:
        dirty lane count (channels past their summarize epoch), cached
        blob count, and the summarize.* process counters rolled into a
        blob-cache hit rate — the health-report view of the dirty-epoch
        extraction path."""

        def probe() -> dict:
            snap = process_counters.snapshot()
            hits = snap.get("summarize.blob_cache.hits", 0.0)
            misses = snap.get("summarize.blob_cache.misses", 0.0)
            return {
                "dirtyLanes": len(merge_store.dirty_keys()),
                "cachedBlobs": merge_store.cached_blob_count(),
                "blobCacheHitRate": hits / max(hits + misses, 1.0),
                "extractMs": snap.get("summarize.extract_ms", 0.0),
                "dirtyDocs": snap.get("summarize.dirty_docs", 0.0),
                "bytesD2H": snap.get("summarize.bytes_d2h", 0.0),
                "wireRefetches": snap.get("summarize.wire_refetch", 0.0),
            }

        self.add_probe(name, probe)

    # -- lifecycle ----------------------------------------------------------
    def start(self) -> "ServiceMonitor":
        self._thread = threading.Thread(target=self._httpd.serve_forever,
                                        name="monitor", daemon=True)
        self._thread.start()
        return self

    def stop(self) -> None:
        self._httpd.shutdown()
        self._httpd.server_close()
        if self._thread:
            self._thread.join(timeout=5)

    @property
    def url(self) -> str:
        return f"http://{self.host}:{self.port}"

    # -- views --------------------------------------------------------------
    def health(self) -> dict:
        checks: Dict[str, Tuple[bool, str]] = {}
        for name, probe in self.probes.items():
            try:
                probe()
                checks[name] = (True, "ok")
            except Exception as exc:  # noqa: BLE001 — probe crash = unhealthy
                checks[name] = (False, repr(exc))
        return {"ok": all(ok for ok, _ in checks.values()),
                "uptimeS": time.time() - self.started_at,
                # Process-wide counters ride on every health report: the
                # swallowed.* rates (fluidlint CC rules' runtime side) and
                # kernel.retrace_count (the RETRACE_HAZARD cross-check).
                "counters": process_counters.snapshot(),
                "checks": {n: {"ok": ok, "detail": d}
                           for n, (ok, d) in checks.items()}}

    def report(self) -> dict:
        out = {"metrics": self.metrics.snapshot(),
               "counters": process_counters.snapshot(), "probes": {}}
        for name, probe in self.probes.items():
            try:
                out["probes"][name] = probe()
            except Exception as exc:  # noqa: BLE001
                out["probes"][name] = {"error": repr(exc)}
        return out

    def _route(self, handler) -> None:
        path = handler.path.partition("?")[0]
        if path == "/healthz":  # k8s-style alias
            path = "/health"
        if path == "/health":
            payload, status = self.health(), 200
            if not payload["ok"]:
                status = 503
        elif path == "/metrics":
            payload, status = self.report(), 200
        else:
            payload, status = {"error": f"no route {path}"}, 404
        body = json.dumps(payload).encode()
        handler.send_response(status)
        handler.send_header("Content-Type", "application/json")
        handler.send_header("Content-Length", str(len(body)))
        handler.end_headers()
        handler.wfile.write(body)
