"""Remote ordered-log service: the networked broker deployment shape.

Capability parity with the reference's Kafka deployment topology
(docker-compose.yml: every lambda service — deli, scriptorium, scribe,
broadcaster — is a separate process connecting to the broker over the
network through librdkafka): `LogServiceServer` exposes a MessageLog
(pure-Python or the native C++ engine) over gRPC raw-bytes methods, and
`RemoteMessageLog` is a drop-in consumer/producer surface — the same
`topic().partitions[].read()` / `send` / `commit` contract the partition
host and lambdas already use in-process — so a `LambdaRunner` can run in a
different process (or host, over DCN) from the broker.

Payloads are pickled across the wire (a trusted internal link, exactly the
role rdkafka's serialized frames play; the front door speaking to untrusted
clients is alfred's REST/WebSocket + JWT, not this)."""

from __future__ import annotations

import pickle
import threading
from concurrent import futures
from typing import Dict, List, Optional

from ..telemetry.counters import record_swallow
from .log import MessageLog, QueuedMessage

SERVICE = "fluidframework.LogService"


class LogServiceServer:
    def __init__(self, log: Optional[MessageLog] = None, port: int = 0,
                 max_workers: int = 8):
        import grpc
        self.log = log if log is not None else MessageLog()
        service = self

        def method(fn):
            return grpc.unary_unary_rpc_method_handler(fn)

        handlers = {
            f"/{SERVICE}/Send": method(service._send),
            f"/{SERVICE}/SendTo": method(service._send_to),
            f"/{SERVICE}/SendToMany": method(service._send_to_many),
            f"/{SERVICE}/Read": method(service._read),
            f"/{SERVICE}/Commit": method(service._commit),
            f"/{SERVICE}/CommitMany": method(service._commit_many),
            f"/{SERVICE}/Committed": method(service._committed),
            f"/{SERVICE}/Topic": method(service._topic),
        }

        class Handler(grpc.GenericRpcHandler):
            def service(self, details):
                return handlers.get(details.method)

        self._server = grpc.server(
            futures.ThreadPoolExecutor(max_workers=max_workers))
        self._server.add_generic_rpc_handlers((Handler(),))
        self.port = self._server.add_insecure_port(f"127.0.0.1:{port}")

    def start(self) -> "LogServiceServer":
        self._server.start()
        return self

    def stop(self) -> None:
        self._server.stop(grace=1)

    @property
    def address(self) -> str:
        return f"127.0.0.1:{self.port}"

    # -- methods (request/response are pickled tuples) ----------------------
    def _send(self, request: bytes, context) -> bytes:
        topic, key, value = pickle.loads(request)
        msg = self.log.send(topic, key, value)
        return pickle.dumps(msg.offset)

    def _send_to(self, request: bytes, context) -> bytes:
        topic, partition, key, value = pickle.loads(request)
        msg = self.log.send_to(topic, partition, key, value)
        return pickle.dumps(msg.offset)

    def _send_to_many(self, request: bytes, context) -> bytes:
        # Batched explicit-partition produce: one RPC for the whole list,
        # and — when the broker engine is the durable log — one group
        # commit (one fsync) covering every record in it.
        topic, partition, items = pickle.loads(request)
        msgs = self.log.send_to_many(topic, partition, items)
        return pickle.dumps([m.offset for m in msgs])

    def _read(self, request: bytes, context) -> bytes:
        topic, partition, offset, limit = pickle.loads(request)
        # read_from (not part.read): on a durable broker opened with
        # replay="committed" it serves offsets below the resident window
        # from the segment files via the sparse index.
        reader = getattr(self.log, "read_from", None)
        if reader is not None:
            msgs = reader(topic, partition, offset, limit)
        else:
            msgs = self.log.topic(topic).partitions[partition].read(
                offset, limit)
        return pickle.dumps([(m.offset, m.key, m.value) for m in msgs])

    def _commit(self, request: bytes, context) -> bytes:
        group, topic, partition, offset = pickle.loads(request)
        self.log.commit(group, topic, partition, offset)
        return pickle.dumps(True)

    def _commit_many(self, request: bytes, context) -> bytes:
        group, topic, offsets = pickle.loads(request)
        self.log.commit_many(group, topic, offsets)
        return pickle.dumps(True)

    def _committed(self, request: bytes, context) -> bytes:
        group, topic, partition = pickle.loads(request)
        return pickle.dumps(self.log.committed(group, topic, partition))

    def _topic(self, request: bytes, context) -> bytes:
        name, partitions = pickle.loads(request)
        topic = self.log.topic(name, partitions)
        return pickle.dumps(len(topic.partitions))


class _RemotePartition:
    def __init__(self, client: "RemoteMessageLog", topic: str, index: int):
        self._client = client
        self.topic = topic
        self.index = index

    def read(self, offset: int, limit: int = 1000) -> List[QueuedMessage]:
        rows = self._client._call("Read",
                                  (self.topic, self.index, offset, limit))
        return [QueuedMessage(self.topic, self.index, off, key, value)
                for off, key, value in rows]


class _RemoteTopic:
    def __init__(self, client: "RemoteMessageLog", name: str,
                 n_partitions: int):
        self.name = name
        self.partitions = [_RemotePartition(client, name, i)
                           for i in range(n_partitions)]


class RemoteMessageLog:
    """MessageLog-compatible client over a LogServiceServer.

    Broker outages (restart, network blip) are handled HERE with a
    bounded-backoff deterministic reconnect: on UNAVAILABLE the client
    closes and rebuilds its channel (discarding gRPC's internal
    reconnect backoff state, which can sit in a multi-second wait after
    repeated failures) and retries through a RetryPolicy. Workers no
    longer depend on the container's gRPC channel-backoff timing to
    notice a restarted broker — the class of flake behind the
    tests/test_deployment.py broker-restart test. Retried sends are
    at-least-once (the pipeline dedups by offset/clientSequenceNumber
    downstream, exactly as for a crash-replayed partition)."""

    def __init__(self, address: str, default_partitions: int = 1,
                 reconnect_policy=None):
        import grpc
        self._grpc = grpc
        self._address = address
        self._channel = grpc.insecure_channel(address)
        self.default_partitions = default_partitions
        self._methods = {}
        self._topics = {}
        self._lock = threading.Lock()
        if reconnect_policy is None:
            from ..core.retry import RetryPolicy
            reconnect_policy = RetryPolicy(max_attempts=8,
                                           base_delay_s=0.05,
                                           max_delay_s=2.0)
        self._reconnect = reconnect_policy

    def _rebuild_channel(self) -> None:
        with self._lock:
            try:
                self._channel.close()
            except Exception:  # noqa: BLE001 — dead channel teardown
                record_swallow("log_service.channel_close")
            self._channel = self._grpc.insecure_channel(self._address)
            self._methods.clear()

    def _call(self, name: str, payload):
        from ..core.retry import NonRetryableError

        def once():
            with self._lock:
                stub = self._methods.get(name)
                if stub is None:
                    stub = self._channel.unary_unary(
                        f"/{SERVICE}/{name}",
                        request_serializer=lambda b: b,
                        response_deserializer=lambda b: b)
                    self._methods[name] = stub
            try:
                return pickle.loads(stub(pickle.dumps(payload)))
            except self._grpc.RpcError as err:
                code = err.code() if hasattr(err, "code") else None
                if code == self._grpc.StatusCode.UNAVAILABLE:
                    # Transport outage: fresh channel, then the policy's
                    # jittered bounded backoff decides the retry cadence.
                    self._rebuild_channel()
                    raise
                raise NonRetryableError(str(err)) from err

        return self._reconnect.run(once)

    # -- MessageLog surface --------------------------------------------------
    def topic(self, name: str, partitions: Optional[int] = None
              ) -> _RemoteTopic:
        known = self._topics.get(name)
        if known is None or (partitions is not None
                             and partitions != len(known.partitions)):
            n = self._call("Topic",
                           (name, partitions or self.default_partitions))
            known = _RemoteTopic(self, name, n)
            self._topics[name] = known
        return known

    def send(self, topic: str, key: str, value) -> QueuedMessage:
        offset = self._call("Send", (topic, key, value))
        return QueuedMessage(topic, 0, offset, key, value)

    def send_to(self, topic: str, partition: int, key: str,
                value) -> QueuedMessage:
        """Produce to an EXPLICIT partition (MessageLog.send_to parity)
        — the sharded ingest tier's md5 document routing must override
        the broker's own key hash."""
        offset = self._call("SendTo", (topic, partition, key, value))
        return QueuedMessage(topic, partition, offset, key, value)

    def send_to_many(self, topic: str, partition: int,
                     items) -> List[QueuedMessage]:
        """Batched explicit-partition produce in ONE round trip — the
        producer-side twin of commit_many. On a durable broker the whole
        list also shares one group commit, so the per-record fsync AND
        the per-record network hop amortize together. At-least-once on
        retry applies to the whole batch (UNAVAILABLE mid-call can
        re-append a prefix; the pipeline dedups downstream exactly as
        for a retried send_to)."""
        items = list(items)
        offsets = self._call("SendToMany", (topic, partition, items))
        return [QueuedMessage(topic, partition, off, key, value)
                for off, (key, value) in zip(offsets, items)]

    def commit_many(self, group: str, topic: str,
                    offsets: Dict[int, int]) -> None:
        """Batched cross-partition ack: ONE round trip commits a whole
        pump round's per-partition offsets (the win that matters on this
        networked deployment shape — N partitions stop costing N gRPC
        calls per checkpoint flush)."""
        self._call("CommitMany", (group, topic, dict(offsets)))

    def poll(self, group: str, topic: str, partition: int = 0,
             limit: int = 1000) -> List[QueuedMessage]:
        start = self.committed(group, topic, partition)
        return self.topic(topic).partitions[partition].read(start, limit)

    def read_from(self, topic: str, partition: int, offset: int,
                  limit: int = 1000) -> List[QueuedMessage]:
        """Group-independent explicit-offset read (MessageLog.read_from
        parity); the broker side serves cold offsets from its segment
        index on a durable engine."""
        rows = self._call("Read", (topic, partition, offset, limit))
        return [QueuedMessage(topic, partition, off, key, value)
                for off, key, value in rows]

    def commit(self, group: str, topic: str, partition: int,
               offset: int) -> None:
        self._call("Commit", (group, topic, partition, offset))

    def committed(self, group: str, topic: str, partition: int) -> int:
        return self._call("Committed", (group, topic, partition))

    def close(self) -> None:
        self._channel.close()
