"""Sustained-typing serving decay probe: does ingest throughput stay
flat as documents age?

Drives waves of same-document typing boxcars through the REAL
TpuSequencerLambda raw fast path and reports per-wave rates. Before the
host zamboni pack (PERF.md round-5 addendum 2), steady-state throughput
decayed 139k -> 75k -> 17k ops/s on the CPU host as lanes climbed
capacity buckets (apply cost scales with C); with the overflow-time fold
it stays flat forever, with a bounded fold wave every ~capacity/window
waves.

    python -m fluidframework_tpu.server.decay_probe               # quick
    python -m fluidframework_tpu.server.decay_probe --docs 256 \
        --ops 16 --waves 40

Prints one JSON line: fast-wave median rate, fold-wave stats, sustained
rate, and lane-health counters. Exit nonzero if the LAST quartile of
fast waves is >2x slower than the first (decay = the regression this
tool exists to catch).

Reference analog: the deli lambda's steady-state throughput under
sustained per-document traffic (deli/lambda.ts:142 ticket loop, whose
cost does not grow with document age because the TS merge-tree zamboni
packs acked segments, mergeTree.ts:1289)."""

from __future__ import annotations

import argparse
import json
import random
import sys
import time


def run(docs: int, ops: int, waves: int) -> dict:
    from fluidframework_tpu.mergetree.client import OP_INSERT
    from fluidframework_tpu.protocol.messages import (Boxcar,
                                                      DocumentMessage,
                                                      MessageType)
    from fluidframework_tpu.server.log import QueuedMessage
    from fluidframework_tpu.server.tpu_sequencer import TpuSequencerLambda
    from fluidframework_tpu.server.wire import boxcar_to_wire

    class Ctx:
        def checkpoint(self, *_):
            pass

        def error(self, err, restart=False):
            raise err

    def build_wave(wave: int):
        rng = random.Random(17 + wave)
        out = []
        base_csn = wave * ops
        for d in range(docs):
            contents = []
            if wave == 0:
                contents.append(DocumentMessage(
                    client_sequence_number=0,
                    reference_sequence_number=-1,
                    type=MessageType.CLIENT_JOIN,
                    data=json.dumps({"clientId": f"c{d}", "detail": {}})))
            for i in range(ops):
                n = rng.randrange(1, 4)
                contents.append(DocumentMessage(
                    client_sequence_number=base_csn + i + 1,
                    reference_sequence_number=base_csn,
                    type=MessageType.OPERATION,
                    contents={"address": "s", "contents": {
                        "address": "t", "contents": {
                            "type": OP_INSERT, "pos1": 0,
                            "seg": {"text": "x" * n}}}}))
            out.append(QueuedMessage(
                topic="rawdeltas", partition=0, offset=wave * docs + d,
                key=f"d{d}",
                value=boxcar_to_wire(Boxcar(
                    tenant_id="b", document_id=f"d{d}", client_id=f"c{d}",
                    contents=contents))))
        return out

    lam = TpuSequencerLambda(Ctx(), emit=lambda *a: None,
                             nack=lambda *a: None, client_timeout_s=0.0)
    lam.emit_window = lambda w: None
    lam.pipelined = True
    if lam._pump is None:
        raise RuntimeError("native wirepump unavailable")

    from fluidframework_tpu.telemetry import counters

    rates = []
    prebuilt = [build_wave(w) for w in range(waves)]
    for w, msgs in enumerate(prebuilt):
        t0 = time.perf_counter()
        for qm in msgs:
            lam.handler_raw(qm)
        lam.flush()
        lam.drain()
        rates.append(docs * ops / (time.perf_counter() - t0))
        # Live gauge per wave: the monitor/health surface sees sustained-
        # typing throughput (and its decay) while the probe runs, instead
        # of the reading living only in this process's stdout.
        counters.gauge("decay_probe.wave_ops_s", rates[-1])
        counters.increment("decay_probe.waves")
    # Warmup (compiles, first promotions) = first quarter; classify the
    # rest into fast waves vs maintenance (fold) waves by median gap.
    tail = rates[waves // 4:]
    med = sorted(tail)[len(tail) // 2]
    fast = [r for r in tail if r >= med / 3]
    folds = [r for r in tail if r < med / 3]
    total_ops = docs * ops * len(tail)
    sustained = total_ops / sum(docs * ops / r for r in tail)
    q = max(1, len(fast) // 4)
    first_q = sorted(fast[:q])[q // 2]
    last_q = sorted(fast[-q:])[q // 2]
    import jax
    decayed = bool(last_q * 2 < first_q)
    # Final verdict + sustained rate into the process counters: a monitor
    # watching this process (or a bench run embedding the probe) exports
    # them via /health and /metrics.prom.
    counters.gauge("decay_probe.sustained_ops_s", sustained)
    counters.gauge("decay_probe.decayed", 1.0 if decayed else 0.0)
    return {
        "backend": jax.default_backend(),
        "docs": docs, "ops_per_wave": ops, "waves": waves,
        "fast_wave_median_ops_per_sec": round(med, 1),
        "fast_wave_first_quartile_median": round(first_q, 1),
        "fast_wave_last_quartile_median": round(last_q, 1),
        "maintenance_waves": len(folds),
        "sustained_ops_per_sec": round(sustained, 1),
        "folds": lam.merge.folds,
        "payload_compactions": lam.merge.payload_compactions,
        "blocks_aged": lam.merge.blocks_aged,
        "overflow_drops": lam.merge.overflow_drops,
        "decayed": decayed,
    }


def main() -> int:
    ap = argparse.ArgumentParser()
    ap.add_argument("--docs", type=int, default=256)
    ap.add_argument("--ops", type=int, default=16)
    ap.add_argument("--waves", type=int, default=40)
    args = ap.parse_args()
    out = run(args.docs, args.ops, args.waves)
    print(json.dumps(out))
    return 1 if out["decayed"] else 0


if __name__ == "__main__":
    sys.exit(main())
