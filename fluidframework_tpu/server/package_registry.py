"""Package registry service: versioned code-bundle delivery.

Capability parity with reference server/auspkn (the npm-registry proxy that
serves package bundles to the code loader / gateway): stores package
metadata + payloads per (name, version), serves version listings and
best-match resolution over REST, and backs `RegistryCodeResolver` — the
remote source a `CodeLoader` consults when a container's code details name
a package this process has not registered locally. The reference proxies
npm/Verdaccio; here bundles are JSON module manifests (this framework's
modules are in-process Python, so a "bundle" carries the entry-point spec
rather than JS sources).
"""

from __future__ import annotations

import json
import threading
import urllib.parse
import urllib.request
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Dict, List, Optional

from ..core.semver import parse_version, satisfies


class PackageStore:
    """In-memory versioned package store (auspkn's npm-backed store role)."""

    def __init__(self):
        self._packages: Dict[str, Dict[str, dict]] = {}
        self._lock = threading.Lock()

    def publish(self, name: str, version: str, manifest: dict) -> None:
        with self._lock:
            versions = self._packages.setdefault(name, {})
            if version in versions:
                raise ValueError(f"{name}@{version} already published")
            versions[version] = dict(manifest)

    def versions(self, name: str) -> List[str]:
        with self._lock:
            return sorted(self._packages.get(name, {}),
                          key=parse_version)

    def resolve(self, name: str, spec: str = "*") -> Optional[dict]:
        with self._lock:
            versions = self._packages.get(name, {})
            matching = [v for v in versions if satisfies(v, spec)]
            if not matching:
                return None
            best = max(matching, key=parse_version)
            return {"name": name, "version": best,
                    "manifest": versions[best]}


class PackageRegistryService:
    """REST front (reference auspkn routes /:package/:version paths):
    GET /packages/<name>            -> {"versions": [...]}
    GET /packages/<name>/<spec>     -> best-match {"name","version","manifest"}
    POST /packages/<name>/<version> -> publish (json body = manifest)
    """

    def __init__(self, store: Optional[PackageStore] = None,
                 host: str = "127.0.0.1", port: int = 0):
        self.store = store or PackageStore()
        service = self

        class Handler(BaseHTTPRequestHandler):
            protocol_version = "HTTP/1.1"

            def log_message(self, fmt, *args):  # quiet
                pass

            def do_GET(self):
                service._route(self, "GET")

            def do_POST(self):
                service._route(self, "POST")

        self._httpd = ThreadingHTTPServer((host, port), Handler)
        self._httpd.daemon_threads = True
        self.host, self.port = self._httpd.server_address
        self._thread: Optional[threading.Thread] = None

    def start(self) -> "PackageRegistryService":
        self._thread = threading.Thread(target=self._httpd.serve_forever,
                                        name="package-registry", daemon=True)
        self._thread.start()
        return self

    def stop(self) -> None:
        self._httpd.shutdown()
        self._httpd.server_close()
        if self._thread:
            self._thread.join(timeout=5)

    @property
    def url(self) -> str:
        return f"http://{self.host}:{self.port}"

    def _route(self, handler, method: str) -> None:
        parts = [urllib.parse.unquote(p) for p in
                 handler.path.partition("?")[0].split("/") if p]
        try:
            if len(parts) >= 2 and parts[0] == "packages":
                name = parts[1]
                if method == "GET" and len(parts) == 2:
                    return _send(handler, 200,
                                 {"versions": self.store.versions(name)})
                if method == "GET" and len(parts) == 3:
                    resolved = self.store.resolve(name, parts[2])
                    if resolved is None:
                        return _send(handler, 404,
                                     {"error": f"no match {name}@{parts[2]}"})
                    return _send(handler, 200, resolved)
                if method == "POST" and len(parts) == 3:
                    length = int(handler.headers.get("Content-Length", 0))
                    raw = handler.rfile.read(length) if length else b""
                    try:
                        manifest = json.loads(raw) if raw else {}
                    except json.JSONDecodeError as exc:
                        return _send(handler, 400,
                                     {"error": f"malformed body: {exc}"})
                    self.store.publish(name, parts[2], manifest)
                    return _send(handler, 201, {"published":
                                                f"{name}@{parts[2]}"})
            _send(handler, 404, {"error": f"no route {handler.path}"})
        except ValueError as exc:
            _send(handler, 409, {"error": str(exc)})
        except Exception as exc:  # noqa: BLE001 — route bug -> 500
            _send(handler, 500, {"error": repr(exc)})


def _send(handler, status: int, payload: dict) -> None:
    body = json.dumps(payload).encode()
    handler.send_response(status)
    handler.send_header("Content-Type", "application/json")
    handler.send_header("Content-Length", str(len(body)))
    handler.end_headers()
    handler.wfile.write(body)


class RegistryCodeResolver:
    """Client-side resolver: fetch best-match manifests from a registry and
    materialize them into a CodeLoader via a manifest interpreter
    (reference: the gateway resolves code details through auspkn before
    instantiating the runtime). `interpreter(manifest) -> runtime_factory`
    maps the served bundle spec onto an in-process factory."""

    def __init__(self, registry_url: str, interpreter):
        self.registry_url = registry_url.rstrip("/")
        self.interpreter = interpreter

    def fetch(self, name: str, spec: str = "*") -> dict:
        url = (f"{self.registry_url}/packages/"
               f"{urllib.parse.quote(name, safe='')}/"
               f"{urllib.parse.quote(spec, safe='')}")
        try:
            with urllib.request.urlopen(url) as resp:
                return json.load(resp)
        except urllib.error.HTTPError as err:
            if err.code == 404:
                raise KeyError(f"no registry match {name}@{spec}") from err
            raise

    def install_into(self, code_loader, name: str, spec: str = "*") -> str:
        """Fetch + register; returns the concrete version installed."""
        resolved = self.fetch(name, spec)
        code_loader.register(resolved["name"], resolved["version"],
                             self.interpreter(resolved["manifest"]))
        return resolved["version"]
