"""The fast serving flush's device program (tpu_sequencer._flush_raw).

One fused jit per window: [B, T] deli ticketing for the whole partition,
then per capacity bucket the merge/LWW apply — each op's assigned seq/msn
gathered from the ticket output by (doc lane, step), the admitted-ops-only
discipline of pipeline.full_step generalized to channel lanes that live in
a different lane space than documents — and finally everything the host
needs packed into ONE int32 vector (per-op seq/msn, nack flags, per-doc
next_seq, overflow summary bits). Over a tunneled device every dispatch
and every fetch pays a serialized RPC (~70 ms floor, PERF.md), so the
window is exactly one dispatch and one D2H.

Reference analog: deli/lambda.ts:142 ticket() feeding downstream lambdas;
the merge/LWW applies play Scribe's materialization role fused into the
same device window.

Observability: the WHOLE program is one dispatch by design, so host-side
tracing (telemetry/tracing.py) cannot subdivide it — the serving flush's
named sub-spans bracket it instead: ``serving.pack`` (staging the cols
this function consumes), ``serving.dispatch`` (this jit call),
``serving.readback`` (the flat16 D2H), with fold/rescue and payload GC
as their own host stages. Each feeds a ``serving.*`` histogram on
``/metrics.prom`` (docs/observability.md).
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp

from ..mergetree import kernel
from ..mergetree.oppack import OpKind, PackedOps
from . import lww_kernel as lk
from . import ticket_kernel as tk


def _serve_window_impl(tstate, ticket_cols, merge_states, merge_cols,
                       lww_states, lww_cols, fused=False, merge_runs=None,
                       noop_skip=False, stats=False, paged_scalars=False):
    """The traced body shared by ``serve_window`` (one jitted window),
    ``serve_window_keep`` (the non-donating recovery variant), and
    ``serve_burst``'s scan step (K windows in one program).

    ``noop_skip`` is the burst-padding escape hatch: stacked burst
    windows pad to the union of staged buckets, so a bucket a window
    never staged carries an all-NOOP op plane — with the flag set, each
    bucket's apply is lax.cond-guarded on "any real op", skipping the
    whole T-step apply for padding (kernel.apply_if_any; a NOOP stream
    is an exact identity on the lane state either way, so results are
    bit-identical — the guard only saves the padded window's compute).
    Single-window callers keep it off: the cond costs a predicate per
    bucket and a real window always has work.

    ticket_cols: [4, B, T] int32 (kind, client, cseq, refseq) — ONE H2D.
    merge_cols:  per bucket [12, lanes, Tm] (10 PackedOps columns +
                 doc_idx + t_idx) — ONE H2D each.
    lww_cols:    per bucket [6, lanes, Tm] (kind, key, val, delta,
                 doc_idx, t_idx).
    Returns (tstate', merge_states', lww_states', flat16, msn32) where
    flat16 is the NARROW int16 result the host fetches every window:
    [seq_delta B*T | msn_delta B*T | flags B*T | next_seq as (lo B, hi B)
    | msn_base as (lo B, hi B) | msn_ok bit | overflow-any bits |
    per-lane overflow planes (merge then LWW, lanes each) | per-lane
    occupancy planes (same order) | (stats=True only) the device
    telemetry plane: device_stats.N_SERVE int32 slots as (lo, hi)
    int16 halves], decoded by tpu_sequencer._finish_window; msn32 is
    the exact int32 msn plane, fetched ONLY when the window's msn span
    overflows the delta (msn_ok == 0; one global bit for the whole
    window).

    ``stats`` (static) appends the device-resident telemetry plane
    (telemetry/device_stats.py SERVE_SLOTS): admitted ops by kind,
    ticket admissions/nacks, overflow-lane and noop-skip counts, and
    post-window lane fill — counted INSIDE the program from the same
    masks the applies use, so a K-window burst reports exact per-window
    facts with zero extra dispatches and zero extra host round-trips
    (the plane rides this same flat16). Pure output: the op phases
    never read it, so results are bit-identical with it on or off.

    ``paged_scalars`` (static) is the MEGAKERNEL mode
    (docs/serving_pipeline.md R10): the merge "buckets" are gathered
    page-group views whose post scalars the host must adopt (paged
    scalars are host-authoritative between flushes), so each merge
    apply also produces the narrow tuple (overflow int16, count,
    min_seq, seq) — extracted IN-KERNEL by the fused pallas program on
    its last op step, or derived identically by the scan fallback —
    and flat16 grows a per-group int32-halves scalar tail (see the
    ``paged_tail`` packing below). ``fused == "interpret"`` runs the
    SAME pallas program through the pallas interpreter so CPU tier-1
    exercises the identical kernel; any other truthy ``fused`` means
    Mosaic-lowered."""
    raw = tk.RawOps(client=ticket_cols[1], client_seq=ticket_cols[2],
                    ref_seq=ticket_cols[3], kind=ticket_cols[0])
    tstate, ticketed = tk._scan_tickets(tstate, raw, batched=True,
                                        require_join=True)
    seq_bt, msn_bt = ticketed.seq, ticketed.min_seq

    if merge_runs is None:
        merge_runs = [None] * len(merge_cols)
    # Device telemetry accumulators (stats=True): counted from the SAME
    # ok-masks the applies consume, so the host mirror derived from the
    # decoded ticket results reconciles exactly.
    zero = jnp.zeros((), jnp.int32)
    st_kind = [zero] * 6  # INSERT..INSERT_RUN admitted counts
    st_lww = zero
    st_skips = zero
    new_merge = []
    merge_narrow = []  # paged_scalars only: per-group narrow tuples
    # fluidlint: disable=RETRACE_HAZARD — deliberate bounded unroll: one
    # iteration per capacity bucket (≤3 in production; docstring), fused
    # so the whole window stays a single device program.
    for mstate, mc, mr in zip(merge_states, merge_cols, merge_runs):
        packed = PackedOps(kind=mc[0], seq=mc[1], ref_seq=mc[2],
                           client=mc[3], pos1=mc[4], pos2=mc[5],
                           op_id=mc[6], new_len=mc[7], local_seq=mc[8],
                           msn=mc[9])
        seq_g = seq_bt[mc[10], mc[11]]
        msn_g = msn_bt[mc[10], mc[11]]
        ok = (packed.kind != OpKind.NOOP) & (seq_g > 0)
        runs = None
        over_extra = None
        if mr is not None:
            # INSERT_RUN slots: every member gathers ITS OWN ticketed
            # seq; a member the ticket pass nacked (dup/stale — the host
            # packed on a prediction) voids the WHOLE slot and flags the
            # lane, which then takes the standard overflow rollback +
            # scalar re-run. mr: [4, lanes, Tm, K] = len, op_id,
            # doc_lane, t_idx per member (len 0 = padding).
            from ..mergetree.oppack import RunCols
            sub_len, sub_oid = mr[0], mr[1]
            sub_seq = seq_bt[mr[2], mr[3]]
            expected = sub_len > 0
            is_run = packed.kind == OpKind.INSERT_RUN
            mispredict = is_run & jnp.any(expected & (sub_seq <= 0),
                                          axis=-1)
            ok = ok & ~mispredict
            runs = RunCols(length=sub_len,
                           seq=jnp.where(expected, sub_seq, 0),
                           op_id=sub_oid)
            over_extra = jnp.any(mispredict, axis=-1)
        ops2 = packed._replace(
            kind=jnp.where(ok, packed.kind, OpKind.NOOP),
            seq=jnp.where(ok, seq_g, 0),
            msn=jnp.where(ok, msn_g, 0))
        if stats:
            for ki, kv in enumerate((OpKind.INSERT, OpKind.REMOVE,
                                     OpKind.ANNOTATE, OpKind.ACK_INSERT,
                                     OpKind.ACK_REMOVE,
                                     OpKind.INSERT_RUN)):
                st_kind[ki] = st_kind[ki] + jnp.sum(
                    (ops2.kind == kv).astype(jnp.int32))
        from ..mergetree.pallas_apply import (FUSED_MAX_CAPACITY,
                                             apply_ops_fused_pallas)
        interp = fused == "interpret"
        use_fused = bool(fused) and mstate.capacity <= FUSED_MAX_CAPACITY
        if paged_scalars:
            # Megakernel mode: every merge apply also yields the narrow
            # scalar tuple the host adopts. The fused kernel extracts it
            # on its last op step (one pallas invocation per group per
            # window: gather view in, ops applied, narrow planes out);
            # the scan fallback derives the bit-identical tuple.
            def _narrow(s):
                # fluidlint: disable=DTYPE_DRIFT — deliberate 16-bit
                # wire packing (the overflow plane rides flat16).
                return (s.overflow.astype(jnp.int16), s.count,
                        s.min_seq, s.seq)
            if use_fused:
                def apply_m(s, o=ops2, r=runs):
                    return apply_ops_fused_pallas(s, o, interpret=interp,
                                                  runs=r, extract=True)
            else:
                def apply_m(s, o=ops2, r=runs):
                    out = kernel._scan_ops(s, o, batched=True, runs=r)
                    return out, _narrow(out)
            if noop_skip:
                active = jnp.any(ops2.kind != OpKind.NOOP)
                if stats:
                    st_skips = st_skips + (~active).astype(jnp.int32)
                # kernel.apply_if_any carries state only; the megakernel
                # body also threads the narrow tuple, so the pad-skip
                # cond is inlined with a derived-narrow identity arm.
                out, nr = jax.lax.cond(
                    active, apply_m, lambda s: (s, _narrow(s)), mstate)
            else:
                out, nr = apply_m(mstate)
            if over_extra is not None:
                # A nacked INSERT_RUN member voids the slot host-side:
                # the flag must reach BOTH the carried state and the
                # narrow plane the host actually reads.
                out = out._replace(overflow=out.overflow | over_extra)
                nr = (nr[0] | over_extra.astype(jnp.int16),) + nr[1:]
            new_merge.append(out)
            merge_narrow.append(nr)
            continue
        if runs is not None:
            # Run-bearing buckets: the fused kernel's INSERT_RUN variant
            # when Mosaic lowers it (fused == "both probes passed", see
            # tpu_sequencer), else the scan kernel — whose per-step cost
            # the packing itself collapses.
            if use_fused:
                def apply_m(s, o=ops2, r=runs):
                    return apply_ops_fused_pallas(s, o, interpret=interp,
                                                  runs=r)
            else:
                def apply_m(s, o=ops2, r=runs):
                    return kernel._scan_ops(s, o, batched=True, runs=r)
        elif use_fused:
            # VMEM-resident fused apply: the bucket's lane block stays
            # on-core across the whole op stream — the T-step HBM
            # re-read/re-write of the scan kernel (the serving apply's
            # dominant cost) collapses to one read + one write.
            # Bit-identical to the scan kernel (tests/test_pallas_apply).
            def apply_m(s, o=ops2):
                return apply_ops_fused_pallas(s, o, interpret=interp)
        else:
            def apply_m(s, o=ops2):
                return kernel._scan_ops(s, o, batched=True)
        if noop_skip:
            active = jnp.any(ops2.kind != OpKind.NOOP)
            if stats:
                st_skips = st_skips + (~active).astype(jnp.int32)
            out = kernel.apply_if_any(apply_m, mstate, active)
        else:
            out = apply_m(mstate)
        if over_extra is not None:
            out = out._replace(overflow=out.overflow | over_extra)
        new_merge.append(out)

    new_lww = []
    # fluidlint: disable=RETRACE_HAZARD — deliberate bounded unroll, one
    # iteration per LWW capacity bucket (same contract as the merge loop).
    for lstate, lc in zip(lww_states, lww_cols):
        seq_g = seq_bt[lc[4], lc[5]]
        ok = (lc[0] != lk.LwwKind.NOOP) & (seq_g > 0)
        ops = lk.LwwOps(kind=jnp.where(ok, lc[0], lk.LwwKind.NOOP),
                        key=lc[1], val=lc[2], delta=lc[3],
                        seq=jnp.where(ok, seq_g, 0))
        if stats:
            st_lww = st_lww + jnp.sum(
                (ops.kind != lk.LwwKind.NOOP).astype(jnp.int32))

        def apply_l(s, o=ops):
            return lk._scan(s, o, batched=True)
        if noop_skip:
            active_l = jnp.any(ops.kind != lk.LwwKind.NOOP)
            if stats:
                st_skips = st_skips + (~active_l).astype(jnp.int32)
            new_lww.append(kernel.apply_if_any(apply_l, lstate, active_l))
        else:
            new_lww.append(apply_l(lstate))

    flags = ticketed.nacked.astype(jnp.int32) | \
        (ticketed.not_joined.astype(jnp.int32) << 1)
    bits = [tstate.overflow.any()[None].astype(jnp.int32)]
    bits += [s.overflow.any()[None].astype(jnp.int32) for s in new_merge]
    bits += [s.overflow.any()[None].astype(jnp.int32) for s in new_lww]
    # Per-lane overflow planes ride the SAME narrow result (one int16 per
    # staged bucket lane): overflow recovery learns WHICH lanes flagged
    # without touching the post states at all — required once the lane
    # states are donated (the in-ring rollback cannot read a buffer the
    # next window's dispatch reused), and it also deletes the separate
    # per-bucket `overflow` D2H the rare recovery path used to pay.
    # fluidlint: disable=DTYPE_DRIFT — deliberate 16-bit wire packing:
    # the planes ride flat16, the narrow result plane (docstring).
    if paged_scalars:
        # Megakernel: the overflow planes come from the narrow tuples
        # (in-kernel extracted under fused; bit-identical derivation
        # under the scan fallback — over_extra already OR'd in).
        planes = [nr[0] for nr in merge_narrow]
    else:
        planes = [s.overflow.astype(jnp.int16) for s in new_merge]
    # fluidlint: disable=DTYPE_DRIFT — deliberate 16-bit wire packing
    # (same flat16 plane as the merge overflow planes above).
    planes += [s.overflow.astype(jnp.int16) for s in new_lww]
    # Post-window occupancy planes (row count per merge lane, occupied
    # key slots per LWW lane; capacities are <= 16k so int16 is exact):
    # the host's donation/deferral gate keeps its occupancy hints EXACT
    # from every window's own result instead of decaying pessimistic
    # until a compact-tick refresh — no extra device round-trip.
    # fluidlint: disable=DTYPE_DRIFT — deliberate 16-bit wire packing
    # (rides the same flat16 narrow result plane).
    if paged_scalars:
        # int16 view of the group counts keeps the flat16 layout uniform
        # with the bucketed wire; a large page group can wrap it, so the
        # host adopts from the exact int32 paged_tail below instead.
        planes += [nr[1].astype(jnp.int16) for nr in merge_narrow]
    else:
        planes += [s.count.astype(jnp.int16) for s in new_merge]
    # fluidlint: disable=DTYPE_DRIFT — deliberate 16-bit wire packing
    # (rides the same flat16 narrow result plane).
    planes += [(s.key >= 0).sum(-1).astype(jnp.int16) for s in new_lww]

    # NARROW result packing: the window result is the serving path's one
    # D2H, and over a tunneled device transfer bytes are throughput
    # (PERF.md: ~25 MB/s, per-array RPC floor => ONE int16 array).
    #   seq  -> delta from the lane's post-window next_seq: bounded by
    #           ops-per-lane <= T (structural); -1 = not admitted.
    #   msn  -> delta from the lane's min admitted msn; a catch-up jump
    #           can exceed int16 (rare) => one window-global ok bit, host
    #           refetches the int32 plane only then.
    #   int32 lane scalars ride as (lo, hi) int16 halves.
    admitted = seq_bt > 0
    next32 = tstate.next_seq.astype(jnp.int32)
    seq_d = jnp.where(admitted, next32[:, None] - seq_bt, -1)
    big = jnp.int32(1 << 30)
    msn_base = jnp.min(jnp.where(admitted, msn_bt, big), axis=1)
    msn_base = jnp.where(msn_base == big, 0, msn_base)
    msn_d = jnp.where(admitted, msn_bt - msn_base[:, None], 0)
    msn_ok = (jnp.max(msn_d) < 32000).astype(jnp.int32)
    msn_d = jnp.minimum(msn_d, 32000)

    def halves(x32):
        # lo may land negative in int16 (bit 15): host re-masks & 0xFFFF.
        return [(x32 & 0xFFFF).astype(jnp.int16),
                (x32 >> 16).astype(jnp.int16)]

    paged_tail = []
    if paged_scalars:
        # Megakernel scalar-adoption plane: each page group's post
        # count/min_seq/seq as EXACT int32 (lo, hi) halves — the host's
        # paged scalars are authoritative between flushes, and the int16
        # occupancy planes above can wrap for a large group, so every
        # window's finish adopts these (the last window's adoption is
        # the post-burst truth). Rides the same one flat16 readback.
        for nr in merge_narrow:
            paged_tail += halves(nr[1]) + halves(nr[2]) + halves(nr[3])

    stats_tail = []
    if stats:
        # The device telemetry plane (telemetry/device_stats.SERVE_SLOTS
        # order): int32 facts as (lo, hi) int16 halves riding the SAME
        # flat16 readback — no extra output, no extra RPC.
        st_vec = jnp.stack(st_kind + [
            st_lww,
            jnp.sum(admitted.astype(jnp.int32)),
            jnp.sum(ticketed.nacked.astype(jnp.int32)),
            jnp.sum(ticketed.not_joined.astype(jnp.int32)),
            sum((s.overflow.astype(jnp.int32).sum() for s in new_merge),
                zero),
            sum((s.overflow.astype(jnp.int32).sum() for s in new_lww),
                zero),
            st_skips,
            sum((s.count.astype(jnp.int32).sum() for s in new_merge),
                zero),
            sum(((s.key >= 0).astype(jnp.int32).sum() for s in new_lww),
                zero),
        ])
        stats_tail = halves(st_vec)

    flat16 = jnp.concatenate(
        [seq_d.ravel().astype(jnp.int16),
         msn_d.ravel().astype(jnp.int16),
         flags.ravel().astype(jnp.int16)]
        + halves(next32) + halves(msn_base)
        # fluidlint: disable=DTYPE_DRIFT — deliberate 16-bit wire packing:
        # flat16 is the NARROW result plane (docstring); decoded by
        # tpu_sequencer._finish_window.
        + [jnp.concatenate([msn_ok[None]] + bits).astype(jnp.int16)]
        + planes + paged_tail + stats_tail)
    # Fetched ONLY when msn_ok == 0 (second RPC on the rare path).
    return tstate, new_merge, new_lww, flat16, msn_bt


@functools.partial(jax.jit, donate_argnums=(0, 2, 4),
                   static_argnums=(6, 8))
def serve_window(tstate, ticket_cols, merge_states, merge_cols,
                 lww_states, lww_cols, fused=False, merge_runs=None,
                 stats=False):
    """One fast window, donating: the jitted single-window entry point
    over ``_serve_window_impl`` (docstring there carries the full
    contract and the flat16 layout)."""
    return _serve_window_impl(tstate, ticket_cols, merge_states,
                              merge_cols, lww_states, lww_cols, fused,
                              merge_runs, stats=stats)


# The non-donating recovery-replay variant: identical traced body, but the
# merge/LWW lane states survive the call. The sequencer dispatches through
# THIS variant whenever its host-side occupancy hints cannot prove the
# window overflow-free — the retained pre-window states are what the
# fold/rescue rollback scatters back before the batched re-run
# (tpu_sequencer._recover_fast_merge). The common provably-clean window
# takes the donating `serve_window` above and never allocates a second
# copy of the lane planes.
serve_window_keep = functools.partial(
    jax.jit, donate_argnums=(0,), static_argnums=(6, 8))(
        serve_window.__wrapped__)


def _serve_burst(tstate, merge_states, lww_states, ticket_xs, merge_xs,
                 lww_xs, runs_xs, fused=False, stats=False):
    """K serving windows in ONE scanned device program (the fused
    serving burst, docs/serving_pipeline.md R8).

    The ring overlaps the per-window host→device dispatch and narrow
    readback but every window still PAYS them — over a tunneled device
    each is a serialized RPC (~70 ms floor, PERF.md). The burst
    collapses K ready windows into one ``lax.scan`` whose carry is the
    donated lane-bucket state (ticket state + every staged merge/LWW
    bucket, updated in place across all K windows) and whose xs are the
    per-window packed op planes, pre-staged host-side into single
    stacked buffers:

      ticket_xs: [K, 4, B, T]  — per-window ticket staging
      merge_xs:  per union bucket [K, 12, lanes, Tm] (NOOP-padded where
                 a window staged nothing for the bucket; the scan body
                 cond-skips those applies — kernel.apply_if_any)
      lww_xs:    per union bucket [K, 6, lanes, Tm]
      runs_xs:   per union bucket [K, 4, lanes, Tm, RUN_K] or None

    ys are each window's narrow int16 result [K, flat] plus the exact
    msn planes [K, B, T] (fetched per window only on the rare msn-delta
    overflow) — the whole burst is ONE dispatch and ONE readback. The
    body is ``_serve_window_impl`` itself, so results are bit-identical
    to dispatching the K windows through ``serve_window`` back to back;
    the host-side finish path (seq distribution, nacks, overflow
    quarantine) runs per window off the stacked result exactly as it
    does off ring entries.

    Burst admission is the sequencer's job: only windows whose
    occupancy-hint fit proofs pass (non-risky AND donate-eligible)
    enter a burst, so overflow here is the same rare unpredicted class
    the donated per-window path handles (degrade + quarantine fixup).
    Mesh note: the body shards exactly as serve_window does under
    GSPMD, but bursts require donation, which dp meshes gate off (the
    jax 0.4.37 warm-cache corruption, docs/serving_pipeline.md R6) —
    so meshes stay on the per-window ring until that clears."""
    def body(carry, xs):
        ts, ms, ls = carry
        tc, mc, lc, rc = xs
        ts2, nm, nl, flat16, msn32 = _serve_window_impl(
            ts, tc, list(ms), list(mc), list(ls), list(lc), fused,
            list(rc), noop_skip=True, stats=stats)
        return (ts2, tuple(nm), tuple(nl)), (flat16, msn32)

    carry, ys = jax.lax.scan(
        body, (tstate, tuple(merge_states), tuple(lww_states)),
        (ticket_xs, tuple(merge_xs), tuple(lww_xs), tuple(runs_xs)))
    ts, ms, ls = carry
    return ts, list(ms), list(ls), ys[0], ys[1]


serve_burst = functools.partial(
    jax.jit, donate_argnums=(0, 1, 2),
    static_argnums=(7, 8))(_serve_burst)


def _serve_paged_burst(pool, page_ids, counts, min_seqs, seqs, ops_xs,
                       stats=False):
    """K op windows over PAGED documents in ONE scanned device program
    (the paged serving burst, docs/paged_memory.md): gather each doc's
    pages once, scan the K stacked [B, T] op planes with the gathered
    view as the carry, scatter back once through the page-table plane
    (immutable for the whole burst, so it carries no per-step scan
    leg). The page pool and page tables are the DONATED operands — the
    pool updates in place across the whole burst and page_ids alias
    straight through to the returned plane, so a bulk catch-up stream
    costs one dispatch regardless of its chunk count, with no
    bucket-padded planes anywhere: view capacity is the GROUP's page
    bucket, not the fleet-wide storm doc's.

    Returns (pool', page_ids, count, min_seq, seq, overflow, over_k,
    pre_view): over_k is the per-chunk overflow plane [K, B] (any bit
    -> the host rolls the flagged docs back from pre_view and runs the
    host rescue with the FULL stream, mirroring the bucketed recovery
    contract); pre_view is the gathered pre-burst group view that makes
    that rollback possible under donation. ``stats`` (static) appends
    the per-chunk device telemetry plane [K, N_PAGED]
    (kernel.paged_stats_vec riding the scan ys — per-chunk facts from
    the one dispatch the burst already is)."""
    from ..mergetree import kernel

    pre = kernel.gather_pages(pool, page_ids, counts, min_seqs, seqs)

    def body(view, ops):
        out = kernel._scan_ops(view, ops, batched=True)
        if stats:
            return out, (out.overflow, kernel.paged_stats_vec(ops, out))
        return out, out.overflow

    out, ys = jax.lax.scan(body, pre, ops_xs)
    over_k = ys[0] if stats else ys
    pool2 = kernel.scatter_pages(pool, page_ids, out)
    # page_ids pass straight through as an output (identity), which is
    # what lets XLA alias the donated plane; tables are immutable for
    # the whole burst, so they carry no per-step scan leg.
    base = (pool2, page_ids, out.count, out.min_seq, out.seq,
            out.overflow, over_k, pre)
    if stats:
        return base + (ys[1],)
    return base


serve_paged_burst = functools.partial(
    jax.jit, donate_argnums=(0, 1), static_argnums=(6,))(
        _serve_paged_burst)

# Non-donating K-chunk burst for MESH-placed pools (serving_pipeline.md
# R6: donation never reaches a mesh-placed dispatch; MESH_DONATION_GATE
# is the lint half of the same contract).
serve_paged_burst_keep = functools.partial(
    jax.jit, static_argnums=(6,))(_serve_paged_burst)


def _serve_megakernel(tstate, pool, lww_states, ticket_xs, page_ids,
                      counts, min_seqs, seqs, merge_xs, lww_xs, runs_xs,
                      fused=False, stats=False):
    """K fast serving windows over PAGED merge lanes in ONE device
    program — the serving megakernel (docs/serving_pipeline.md R10).

    This is the paged twin of ``_serve_burst``: the native pump's fast
    flush stages its merge rows as PAGE-GROUP jobs (one group per pow2
    page-count class, tpu_sequencer.MergeLaneStore paged mode) instead
    of capacity buckets, and the whole pre-staged ring drains as one
    dispatch. The program:

      1. gathers each group's documents ONCE by page id
         (kernel.gather_pages — view capacity is the GROUP's page
         bucket, never a fleet-wide padded plane),
      2. scans the K stacked windows with ``_serve_window_impl`` as the
         body (ticketing + op applies + narrow extraction), the gathered
         group views + LWW bucket states + ticket state as the carry —
         under ``fused`` each group×window apply is one pallas kernel
         invocation that applies the op phases VMEM-resident and
         EXTRACTS the narrow planes (overflow int16, count/min_seq/seq
         int32) on its own last op step (``fused == "interpret"`` runs
         the identical program through the pallas interpreter for CPU
         tier-1; ``fused=False`` is the counted scan-path fallback,
         bit-identical by construction),
      3. scatters each group's post view back through its immutable
         page table.

    xs layout:
      ticket_xs: [K, 4, B, T]
      page_ids/counts/min_seqs/seqs: per group, the dispatch-time paged
        staging ([n_pad, p2] int32 tables + [n_pad] scalars; pid -1 =
        padding) — immutable for the whole ring, NOT scanned over.
      merge_xs:  per group [K, 12, n_pad, Tm] (NOOP-padded where a
                 window staged nothing for the group)
      lww_xs:    per LWW union bucket [K, 6, lanes, Tm]
      runs_xs:   per group [K, 4, n_pad, Tm, RUN_K] or None

    Returns (tstate', pool', lww_states', flat16_k [K, flat], msn_k
    [K, B, T], pre_views): flat16 here carries the R10 paged scalar
    tail (``paged_scalars`` in ``_serve_window_impl``) so the host
    adopts exact post int32 scalars per window with no extra readback;
    pre_views are the gathered pre-ring group views that make the
    overflow rollback possible under donation (the paged analog of the
    bucketed ``pre`` job states — rollback_pages + host rescue, same
    recovery contract as ``_serve_paged_burst``).

    One ring = one dispatch = one readback: dispatches/burst amortizes
    toward 0 as the ring deepens, and the jit signature depends only on
    (K, group shapes, B, T) — scan length does not fragment the grid
    beyond the K axis, which the sequencer quantizes exactly like burst
    k (``_burst_k_grid``)."""
    pre = tuple(kernel.gather_pages(pool, p, c, m, s)
                for p, c, m, s in zip(page_ids, counts, min_seqs, seqs))

    def body(carry, xs):
        ts, ms, ls = carry
        tc, mc, lc, rc = xs
        ts2, nm, nl, flat16, msn32 = _serve_window_impl(
            ts, tc, list(ms), list(mc), list(ls), list(lc), fused,
            list(rc), noop_skip=True, stats=stats, paged_scalars=True)
        return (ts2, tuple(nm), tuple(nl)), (flat16, msn32)

    carry, ys = jax.lax.scan(
        body, (tstate, pre, tuple(lww_states)),
        (ticket_xs, tuple(merge_xs), tuple(lww_xs), tuple(runs_xs)))
    ts, ms, ls = carry
    pool2 = pool
    for p, out in zip(page_ids, ms):
        pool2 = kernel.scatter_pages(pool2, p, out)
    return ts, pool2, list(ls), ys[0], ys[1], pre


serve_megakernel = functools.partial(
    jax.jit, donate_argnums=(0, 1, 2), static_argnums=(11, 12))(
        _serve_megakernel)

# Non-donating twin for MESH-placed pools (serving_pipeline.md R6, same
# contract as serve_paged_burst_keep: donation never reaches a
# mesh-placed dispatch).
serve_megakernel_keep = functools.partial(
    jax.jit, static_argnums=(11, 12))(_serve_megakernel)
