"""Durable service backends: checkpoints/deltas/summaries that survive
process death.

The reference persists lambda state to MongoDB and summaries to bare git
repos on disk (scriptorium/lambda.ts:16-103 insertMany into Mongo;
gitrest over nodegit). The equivalents here:

- SqliteDatabaseManager / SqliteCollection: the services-core ICollection
  SPI over a sqlite3 file — same API as the in-memory DatabaseManager
  (database.py), drop-in for LocalServer(db=...). Unique-key idempotence
  (the dup-key-11000 replay guard) becomes a UNIQUE column.
- FileGitStore / FileHistorian: content-addressed objects + refs persisted
  to a directory (objects/<sha>, refs.json), loadable by a fresh process.

In-memory remains the test default; pass these in where durability is the
point (kill-and-restart, multi-node over shared storage).
"""

from __future__ import annotations

import bisect
import json
import os
import pickle
import sqlite3
import struct
import threading
import time
import zlib
from typing import Any, Callable, Dict, List, Optional, Tuple

from ..telemetry.counters import bounded, gauge, increment, observe
from .log import MessageLog
from .storage import GitBlob, GitCommit, GitStore, GitTree, Historian


class SqliteCollection:
    """services-core ICollection over one sqlite table. Documents are JSON
    rows; the unique key (when configured) is a computed TEXT column with a
    UNIQUE index, so replayed inserts are dropped exactly like the
    reference's ignored dup-key errors."""

    def __init__(self, conn: sqlite3.Connection, lock: threading.Lock,
                 name: str,
                 unique_key: Optional[Callable[[dict], Any]] = None):
        self._conn = conn
        self._lock = lock
        self._table = f'col_{name}'
        self._unique_key = unique_key
        with self._lock:
            self._conn.execute(
                f'CREATE TABLE IF NOT EXISTS "{self._table}" '
                '(id INTEGER PRIMARY KEY AUTOINCREMENT, '
                ' ukey TEXT, doc TEXT NOT NULL)')
            if unique_key is not None:
                self._conn.execute(
                    f'CREATE UNIQUE INDEX IF NOT EXISTS '
                    f'"{self._table}_ukey" ON "{self._table}" (ukey) '
                    'WHERE ukey IS NOT NULL')
            self._conn.commit()

    def _key(self, doc: dict) -> Optional[str]:
        if self._unique_key is None:
            return None
        return json.dumps(self._unique_key(doc), sort_keys=True, default=str)

    def insert_one(self, doc: dict) -> bool:
        with self._lock:
            try:
                self._conn.execute(
                    f'INSERT INTO "{self._table}" (ukey, doc) VALUES (?, ?)',
                    (self._key(doc), json.dumps(doc, default=str)))
                self._conn.commit()
                return True
            except sqlite3.IntegrityError:
                return False  # idempotent replay

    def insert_many(self, docs: List[dict]) -> int:
        """Batch insert as ONE transaction: executemany under a single
        commit instead of a commit per row (the reference's insertMany).
        INSERT OR IGNORE keeps the per-row idempotence contract — a
        replayed row with a duplicate unique key is dropped without
        aborting the rest of the batch, exactly like insert_one's
        swallowed IntegrityError — and rowcount reports only the rows
        actually inserted."""
        if not docs:
            return 0
        with self._lock:
            cur = self._conn.executemany(
                f'INSERT OR IGNORE INTO "{self._table}" (ukey, doc) '
                'VALUES (?, ?)',
                [(self._key(d), json.dumps(d, default=str)) for d in docs])
            self._conn.commit()
            return max(cur.rowcount, 0)

    def _rows(self) -> List[Tuple[int, dict]]:
        # Takes the shared-connection lock itself: every reader of the
        # row snapshot is serialized against writers' commits even if a
        # future caller forgets the outer lock.
        with self._lock:
            return self._rows_locked()

    def _rows_locked(self) -> List[Tuple[int, dict]]:
        cur = self._conn.execute(
            f'SELECT id, doc FROM "{self._table}" ORDER BY id')
        return [(rid, json.loads(doc)) for rid, doc in cur.fetchall()]

    def find(self, predicate: Callable[[dict], bool]) -> List[dict]:
        return [d for _, d in self._rows() if predicate(d)]

    def find_one(self, predicate: Callable[[dict], bool]) -> Optional[dict]:
        for _, d in self._rows():
            if predicate(d):
                return d
        return None

    def upsert(self, match: Callable[[dict], bool], doc: dict) -> None:
        with self._lock:
            for rid, d in self._rows_locked():
                if match(d):
                    self._conn.execute(
                        f'UPDATE "{self._table}" SET doc = ?, ukey = ? '
                        'WHERE id = ?',
                        (json.dumps(doc, default=str), self._key(doc), rid))
                    self._conn.commit()
                    return
            self._conn.execute(
                f'INSERT INTO "{self._table}" (ukey, doc) VALUES (?, ?)',
                (self._key(doc), json.dumps(doc, default=str)))
            self._conn.commit()

    def __len__(self) -> int:
        with self._lock:
            cur = self._conn.execute(
                f'SELECT COUNT(*) FROM "{self._table}"')
            return cur.fetchone()[0]


class SqliteDatabaseManager:
    """IDatabaseManager over one sqlite file (drop-in for DatabaseManager)."""

    def __init__(self, path: str):
        self.path = path
        os.makedirs(os.path.dirname(os.path.abspath(path)), exist_ok=True)
        self._conn = sqlite3.connect(path, check_same_thread=False)
        self._lock = threading.Lock()
        self._collections: Dict[str, SqliteCollection] = {}
        self._meta_lock = threading.Lock()

    def collection(self, name: str,
                   unique_key: Optional[Callable[[dict], Any]] = None
                   ) -> SqliteCollection:
        with self._meta_lock:
            if name not in self._collections:
                self._collections[name] = SqliteCollection(
                    self._conn, self._lock, name, unique_key)
            return self._collections[name]

    def close(self) -> None:
        self._conn.close()


# ---------------------------------------------------------------------------
# durable ordered log: segment files + group commit
# ---------------------------------------------------------------------------

# Record framing inside a segment: <u32 payload len><u32 crc32(payload)>
# <payload>. The CRC is what detects a torn tail — a crash can persist the
# header without (all of) the payload, or the payload bytes only partially,
# and a length check alone cannot tell a torn record from a valid one.
_FRAME_HDR = struct.Struct("<II")
# Sparse index sidecar (<base>.idx): fixed (absolute offset, file pos)
# pairs every INDEX_EVERY records. Never fsynced — it is a pure
# accelerator, rebuilt from the segment walk whenever recovery rewrites
# the tail.
_IDX_ENTRY = struct.Struct("<QQ")
DEFAULT_SEGMENT_BYTES = 64 * 1024 * 1024
DEFAULT_INDEX_EVERY = 64


class _SegmentStore:
    """Disk half of ONE partition: rotating append-only segment files named
    by base offset (<base:020d>.seg) under <topic>/<partition>.d/, each
    with a sparse offset->position sidecar index. The rdkafka segment
    shape: rolled segments are immutable and fully fsynced; only the
    active tail can be torn by a crash."""

    def __init__(self, dirpath: str, segment_bytes: int, index_every: int):
        self.dir = dirpath
        self.segment_bytes = segment_bytes
        self.index_every = index_every
        os.makedirs(dirpath, exist_ok=True)
        self.bases: List[int] = sorted(
            int(name[:-4]) for name in os.listdir(dirpath)
            if name.endswith(".seg"))
        self.end = 0                 # next offset to assign
        self.truncated_bytes = 0     # torn tail dropped by last recover()
        self._active = None          # append handle for the last segment
        self._active_base = -1
        self._active_size = 0
        self._idx = None             # append handle for the active index

    def _seg_path(self, base: int) -> str:
        return os.path.join(self.dir, f"{base:020d}.seg")

    def _idx_path(self, base: int) -> str:
        return os.path.join(self.dir, f"{base:020d}.idx")

    # -- recovery ----------------------------------------------------------
    def recover(self) -> int:
        """Walk the segments, establish the end offset, and truncate a torn
        final record (short header, short payload, or CRC mismatch) off
        the last segment AND its index. Returns the end offset. Rolled
        (non-final) segments were fsynced before the roll, so only the
        final segment gets the full CRC walk."""
        self.end = self.bases[0] if self.bases else 0
        for i, base in enumerate(self.bases):
            final = i == len(self.bases) - 1
            path = self._seg_path(base)
            count, valid_bytes = self._walk(path, check_crc=final)
            self.end = base + count
            size = os.path.getsize(path)
            if valid_bytes < size:
                self.truncated_bytes += size - valid_bytes
                with open(path, "r+b") as f:
                    f.truncate(valid_bytes)
                self._rewrite_index(base, count, path)
                break  # nothing after a torn record is trustworthy
        return self.end

    @staticmethod
    def _walk(path: str, check_crc: bool) -> Tuple[int, int]:
        """Count whole valid records; returns (count, byte length of the
        valid prefix). With check_crc, payload bytes are read and
        checksummed; without, payloads are seeked over (header walk)."""
        count, pos = 0, 0
        size = os.path.getsize(path)
        with open(path, "rb") as f:
            while True:
                header = f.read(_FRAME_HDR.size)
                if len(header) < _FRAME_HDR.size:
                    break
                length, crc = _FRAME_HDR.unpack(header)
                if pos + _FRAME_HDR.size + length > size:
                    break  # torn payload
                if check_crc:
                    payload = f.read(length)
                    if zlib.crc32(payload) & 0xFFFFFFFF != crc:
                        break  # torn write that completed the length field
                else:
                    f.seek(length, 1)
                pos += _FRAME_HDR.size + length
                count += 1
        return count, pos

    def _rewrite_index(self, base: int, count: int, seg_path: str) -> None:
        """Drop index entries past a truncation point (a stale entry would
        otherwise point mid-record once appends resume)."""
        entries = [(off, fpos) for off, fpos in self._load_index(base)
                   if off < base + count]
        tmp = self._idx_path(base) + ".tmp"
        with open(tmp, "wb") as f:
            for off, fpos in entries:
                f.write(_IDX_ENTRY.pack(off, fpos))
        os.replace(tmp, self._idx_path(base))

    def _load_index(self, base: int) -> List[Tuple[int, int]]:
        path = self._idx_path(base)
        if not os.path.exists(path):
            return []
        out = []
        with open(path, "rb") as f:
            blob = f.read()
        for i in range(0, len(blob) - len(blob) % _IDX_ENTRY.size,
                       _IDX_ENTRY.size):
            out.append(_IDX_ENTRY.unpack_from(blob, i))
        return out

    # -- iteration / indexed reads -----------------------------------------
    def read(self, start: int, limit: int) -> List[Tuple[int, str, Any]]:
        """Indexed seek: find the segment covering `start` via bisect over
        base offsets, jump to the greatest indexed position <= start,
        and decode forward — replay from a committed offset touches only
        the record's neighbourhood, not the whole partition history."""
        out: List[Tuple[int, str, Any]] = []
        if not self.bases or start >= self.end:
            return out
        start = max(start, self.bases[0])
        si = bisect.bisect_right(self.bases, start) - 1
        for base in self.bases[si:]:
            if len(out) >= limit:
                break
            off, pos = base, 0
            if base <= start:
                for ioff, ipos in self._load_index(base):
                    if ioff <= start:
                        off, pos = ioff, ipos
                    else:
                        break
            path = self._seg_path(base)
            size = os.path.getsize(path)
            with open(path, "rb") as f:
                f.seek(pos)
                while pos < size and len(out) < limit:
                    header = f.read(_FRAME_HDR.size)
                    if len(header) < _FRAME_HDR.size:
                        break
                    length, _crc = _FRAME_HDR.unpack(header)
                    if pos + _FRAME_HDR.size + length > size:
                        break
                    payload = f.read(length)
                    if off >= start:
                        key, value = pickle.loads(payload)
                        out.append((off, key, value))
                    pos += _FRAME_HDR.size + length
                    off += 1
        return out

    def records(self, start: int = 0):
        """Stream (offset, key, value) from `start` to the end — the full
        replay path at open."""
        remaining = self.end - start
        while remaining > 0:
            chunk = self.read(start, min(remaining, 1024))
            if not chunk:
                break
            for row in chunk:
                yield row
            start = chunk[-1][0] + 1
            remaining = self.end - start

    # -- append ------------------------------------------------------------
    def _roll(self) -> None:
        if self._active is not None:
            self._active.flush()
            os.fsync(self._active.fileno())
            self._active.close()
            if self._idx is not None:
                self._idx.close()
        base = self.end
        self.bases.append(base)
        self._active = open(self._seg_path(base), "ab")
        self._idx = open(self._idx_path(base), "ab")
        self._active_base = base
        self._active_size = 0

    def _open_tail(self) -> None:
        """Attach the append handles to the recovered final segment."""
        base = self.bases[-1]
        self._active = open(self._seg_path(base), "ab")
        self._idx = open(self._idx_path(base), "ab")
        self._active_base = base
        self._active_size = os.path.getsize(self._seg_path(base))

    def append_frame(self, frame: bytes) -> int:
        """Stage one record into the active segment (NO fsync — the group
        commit fsyncs once per batch). Returns the assigned offset."""
        if self._active is None:
            if self.bases:
                self._open_tail()
            else:
                self._roll()
        if self._active_size >= self.segment_bytes:
            self._roll()
        if self.end % self.index_every == 0:
            self._idx.write(_IDX_ENTRY.pack(self.end, self._active_size))
        self._active.write(_FRAME_HDR.pack(
            len(frame), zlib.crc32(frame) & 0xFFFFFFFF) + frame)
        self._active_size += _FRAME_HDR.size + len(frame)
        offset = self.end
        self.end += 1
        return offset

    def fsync(self) -> None:
        if self._active is not None:
            self._active.flush()
            os.fsync(self._active.fileno())
        if self._idx is not None:
            self._idx.flush()  # index is rebuildable: flushed, not fsynced

    def close(self) -> None:
        if self._active is not None:
            self._active.flush()
            os.fsync(self._active.fileno())
            self._active.close()
            self._active = None
        if self._idx is not None:
            self._idx.close()
            self._idx = None

    @property
    def segment_count(self) -> int:
        return len(self.bases)


class _PendingAppend:
    """One producer's record waiting for the covering fsync."""

    __slots__ = ("topic", "part", "key", "value", "done", "msg", "error")

    def __init__(self, topic: str, part, key: str, value: Any):
        self.topic = topic
        self.part = part
        self.key = key
        self.value = value
        self.done = threading.Event()
        self.msg = None
        self.error: Optional[BaseException] = None


class DurableMessageLog(MessageLog):
    """MessageLog whose partitions and consumer offsets persist to disk —
    the Kafka durability role for the broker deployment (a crashed broker
    restarts with its full history and committed offsets; lambdas replay
    only their uncheckpointed suffix).

    Layout: <root>/<topic>/<partition>.d/<base>.seg rotating segment files
    (length+CRC framed pickle frames, sparse <base>.idx offset->position
    sidecars) + <root>/offsets.json (atomic fsync'd rewrite on commit).
    The pre-segment single-file layout (<partition>.log) is migrated in
    place at open. Pickle is fine here for the same reason it is on the
    gRPC link: this is a trusted internal surface; untrusted clients speak
    to alfred's JSON/JWT front door, never to the broker.

    Produce runs through a GROUP COMMIT: senders stage their record into a
    bounded append buffer; the first sender in becomes the drain leader,
    writes every staged frame, and issues ONE fsync per touched partition
    file for the whole batch. An ack (the send_to return / listener fire)
    is released only after the covering fsync, so the at-least-once
    contract is bit-for-bit the per-message-fsync engine's — what changes
    is only that N concurrent producers share one disk flush instead of
    queueing N. A single-threaded producer degrades to exactly the old
    one-fsync-per-send behaviour. send_to_many() batches explicitly: the
    whole list rides one commit regardless of concurrency.

    replay="committed" keeps only each partition's uncheckpointed suffix
    in memory (Partition.base_offset) and serves colder offsets straight
    from the segment files via the sparse index — a restarted broker with
    a long history seeks to the committed frontier instead of re-reading
    and re-materializing every record ever appended."""

    def __init__(self, root: str, default_partitions: int = 1,
                 replay: str = "full",
                 segment_bytes: int = DEFAULT_SEGMENT_BYTES,
                 index_every: int = DEFAULT_INDEX_EVERY,
                 max_pending: int = 4096):
        if replay not in ("full", "committed"):
            raise ValueError(f"replay must be full|committed, got {replay!r}")
        super().__init__(default_partitions)
        self.root = root
        os.makedirs(root, exist_ok=True)
        self.segment_bytes = segment_bytes
        self.index_every = index_every
        self._stores: Dict[Tuple[str, int], _SegmentStore] = {}
        self._io_lock = threading.Lock()
        # Group-commit state: bounded staging buffer + leader election.
        self._gc_cv = threading.Condition()
        self._gc_pending: List[_PendingAppend] = []
        self._gc_leader = False
        self._gc_max_pending = max_pending
        self._offsets_path = os.path.join(root, "offsets.json")
        if os.path.exists(self._offsets_path):
            with open(self._offsets_path) as f:
                for key, off in json.load(f).items():
                    group, topic, part = key.rsplit("|", 2)
                    self.checkpoints[(group, topic, int(part))] = off
        for topic_name in sorted(os.listdir(root)):
            tdir = os.path.join(root, topic_name)
            if not os.path.isdir(tdir):
                continue
            self._open_topic(topic_name, tdir, replay)

    # -- open / recovery ---------------------------------------------------
    def _open_topic(self, topic_name: str, tdir: str, replay: str) -> None:
        parts: set = set()
        for entry in os.listdir(tdir):
            if entry.endswith(".log") and entry[:-4].isdigit():
                parts.add(int(entry[:-4]))       # pre-segment layout
            elif entry.endswith(".d") and entry[:-2].isdigit():
                parts.add(int(entry[:-2]))
        topic = self.topic(topic_name,
                           partitions=max(len(parts) and max(parts) + 1,
                                          self.default_partitions))
        for p in sorted(parts):
            legacy = os.path.join(tdir, f"{p}.log")
            if os.path.exists(legacy):
                self._migrate_legacy(topic_name, p, legacy)
            store = self._store_for(topic_name, p)
            store.recover()
            partition = topic.partitions[p]
            start = 0
            if replay == "committed":
                committed = [off for (g, t, pi), off
                             in self.checkpoints.items()
                             if t == topic_name and pi == p]
                start = min(committed) if committed else 0
                start = min(start, store.end)
            partition.base_offset = start
            for off, key, value in store.records(start):
                msg = partition.append(key, value)  # on disk: no re-write
                assert msg.offset == off

    def _migrate_legacy(self, topic_name: str, p: int, legacy: str) -> None:
        """One-time layout upgrade: re-frame a pre-segment <p>.log (length-
        only framing, no CRC) into the segment store. Idempotent across a
        crash mid-migration: the legacy file is removed only after the
        migrated segment is fsynced, and a partial <p>.d left by an
        earlier attempt is wiped before redoing (the legacy file is still
        the authority while it exists)."""
        dirpath = os.path.join(self.root, topic_name, f"{p}.d")
        if os.path.isdir(dirpath):
            for name in os.listdir(dirpath):
                os.unlink(os.path.join(dirpath, name))
        store = self._store_for(topic_name, p)
        with open(legacy, "rb") as f:
            while True:
                header = f.read(4)
                if len(header) < 4:
                    break  # clean EOF or torn tail: stop here
                (size,) = struct.unpack("<I", header)
                frame = f.read(size)
                if len(frame) < size:
                    break  # torn frame from a mid-write crash: drop it
                store.append_frame(frame)
        store.fsync()
        os.unlink(legacy)

    def _store_for(self, topic: str, partition: int) -> _SegmentStore:
        skey = (topic, partition)
        store = self._stores.get(skey)
        if store is None:
            dirpath = os.path.join(self.root, topic, f"{partition}.d")
            store = _SegmentStore(dirpath, self.segment_bytes,
                                  self.index_every)
            self._stores[skey] = store
        return store

    # -- produce: group commit ---------------------------------------------
    def send(self, topic: str, key: str, value: Any):
        part = self.topic(topic).partition_for(key)
        return self._produce(topic, part, [(key, value)])[0]

    def send_to(self, topic: str, partition: int, key: str, value: Any):
        # Explicit-partition produce (the sharded ingest tier's md5
        # routing) must hit the SAME disk-first path as keyed sends — the
        # inherited in-memory send_to would silently drop durability.
        part = self.topic(topic).partitions[partition]
        return self._produce(topic, part, [(key, value)])[0]

    def send_to_many(self, topic: str, partition: int, items):
        """The whole batch rides one group commit: one write pass + one
        fsync covers every record, and every ack releases together after
        that fsync."""
        part = self.topic(topic).partitions[partition]
        return self._produce(topic, part, list(items))

    def _produce(self, topic: str, part, items) -> list:
        entries = [_PendingAppend(topic, part, k, v) for k, v in items]
        if not entries:
            return []
        lead = False
        with self._gc_cv:
            while (len(self._gc_pending) >= self._gc_max_pending
                   and self._gc_leader):
                self._gc_cv.wait(0.05)  # bounded buffer: backpressure
            self._gc_pending.extend(entries)
            if not self._gc_leader:
                self._gc_leader = True
                lead = True
        if lead:
            self._drain_as_leader()
        for e in entries:
            e.done.wait()
            if e.error is not None:
                raise e.error
        return [e.msg for e in entries]

    def _drain_as_leader(self) -> None:
        """Group-commit drain loop: swap out whatever accumulated, write
        and fsync it as one batch, release its acks, repeat until the
        buffer is empty. Records staged while a batch is on disk form
        the next batch — the Kafka group-commit window."""
        while True:
            with self._gc_cv:
                batch = self._gc_pending
                self._gc_pending = []
                if not batch:
                    self._gc_leader = False
                    self._gc_cv.notify_all()
                    return
                self._gc_cv.notify_all()  # wake backpressured producers
            self._commit_batch(batch)

    def _commit_batch(self, batch: List[_PendingAppend]) -> None:
        t0 = time.perf_counter()
        touched: Dict[_SegmentStore, str] = {}
        nbytes = 0
        error: Optional[BaseException] = None
        with self._io_lock:
            try:
                # Disk first, memory second: a crash between the two
                # replays the batch from disk; the reverse order would
                # lose acked records.
                for e in batch:
                    frame = pickle.dumps((e.key, e.value))
                    store = self._store_for(e.topic, e.part.index)
                    store.append_frame(frame)
                    touched[store] = e.topic
                    nbytes += _FRAME_HDR.size + len(frame)
                for store, tname in touched.items():
                    store.fsync()
                    increment("durable.fsyncs_total")
                    increment(bounded("durable.fsyncs_by_topic", tname))
            except BaseException as exc:  # noqa: BLE001 — disk faults vary
                error = exc
        if error is not None:
            # Nothing in this batch is known durable: fail every sender
            # (none were acked, so at-least-once holds — callers retry).
            for e in batch:
                e.error = error
                e.done.set()
            return
        increment("durable.batch_bytes", nbytes)
        increment("durable.records_total", len(batch))
        increment("durable.group_commits")
        gauge("durable.last_batch_records", len(batch))
        # Acks release only now, after the covering fsync: the in-memory
        # append (whose return value / listener fire IS the ack) happens
        # per record in staging order, so per-partition order on disk and
        # in memory are identical.
        for e in batch:
            try:
                e.msg = e.part.append(e.key, e.value)
            except BaseException as exc:  # noqa: BLE001
                e.error = exc
            e.done.set()
        observe("durable.group_commit", (time.perf_counter() - t0) * 1000.0)

    # -- consume: indexed cold reads ---------------------------------------
    def poll(self, group: str, topic: str, partition: int = 0,
             limit: int = 1000) -> list:
        return self.read_from(topic, partition,
                              self.committed(group, topic, partition),
                              limit)

    def read_from(self, topic: str, partition: int, offset: int,
                  limit: int = 1000) -> list:
        part = self.topic(topic).partitions[partition]
        if offset >= part.base_offset:
            return part.read(offset, limit)
        # Cold read below the resident window (replay="committed" open):
        # serve from the segment files via the sparse index.
        store = self._stores.get((topic, partition))
        if store is None:
            return part.read(offset, limit)
        with self._io_lock:
            rows = store.read(offset, limit)
        from .log import QueuedMessage
        return [QueuedMessage(topic, partition, off, key, value)
                for off, key, value in rows]

    # -- offsets -----------------------------------------------------------
    def commit(self, group: str, topic: str, partition: int,
               offset: int) -> None:
        super().commit(group, topic, partition, offset)
        self._persist_offsets()

    def commit_many(self, group: str, topic: str, offsets) -> None:
        # Batched cross-partition ack: ONE offsets-file rewrite for the
        # whole batch (the per-commit fsync'd rewrite is the expensive
        # half on this engine).
        super().commit_many(group, topic, offsets)
        self._persist_offsets()

    def _persist_offsets(self) -> None:
        with self._io_lock:
            dump = {f"{g}|{t}|{p}": off
                    for (g, t, p), off in self.checkpoints.items()}
            tmp = self._offsets_path + ".tmp"
            with open(tmp, "w") as f:
                json.dump(dump, f)
                f.flush()
                # fsync BEFORE the rename: os.replace is atomic in the
                # namespace but says nothing about the data — without
                # this, a crash can publish a zero-length/torn offsets
                # file under the final name.
                os.fsync(f.fileno())
            os.replace(tmp, self._offsets_path)

    def durable_stats(self) -> dict:
        """Monitor probe surface (server/monitor.py watch_durable)."""
        with self._gc_cv:
            pending = len(self._gc_pending)
        with self._io_lock:
            segments = sum(s.segment_count for s in self._stores.values())
            truncated = sum(s.truncated_bytes for s in self._stores.values())
        return {"pendingAppends": pending, "segments": segments,
                "tornBytesTruncated": truncated,
                "partitions": len(self._stores)}

    def close(self) -> None:
        # Drain in-flight group commits before tearing down the handles.
        with self._gc_cv:
            while self._gc_leader or self._gc_pending:
                self._gc_cv.wait(0.05)
        with self._io_lock:
            for store in self._stores.values():
                store.close()
            self._stores.clear()


# ---------------------------------------------------------------------------
# file-backed git storage
# ---------------------------------------------------------------------------

class FileGitStore(GitStore):
    """GitStore whose objects/refs persist under a directory:
    <root>/objects/<sha> (JSON-framed object) and <root>/refs.json —
    the gitrest bare-repo equivalent. Loads everything at construction
    (object counts here are summary-scale, not monorepo-scale)."""

    def __init__(self, root: str):
        super().__init__()
        self.root = root
        self._objdir = os.path.join(root, "objects")
        os.makedirs(self._objdir, exist_ok=True)
        self._refs_path = os.path.join(root, "refs.json")
        if os.path.exists(self._refs_path):
            with open(self._refs_path) as f:
                self._refs.update(json.load(f))
        for sha in os.listdir(self._objdir):
            self._objects[sha] = self._load_object(sha)

    def _load_object(self, sha: str):
        with open(os.path.join(self._objdir, sha), "rb") as f:
            framed = json.loads(f.read().decode("utf-8"))
        kind = framed["kind"]
        if kind == "blob":
            return GitBlob(sha, bytes.fromhex(framed["content"]))
        if kind == "tree":
            return GitTree(sha, {k: tuple(v)
                                 for k, v in framed["entries"].items()})
        return GitCommit(sha, framed["tree"], framed["parents"],
                         framed["message"], framed["timestamp"])

    def _persist_object(self, sha: str, obj) -> None:
        path = os.path.join(self._objdir, sha)
        if os.path.exists(path):
            return  # content-addressed: same sha == same bytes
        if isinstance(obj, GitBlob):
            framed = {"kind": "blob", "content": obj.content.hex()}
        elif isinstance(obj, GitTree):
            framed = {"kind": "tree",
                      "entries": {k: list(v)
                                  for k, v in obj.entries.items()}}
        else:
            framed = {"kind": "commit", "tree": obj.tree_sha,
                      "parents": obj.parents, "message": obj.message,
                      "timestamp": obj.timestamp}
        tmp = path + ".tmp"
        with open(tmp, "w") as f:
            json.dump(framed, f)
        os.replace(tmp, path)  # atomic publish

    def put_blob(self, content: bytes) -> str:
        sha = super().put_blob(content)
        self._persist_object(sha, self._objects[sha])
        return sha

    def put_tree(self, entries) -> str:
        sha = super().put_tree(entries)
        self._persist_object(sha, self._objects[sha])
        return sha

    def put_commit(self, tree_sha, parents, message) -> str:
        sha = super().put_commit(tree_sha, parents, message)
        self._persist_object(sha, self._objects[sha])
        return sha

    def set_ref(self, name: str, commit_sha: str) -> None:
        super().set_ref(name, commit_sha)
        tmp = self._refs_path + ".tmp"
        with open(tmp, "w") as f:
            json.dump(self._refs, f)
        os.replace(tmp, self._refs_path)


class FileHistorian(Historian):
    """Historian whose per-document stores persist under
    <root>/<tenant>/<document>/ (reference gitrest's repo-per-document
    layout behind the historian proxy)."""

    def __init__(self, root: str):
        super().__init__()
        self.root = root
        os.makedirs(root, exist_ok=True)

    def store(self, tenant_id: str, document_id: str) -> GitStore:
        key = (tenant_id, document_id)
        with self._lock:
            if key not in self._stores:
                self._stores[key] = FileGitStore(
                    os.path.join(self.root, _safe(tenant_id),
                                 _safe(document_id)))
            return self._stores[key]


def _safe(name: str) -> str:
    return "".join(c if c.isalnum() or c in "-_." else "_" for c in name)
