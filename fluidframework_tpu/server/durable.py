"""Durable service backends: checkpoints/deltas/summaries that survive
process death.

The reference persists lambda state to MongoDB and summaries to bare git
repos on disk (scriptorium/lambda.ts:16-103 insertMany into Mongo;
gitrest over nodegit). The equivalents here:

- SqliteDatabaseManager / SqliteCollection: the services-core ICollection
  SPI over a sqlite3 file — same API as the in-memory DatabaseManager
  (database.py), drop-in for LocalServer(db=...). Unique-key idempotence
  (the dup-key-11000 replay guard) becomes a UNIQUE column.
- FileGitStore / FileHistorian: content-addressed objects + refs persisted
  to a directory (objects/<sha>, refs.json), loadable by a fresh process.

In-memory remains the test default; pass these in where durability is the
point (kill-and-restart, multi-node over shared storage).
"""

from __future__ import annotations

import json
import os
import sqlite3
import threading
from typing import Any, Callable, Dict, List, Optional, Tuple

from .log import MessageLog
from .storage import GitBlob, GitCommit, GitStore, GitTree, Historian


class SqliteCollection:
    """services-core ICollection over one sqlite table. Documents are JSON
    rows; the unique key (when configured) is a computed TEXT column with a
    UNIQUE index, so replayed inserts are dropped exactly like the
    reference's ignored dup-key errors."""

    def __init__(self, conn: sqlite3.Connection, lock: threading.Lock,
                 name: str,
                 unique_key: Optional[Callable[[dict], Any]] = None):
        self._conn = conn
        self._lock = lock
        self._table = f'col_{name}'
        self._unique_key = unique_key
        with self._lock:
            self._conn.execute(
                f'CREATE TABLE IF NOT EXISTS "{self._table}" '
                '(id INTEGER PRIMARY KEY AUTOINCREMENT, '
                ' ukey TEXT, doc TEXT NOT NULL)')
            if unique_key is not None:
                self._conn.execute(
                    f'CREATE UNIQUE INDEX IF NOT EXISTS '
                    f'"{self._table}_ukey" ON "{self._table}" (ukey) '
                    'WHERE ukey IS NOT NULL')
            self._conn.commit()

    def _key(self, doc: dict) -> Optional[str]:
        if self._unique_key is None:
            return None
        return json.dumps(self._unique_key(doc), sort_keys=True, default=str)

    def insert_one(self, doc: dict) -> bool:
        with self._lock:
            try:
                self._conn.execute(
                    f'INSERT INTO "{self._table}" (ukey, doc) VALUES (?, ?)',
                    (self._key(doc), json.dumps(doc, default=str)))
                self._conn.commit()
                return True
            except sqlite3.IntegrityError:
                return False  # idempotent replay

    def insert_many(self, docs: List[dict]) -> int:
        return sum(1 for d in docs if self.insert_one(d))

    def _rows(self) -> List[Tuple[int, dict]]:
        cur = self._conn.execute(
            f'SELECT id, doc FROM "{self._table}" ORDER BY id')
        return [(rid, json.loads(doc)) for rid, doc in cur.fetchall()]

    def find(self, predicate: Callable[[dict], bool]) -> List[dict]:
        with self._lock:
            return [d for _, d in self._rows() if predicate(d)]

    def find_one(self, predicate: Callable[[dict], bool]) -> Optional[dict]:
        with self._lock:
            for _, d in self._rows():
                if predicate(d):
                    return d
        return None

    def upsert(self, match: Callable[[dict], bool], doc: dict) -> None:
        with self._lock:
            for rid, d in self._rows():
                if match(d):
                    self._conn.execute(
                        f'UPDATE "{self._table}" SET doc = ?, ukey = ? '
                        'WHERE id = ?',
                        (json.dumps(doc, default=str), self._key(doc), rid))
                    self._conn.commit()
                    return
            self._conn.execute(
                f'INSERT INTO "{self._table}" (ukey, doc) VALUES (?, ?)',
                (self._key(doc), json.dumps(doc, default=str)))
            self._conn.commit()

    def __len__(self) -> int:
        with self._lock:
            cur = self._conn.execute(
                f'SELECT COUNT(*) FROM "{self._table}"')
            return cur.fetchone()[0]


class SqliteDatabaseManager:
    """IDatabaseManager over one sqlite file (drop-in for DatabaseManager)."""

    def __init__(self, path: str):
        self.path = path
        os.makedirs(os.path.dirname(os.path.abspath(path)), exist_ok=True)
        self._conn = sqlite3.connect(path, check_same_thread=False)
        self._lock = threading.Lock()
        self._collections: Dict[str, SqliteCollection] = {}
        self._meta_lock = threading.Lock()

    def collection(self, name: str,
                   unique_key: Optional[Callable[[dict], Any]] = None
                   ) -> SqliteCollection:
        with self._meta_lock:
            if name not in self._collections:
                self._collections[name] = SqliteCollection(
                    self._conn, self._lock, name, unique_key)
            return self._collections[name]

    def close(self) -> None:
        self._conn.close()


# ---------------------------------------------------------------------------
# durable ordered log
# ---------------------------------------------------------------------------

class DurableMessageLog(MessageLog):
    """MessageLog whose partitions and consumer offsets persist to disk —
    the Kafka durability role for the broker deployment (a crashed broker
    restarts with its full history and committed offsets; lambdas replay
    only their uncheckpointed suffix).

    Layout: <root>/<topic>/<partition>.log (length-prefixed pickle frames,
    append-only — the rdkafka segment-file shape) + <root>/offsets.json
    (atomic rewrite on commit). Pickle is fine here for the same reason it
    is on the gRPC link: this is a trusted internal surface; untrusted
    clients speak to alfred's JSON/JWT front door, never to the broker."""

    def __init__(self, root: str, default_partitions: int = 1):
        super().__init__(default_partitions)
        self.root = root
        os.makedirs(root, exist_ok=True)
        self._files: dict = {}
        self._io_lock = threading.Lock()
        self._offsets_path = os.path.join(root, "offsets.json")
        if os.path.exists(self._offsets_path):
            with open(self._offsets_path) as f:
                for key, off in json.load(f).items():
                    group, topic, part = key.rsplit("|", 2)
                    self.checkpoints[(group, topic, int(part))] = off
        for topic_name in sorted(os.listdir(root)):
            tdir = os.path.join(root, topic_name)
            if not os.path.isdir(tdir):
                continue
            part_files = sorted(int(p[:-4]) for p in os.listdir(tdir)
                                if p.endswith(".log"))
            topic = self.topic(topic_name,
                               partitions=max(len(part_files),
                                              self.default_partitions))
            for p in part_files:
                self._replay_partition(topic.partitions[p],
                                       os.path.join(tdir, f"{p}.log"))

    def _replay_partition(self, partition, path: str) -> None:
        import pickle
        import struct
        with open(path, "rb") as f:
            while True:
                header = f.read(4)
                if len(header) < 4:
                    break  # clean EOF or torn tail write: stop replay here
                (size,) = struct.unpack("<I", header)
                frame = f.read(size)
                if len(frame) < size:
                    break  # torn frame from a mid-write crash: drop it
                key, value = pickle.loads(frame)
                partition.append(key, value)  # already on disk: no re-write

    def _file_for(self, topic: str, partition: int):
        fkey = (topic, partition)
        handle = self._files.get(fkey)
        if handle is None:
            tdir = os.path.join(self.root, topic)
            os.makedirs(tdir, exist_ok=True)
            handle = open(os.path.join(tdir, f"{partition}.log"), "ab")
            self._files[fkey] = handle
        return handle

    def send(self, topic: str, key: str, value: Any):
        part = self.topic(topic).partition_for(key)
        return self._send_durable(topic, part, key, value)

    def send_to(self, topic: str, partition: int, key: str, value: Any):
        # Explicit-partition produce (the sharded ingest tier's md5
        # routing) must hit the SAME disk-first path as keyed sends — the
        # inherited in-memory send_to would silently drop durability.
        part = self.topic(topic).partitions[partition]
        return self._send_durable(topic, part, key, value)

    def _send_durable(self, topic: str, part, key: str, value: Any):
        import pickle
        import struct
        with self._io_lock:
            # Disk first, memory second: a crash between the two replays
            # the message from disk; the reverse order would lose it.
            frame = pickle.dumps((key, value))
            handle = self._file_for(topic, part.index)
            handle.write(struct.pack("<I", len(frame)) + frame)
            handle.flush()
            os.fsync(handle.fileno())
        return part.append(key, value)

    def commit(self, group: str, topic: str, partition: int,
               offset: int) -> None:
        super().commit(group, topic, partition, offset)
        self._persist_offsets()

    def commit_many(self, group: str, topic: str, offsets) -> None:
        # Batched cross-partition ack: ONE offsets-file rewrite for the
        # whole batch (the per-commit fsync'd rewrite is the expensive
        # half on this engine).
        super().commit_many(group, topic, offsets)
        self._persist_offsets()

    def _persist_offsets(self) -> None:
        with self._io_lock:
            dump = {f"{g}|{t}|{p}": off
                    for (g, t, p), off in self.checkpoints.items()}
            tmp = self._offsets_path + ".tmp"
            with open(tmp, "w") as f:
                json.dump(dump, f)
            os.replace(tmp, self._offsets_path)

    def close(self) -> None:
        with self._io_lock:
            for handle in self._files.values():
                handle.close()
            self._files.clear()


# ---------------------------------------------------------------------------
# file-backed git storage
# ---------------------------------------------------------------------------

class FileGitStore(GitStore):
    """GitStore whose objects/refs persist under a directory:
    <root>/objects/<sha> (JSON-framed object) and <root>/refs.json —
    the gitrest bare-repo equivalent. Loads everything at construction
    (object counts here are summary-scale, not monorepo-scale)."""

    def __init__(self, root: str):
        super().__init__()
        self.root = root
        self._objdir = os.path.join(root, "objects")
        os.makedirs(self._objdir, exist_ok=True)
        self._refs_path = os.path.join(root, "refs.json")
        if os.path.exists(self._refs_path):
            with open(self._refs_path) as f:
                self._refs.update(json.load(f))
        for sha in os.listdir(self._objdir):
            self._objects[sha] = self._load_object(sha)

    def _load_object(self, sha: str):
        with open(os.path.join(self._objdir, sha), "rb") as f:
            framed = json.loads(f.read().decode("utf-8"))
        kind = framed["kind"]
        if kind == "blob":
            return GitBlob(sha, bytes.fromhex(framed["content"]))
        if kind == "tree":
            return GitTree(sha, {k: tuple(v)
                                 for k, v in framed["entries"].items()})
        return GitCommit(sha, framed["tree"], framed["parents"],
                         framed["message"], framed["timestamp"])

    def _persist_object(self, sha: str, obj) -> None:
        path = os.path.join(self._objdir, sha)
        if os.path.exists(path):
            return  # content-addressed: same sha == same bytes
        if isinstance(obj, GitBlob):
            framed = {"kind": "blob", "content": obj.content.hex()}
        elif isinstance(obj, GitTree):
            framed = {"kind": "tree",
                      "entries": {k: list(v)
                                  for k, v in obj.entries.items()}}
        else:
            framed = {"kind": "commit", "tree": obj.tree_sha,
                      "parents": obj.parents, "message": obj.message,
                      "timestamp": obj.timestamp}
        tmp = path + ".tmp"
        with open(tmp, "w") as f:
            json.dump(framed, f)
        os.replace(tmp, path)  # atomic publish

    def put_blob(self, content: bytes) -> str:
        sha = super().put_blob(content)
        self._persist_object(sha, self._objects[sha])
        return sha

    def put_tree(self, entries) -> str:
        sha = super().put_tree(entries)
        self._persist_object(sha, self._objects[sha])
        return sha

    def put_commit(self, tree_sha, parents, message) -> str:
        sha = super().put_commit(tree_sha, parents, message)
        self._persist_object(sha, self._objects[sha])
        return sha

    def set_ref(self, name: str, commit_sha: str) -> None:
        super().set_ref(name, commit_sha)
        tmp = self._refs_path + ".tmp"
        with open(tmp, "w") as f:
            json.dump(self._refs, f)
        os.replace(tmp, self._refs_path)


class FileHistorian(Historian):
    """Historian whose per-document stores persist under
    <root>/<tenant>/<document>/ (reference gitrest's repo-per-document
    layout behind the historian proxy)."""

    def __init__(self, root: str):
        super().__init__()
        self.root = root
        os.makedirs(root, exist_ok=True)

    def store(self, tenant_id: str, document_id: str) -> GitStore:
        key = (tenant_id, document_id)
        with self._lock:
            if key not in self._stores:
                self._stores[key] = FileGitStore(
                    os.path.join(self.root, _safe(tenant_id),
                                 _safe(document_id)))
            return self._stores[key]


def _safe(name: str) -> str:
    return "".join(c if c.isalnum() or c in "-_." else "_" for c in name)
