"""JSON wire codecs for protocol messages.

The in-process stack passes dataclass objects by reference; the network
stack (alfred websocket + REST, reference `services-client` serialization)
needs a stable JSON encoding. Field names mirror the dataclasses
(snake_case) so a row from scriptorium's delta collection and a wire
message decode identically (`loader/drivers/local.py:_row_to_message`).
"""

from __future__ import annotations

from dataclasses import asdict
from typing import List, Optional

from ..protocol.messages import (
    Boxcar,
    DocumentMessage,
    ITrace,
    Nack,
    NackContent,
    SequencedDocumentMessage,
)


def document_message_to_dict(msg: DocumentMessage) -> dict:
    return asdict(msg)


def document_message_from_dict(d: dict) -> DocumentMessage:
    return DocumentMessage(
        client_sequence_number=d["client_sequence_number"],
        reference_sequence_number=d["reference_sequence_number"],
        type=d["type"],
        contents=d.get("contents"),
        metadata=d.get("metadata"),
        server_metadata=d.get("server_metadata"),
        traces=[ITrace(**t) for t in d.get("traces", [])],
        data=d.get("data"),
    )


def sequenced_message_to_dict(msg: SequencedDocumentMessage) -> dict:
    return asdict(msg)


def sequenced_message_from_dict(d: dict) -> SequencedDocumentMessage:
    return SequencedDocumentMessage(
        client_id=d.get("client_id"),
        sequence_number=d["sequence_number"],
        minimum_sequence_number=d["minimum_sequence_number"],
        client_sequence_number=d["client_sequence_number"],
        reference_sequence_number=d["reference_sequence_number"],
        type=d["type"],
        contents=d.get("contents"),
        metadata=d.get("metadata"),
        server_metadata=d.get("server_metadata"),
        timestamp=d.get("timestamp", 0.0),
        term=d.get("term", 1),
        traces=[ITrace(**t) for t in d.get("traces", [])],
        data=d.get("data"),
        additional_content=d.get("additional_content"),
    )


def nack_to_dict(nack: Nack) -> dict:
    return {
        "operation": document_message_to_dict(nack.operation)
        if nack.operation is not None else None,
        "sequence_number": nack.sequence_number,
        "content": asdict(nack.content),
    }


def nack_from_dict(d: dict) -> Nack:
    op = d.get("operation")
    return Nack(
        operation=document_message_from_dict(op) if op else None,
        sequence_number=d["sequence_number"],
        content=NackContent(**d["content"]),
    )


def delta_rows_to_messages(rows: List[dict]) -> List[SequencedDocumentMessage]:
    return [sequenced_message_from_dict(r) for r in rows]


def boxcar_to_wire(boxcar: "Boxcar") -> bytes:
    """Canonical raw-log encoding of a boxcar (the shape a production
    Kafka topic carries; reference IBoxcarMessage JSON). Key order is part
    of the contract: the native pump (native/src/wirepump.cpp) requires
    documentId/clientId before contents, which json.dumps preserves."""
    import json as _json
    return _json.dumps({
        "tenantId": boxcar.tenant_id,
        "documentId": boxcar.document_id,
        "clientId": boxcar.client_id,
        "contents": [asdict(m) for m in boxcar.contents],
    }).encode("utf-8")


def boxcar_from_wire(raw: bytes) -> "Boxcar":
    import json as _json
    d = _json.loads(raw)
    return Boxcar(
        tenant_id=d.get("tenantId", ""),
        document_id=d.get("documentId", ""),
        client_id=d.get("clientId"),
        contents=[document_message_from_dict(m)
                  for m in d.get("contents", [])],
    )
