"""Historian: the standalone summary-cache tier between serving and GitStore.

Capability parity with reference server/historian (README:1-4): a caching
proxy that sits between clients/lambda hosts and the git-shaped storage
tier, so summary reads scale with the cache instead of with gitrest. The
reference fronts gitrest with Redis; here the tier is its own process
(`python -m fluidframework_tpu.server.historian`, or the `historian`
service of server/main.py) over the O(1) LRU+TTL policy in
server/cache.py.

Two backing modes, one behavior:
  - proxy mode (`upstream_url`): git objects fetch over alfred's gitrest
    routes (server/alfred.py `/repos/.../git/objects/<sha>`), with the
    caller's bearer token forwarded so alfred keeps enforcing auth. The
    `X-Historian-Tier` header marks tier-originated requests so an alfred
    configured to DELEGATE reads to this historian never loops.
  - store mode (`store=`): objects read straight from a (usually
    file-backed, server/durable.py FileHistorian) store shared with the
    lambda workers — the multi-process deployment shape.

Correctness model: git objects are content-addressed and immutable, so the
sha-keyed object cache never needs invalidation; only refs are mutable.
Refs ride a short-TTL pointer cache that is explicitly invalidated on
every summary commit that flows through the tier (write-through), and the
TTL bounds staleness for writers that bypass it (scribe acks in another
process). A summary upload also WARMS the cache: the new commit's tree and
blobs prefetch immediately, so the next container load is all hits.

Consumers: loader/drivers/routerlicious.py (`historian_url=`) serves
second-and-later container loads from this cache and degrades to direct
alfred/GitStore reads if the tier dies mid-load; server/alfred.py
delegates its latest-summary route here when configured; server/monitor.py
`watch_historian` exports the hit/miss/bytes/evictions counters.
"""

from __future__ import annotations

import base64
import json
import re
import threading
import urllib.error
import urllib.parse
import urllib.request
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Any, Dict, List, Optional

from ..protocol.summary import summary_tree_from_dict
from ..telemetry import tracing
from ..telemetry.counters import increment, record_swallow
from ..telemetry.logger import PerformanceEvent, TelemetryLogger
from .cache import LruTtlCache
from .readpath import CatchupCache
from .storage import GitBlob, GitCommit, GitTree, Historian

# Marks tier-originated upstream requests; alfred serves them directly
# from its GitStore instead of delegating back here (loop prevention).
TIER_HEADER = "X-Historian-Tier"


class SummaryConflict(Exception):
    """Initial summary for a document that already has a load target."""


class UpstreamError(Exception):
    """Non-404 HTTP failure from the upstream git storage."""

    def __init__(self, status: int, detail: str):
        super().__init__(f"upstream HTTP {status}: {detail}")
        self.status = status
        self.detail = detail


def git_object_to_wire(obj) -> Dict[str, Any]:
    """Typed JSON encoding of a git object (the gitrest wire shape)."""
    if isinstance(obj, GitBlob):
        return {"kind": "blob", "sha": obj.sha,
                "content": base64.b64encode(obj.content).decode("ascii"),
                "size": len(obj.content), "encoding": "base64"}
    if isinstance(obj, GitTree):
        return {"kind": "tree", "sha": obj.sha,
                "entries": {name: list(pair)
                            for name, pair in obj.entries.items()}}
    if isinstance(obj, GitCommit):
        return {"kind": "commit", "sha": obj.sha, "tree": obj.tree_sha,
                "parents": list(obj.parents), "message": obj.message,
                "timestamp": obj.timestamp}
    raise TypeError(f"not a git object: {type(obj)!r}")


def _wire_nbytes(wire: Dict[str, Any]) -> int:
    if wire.get("kind") == "blob":
        return len(wire.get("content", "")) + 96
    return len(json.dumps(wire))


def _request(method: str, url: str, token: Optional[str] = None,
             body: Optional[dict] = None, timeout: float = 30.0) -> dict:
    data = json.dumps(body).encode() if body is not None else None
    req = urllib.request.Request(url, data=data, method=method)
    req.add_header("Content-Type", "application/json")
    req.add_header(TIER_HEADER, "1")
    if token:
        req.add_header("Authorization", f"Bearer {token}")
    with urllib.request.urlopen(req, timeout=timeout) as resp:
        return json.loads(resp.read() or b"{}")


def _q(segment: str) -> str:
    return urllib.parse.quote(str(segment), safe="")


def notify_summary_commit(historian_url: str, tenant_id: str,
                          document_id: str, sha: Optional[str] = None,
                          ref: str = "main", timeout: float = 5.0) -> bool:
    """Best-effort commit notification to a historian process: invalidate
    the (tenant, doc, ref) pointer and warm-prefetch `sha`. Callers treat
    a dead historian as fine — the tier's ref TTL bounds staleness."""
    try:
        _request("POST", historian_url.rstrip("/")
                 + f"/historian/invalidate/{_q(tenant_id)}/{_q(document_id)}",
                 body={"sha": sha, "ref": ref}, timeout=timeout)
        return True
    except (OSError, ValueError):
        return False


def notify_catchup_refresh(historian_url: str, tenant_id: str,
                           document_id: str, artifact: dict,
                           token: Optional[str] = None,
                           timeout: float = 5.0) -> bool:
    """Best-effort catch-up artifact push to a historian process (the
    refresh-epoch analog of notify_summary_commit). A dead historian is
    fine — its stale artifact still adopts correctly (residue replay),
    and the next successful push replaces it."""
    try:
        url = (historian_url.rstrip("/")
               + f"/historian/catchup/{_q(tenant_id)}/{_q(document_id)}")
        _request("POST", url, token=token, body=artifact, timeout=timeout)
        return True
    except (OSError, ValueError):
        return False


class StoreUpstream:
    """Direct access to a (shared, usually file-backed) Historian store —
    the deployment mode where the tier and the lambda workers mount the
    same git directory. Auth is the deployer's network boundary here, as
    for the reference's internal gitrest."""

    def __init__(self, historian: Historian):
        self.historian = historian

    def get_object(self, tenant_id: str, document_id: str, sha: str,
                   token: Optional[str] = None) -> Optional[dict]:
        obj = self.historian.store(tenant_id, document_id).get(sha)
        return None if obj is None else git_object_to_wire(obj)

    def get_ref(self, tenant_id: str, document_id: str, ref: str,
                token: Optional[str] = None) -> Optional[str]:
        return self.historian.store(tenant_id, document_id).get_ref(ref)

    def upload_summary(self, tenant_id: str, document_id: str, body: dict,
                       token: Optional[str] = None) -> str:
        store = self.historian.store(tenant_id, document_id)
        initial = bool(body.get("initial"))
        if initial and store.get_ref("main") is not None:
            raise SummaryConflict(f"document {document_id!r} exists")
        tree = summary_tree_from_dict(body["summary"])
        return store.write_summary(tree, base_commit=body.get("parent"),
                                   advance_ref=initial)


class RestUpstream:
    """Upstream over alfred's gitrest REST routes (proxy mode). The
    caller's bearer token forwards per request so alfred's riddler
    validation still gates every object read."""

    def __init__(self, base_url: str, timeout: float = 30.0):
        self.base_url = base_url.rstrip("/")
        self.timeout = timeout

    def _get(self, path: str, token: Optional[str]) -> Optional[dict]:
        try:
            return _request("GET", self.base_url + path, token,
                            timeout=self.timeout)
        except urllib.error.HTTPError as exc:
            if exc.code == 404:
                return None
            raise UpstreamError(exc.code,
                                exc.read().decode(errors="replace")) from exc

    def get_object(self, tenant_id: str, document_id: str, sha: str,
                   token: Optional[str] = None) -> Optional[dict]:
        return self._get(f"/repos/{_q(tenant_id)}/{_q(document_id)}"
                         f"/git/objects/{_q(sha)}", token)

    def get_ref(self, tenant_id: str, document_id: str, ref: str,
                token: Optional[str] = None) -> Optional[str]:
        data = self._get(f"/repos/{_q(tenant_id)}/{_q(document_id)}"
                         f"/git/refs/{_q(ref)}", token)
        return data["sha"] if data else None

    def upload_summary(self, tenant_id: str, document_id: str, body: dict,
                       token: Optional[str] = None) -> str:
        try:
            return _request(
                "POST",
                self.base_url
                + f"/repos/{_q(tenant_id)}/{_q(document_id)}/summaries",
                token, body, timeout=self.timeout)["sha"]
        except urllib.error.HTTPError as exc:
            detail = exc.read().decode(errors="replace")
            if exc.code == 409:
                raise SummaryConflict(detail) from exc
            raise UpstreamError(exc.code, detail) from exc


class HistorianTier:
    """The cache tier itself (embeddable; HistorianService adds HTTP).

    Per-request work is O(objects served), each object O(1) through the
    cache: one short-TTL ref lookup, then a walk of immutable sha-keyed
    entries. Summary commits through the tier invalidate the ref pointer
    and prefetch the new tree (warm-on-summary)."""

    def __init__(self, upstream, max_bytes: int = 256 * 1024 * 1024,
                 max_entries: int = 65536, ref_ttl_s: float = 2.0,
                 auth_ttl_s: float = 60.0,
                 logger: Optional[TelemetryLogger] = None,
                 metrics=None):
        self.upstream = upstream
        self.objects = LruTtlCache(max_entries=max_entries,
                                   max_bytes=max_bytes, ttl_s=None)
        self.refs = LruTtlCache(max_entries=4096, ttl_s=ref_ttl_s)
        # Token-authorization cache (the reference historian validates
        # tokens against riddler and caches the verdict): a (tenant, doc,
        # token) triple must prove itself upstream once per TTL window
        # before CACHED entries serve — otherwise a cache hit would skip
        # the auth check a cold read performs. Store mode's upstream
        # never rejects, making this a no-op in the trusted-network
        # deployment shape.
        self.auth = LruTtlCache(max_entries=4096, ttl_s=auth_ttl_s)
        # Read-path catch-up delta blobs (server/readpath.py): published
        # by the serving tier on refresh epochs (write-through — a
        # publish IS the invalidation: put_if_newer replaces the stale
        # artifact atomically), served beside the summary in one round
        # trip by the `/catchup` route.
        self.catchup = CatchupCache()
        self.logger = logger
        self.metrics = metrics
        self.upstream_fetches = 0
        self.prefetched_objects = 0
        self.prefetch_shared_trees = 0
        self.summary_reads = 0
        self.summary_writes = 0
        self.invalidations = 0

    # -- object/ref reads --------------------------------------------------
    def get_object(self, tenant_id: str, document_id: str, sha: str,
                   token: Optional[str] = None) -> Optional[dict]:
        """Content-addressed read-through. Shas are shareable across
        documents (same rationale as storage.Historian.get_cached): a sha
        uniquely names its bytes."""
        wire = self.objects.get(sha)
        if wire is not None:
            return wire
        wire = self.upstream.get_object(tenant_id, document_id, sha, token)
        self.upstream_fetches += 1
        if wire is not None:
            self.objects.put(sha, wire, nbytes=_wire_nbytes(wire))
        return wire

    def get_ref(self, tenant_id: str, document_id: str, ref: str = "main",
                token: Optional[str] = None) -> Optional[str]:
        key = (tenant_id, document_id, ref)
        if self.auth.get((tenant_id, document_id, token)):
            sha = self.refs.get(key)
            if sha is not None:
                return sha
        sha = self.upstream.get_ref(tenant_id, document_id, ref, token)
        self.upstream_fetches += 1
        # Reaching here without an auth error (401/403 raise) proves the
        # token for this document — a 404 (no ref) is still authorized.
        self.auth.put((tenant_id, document_id, token), True)
        if sha is not None:
            self.refs.put(key, sha, nbytes=len(sha))
        return sha

    def ensure_authorized(self, tenant_id: str, document_id: str,
                          token: Optional[str] = None) -> None:
        """Gate for cache-served requests that would otherwise never
        touch upstream (explicit-sha reads, object routes): one cheap
        upstream ref probe per (tenant, doc, token) per auth-TTL window.
        Raises UpstreamError on a rejected token (proxy mode)."""
        if self.auth.get((tenant_id, document_id, token)):
            return
        self.upstream.get_ref(tenant_id, document_id, "main", token)
        self.upstream_fetches += 1
        self.auth.put((tenant_id, document_id, token), True)

    # -- composite reads ---------------------------------------------------
    def read_summary_dict(self, tenant_id: str, document_id: str,
                          commit_sha: Optional[str] = None,
                          ref: str = "main",
                          token: Optional[str] = None) -> Optional[dict]:
        """The drivers' summary download: the full tree in
        summary_tree_to_dict wire form, every object through the cache."""
        # Tail attribution for loads: the read joins the requesting op's
        # trace when the ambient context carries one; the histogram feeds
        # /metrics.prom either way.
        with tracing.span("historian.read_summary",
                          hist="historian.read_summary",
                          document=document_id) as sp:
            if commit_sha is not None:
                self.ensure_authorized(tenant_id, document_id, token)
            sha = commit_sha or self.get_ref(tenant_id, document_id, ref,
                                             token)
            if sha is None:
                return None
            commit = self.get_object(tenant_id, document_id, sha, token)
            if commit is None or commit.get("kind") != "commit":
                return None
            self.summary_reads += 1
            sp.set(sha=sha)
            return self._tree_dict(tenant_id, document_id, commit["tree"],
                                   token)

    def _tree_dict(self, tenant_id: str, document_id: str, tree_sha: str,
                   token: Optional[str]) -> dict:
        tree = self.get_object(tenant_id, document_id, tree_sha, token)
        if tree is None or tree.get("kind") != "tree":
            raise KeyError(f"missing tree object {tree_sha!r}")
        entries: Dict[str, Any] = {}
        for name, (kind, sha) in tree["entries"].items():
            if kind == "blob":
                blob = self.get_object(tenant_id, document_id, sha, token)
                if blob is None or blob.get("kind") != "blob":
                    raise KeyError(f"missing blob object {sha!r}")
                raw = base64.b64decode(blob["content"])
                try:
                    entries[name] = {"type": "blob",
                                     "content": raw.decode(),
                                     "encoding": "utf-8"}
                except UnicodeDecodeError:
                    entries[name] = {"type": "blob", "content": raw.hex(),
                                     "encoding": "hex"}
            else:
                entries[name] = self._tree_dict(tenant_id, document_id,
                                                sha, token)
        return {"type": "tree", "entries": entries}

    def versions(self, tenant_id: str, document_id: str, count: int = 1,
                 token: Optional[str] = None) -> List[str]:
        """Commit-chain walk: one ref lookup, then immutable commits out
        of the object cache."""
        out: List[str] = []
        sha = self.get_ref(tenant_id, document_id, "main", token)
        while sha and len(out) < count:
            out.append(sha)
            commit = self.get_object(tenant_id, document_id, sha, token)
            if commit is None or commit.get("kind") != "commit":
                break
            parents = commit.get("parents") or []
            sha = parents[0] if parents else None
        return out

    # -- read-path catch-up (docs/read_path.md) ----------------------------
    def publish_catchup(self, tenant_id: str, document_id: str,
                        artifact: dict) -> bool:
        """Write-through artifact publish from the serving tier (the
        refresh-epoch push, same hook shape as summary-commit
        invalidation). put_if_newer semantics: a racing older publish
        never regresses the served artifact."""
        return self.catchup.publish(tenant_id, document_id, artifact)

    def read_catchup(self, tenant_id: str, document_id: str,
                     token: Optional[str] = None,
                     artifact_only: bool = False) -> dict:
        """`summary + delta` in one request: the artifact (when present)
        plus the summary tree of exactly the commit the artifact was
        published against — both halves cache-served, so a warm
        connecting client costs this tier zero upstream traffic."""
        # The artifact IS full document content: cache-served requests
        # must prove their token exactly like the object routes do (the
        # artifact-only path would otherwise never touch upstream).
        self.ensure_authorized(tenant_id, document_id, token)
        artifact = self.catchup.get(tenant_id, document_id)
        out: Dict[str, Any] = {"catchup": artifact}
        if artifact_only:
            return out
        sha = (artifact or {}).get("summarySha")
        out["summary"] = self.read_summary_dict(
            tenant_id, document_id, commit_sha=sha, token=token)
        return out

    # -- writes + invalidation ---------------------------------------------
    def upload_summary(self, tenant_id: str, document_id: str, body: dict,
                       token: Optional[str] = None) -> str:
        """Write-through: the commit lands upstream first, then the ref
        pointer invalidates and the new tree prefetches (warm-on-summary),
        so a concurrent load never sees the cache ahead of storage."""
        sha = self.upstream.upload_summary(tenant_id, document_id, body,
                                           token)
        self.summary_writes += 1
        if self.metrics is not None:
            self.metrics.increment("historian.summaryWrites")
        self.handle_summary_commit(tenant_id, document_id, sha=sha,
                                   token=token)
        return sha

    def handle_summary_commit(self, tenant_id: str, document_id: str,
                              sha: Optional[str] = None, ref: str = "main",
                              token: Optional[str] = None,
                              prefetch: bool = True) -> None:
        """Invalidate the mutable pointer for (tenant, doc, ref) and warm
        the cache with the new commit's objects. Also the target of
        alfred's commit notifications (scribe acks)."""
        self.refs.invalidate((tenant_id, document_id, ref))
        self.invalidations += 1
        if self.metrics is not None:
            self.metrics.increment("historian.invalidations")
        if self.logger is not None:
            self.logger.send_telemetry_event({
                "eventName": "HistorianInvalidate", "tenantId": tenant_id,
                "documentId": document_id, "ref": ref})
        if prefetch and sha:
            self._prefetch(tenant_id, document_id, sha, token)

    def _prefetch(self, tenant_id: str, document_id: str, sha: str,
                  token: Optional[str]) -> None:
        """Best-effort walk of commit -> tree -> blobs into the cache."""
        before = self.objects.puts
        event = (PerformanceEvent.timed_event(
            self.logger, {"eventName": "HistorianPrefetch",
                          "documentId": document_id})
            if self.logger is not None else None)
        with tracing.span("historian.prefetch", hist="historian.prefetch",
                          document=document_id) as sp:
            try:
                commit = self.get_object(tenant_id, document_id, sha, token)
                if commit is not None and commit.get("kind") == "commit":
                    self._prefetch_tree(tenant_id, document_id,
                                        commit["tree"], token)
            except Exception as exc:  # noqa: BLE001 — warmup must never fail a write
                if self.metrics is not None:
                    self.metrics.increment("historian.prefetchFailures")
                record_swallow("historian.prefetch")
                sp.set(error=True)
                if event is not None:
                    event.cancel(error=exc)
                return
            loaded = self.objects.puts - before
            self.prefetched_objects += loaded
            sp.set(objects=loaded)
            if self.metrics is not None:
                self.metrics.increment("historian.prefetchedObjects",
                                       loaded)
            if event is not None:
                event.end({"objects": loaded})

    def _prefetch_tree(self, tenant_id: str, document_id: str,
                       tree_sha: str, token: Optional[str]) -> None:
        # Incremental summaries share unchanged subtrees with the parent
        # commit (clean channels ride as handles / identical shas), and
        # the object cache keys by BARE sha (content-addressed — see
        # get_object), so a shared subtree's walk is all cache hits:
        # upstream prefetch traffic scales with the CHANGED set. The
        # descent itself is NOT skipped on a cached tree sha — a blob
        # evicted under a still-cached tree must re-warm, or eviction
        # would silently break warm-on-summary forever. Shared subtrees
        # are counted (prefetchSharedTrees) so operators can see the
        # incremental sharing rate.
        shared = self.objects.contains(tree_sha)
        tree = self.get_object(tenant_id, document_id, tree_sha, token)
        if tree is None or tree.get("kind") != "tree":
            return
        if shared:
            self.prefetch_shared_trees += 1
            if self.metrics is not None:
                self.metrics.increment("historian.prefetchSharedTrees")
        for _, (kind, sha) in tree["entries"].items():
            if kind == "tree":
                self._prefetch_tree(tenant_id, document_id, sha, token)
            else:
                self.get_object(tenant_id, document_id, sha, token)

    # -- observability -----------------------------------------------------
    def stats(self) -> dict:
        return {
            "objects": self.objects.stats(),
            "refs": self.refs.stats(),
            "auth": self.auth.stats(),
            "catchup": self.catchup.stats(),
            "upstreamFetches": self.upstream_fetches,
            "prefetchedObjects": self.prefetched_objects,
            "prefetchSharedTrees": self.prefetch_shared_trees,
            "summaryReads": self.summary_reads,
            "summaryWrites": self.summary_writes,
            "invalidations": self.invalidations,
        }


class HistorianService:
    """The standalone historian process: HistorianTier behind HTTP, route
    shapes matching alfred's git surface so drivers can point their
    storage endpoint here unchanged."""

    _ROUTES = [
        ("GET", re.compile(r"^/api/v1/ping$"), "_r_ping"),
        ("GET", re.compile(r"^/historian/stats$"), "_r_stats"),
        ("POST", re.compile(
            r"^/historian/invalidate/(?P<tenant>[^/]+)/(?P<doc>[^/]+)$"),
         "_r_invalidate"),
        ("POST", re.compile(
            r"^/historian/catchup/(?P<tenant>[^/]+)/(?P<doc>[^/]+)$"),
         "_r_publish_catchup"),
        ("GET", re.compile(
            r"^/repos/(?P<tenant>[^/]+)/(?P<doc>[^/]+)/catchup$"),
         "_r_catchup"),
        ("GET", re.compile(
            r"^/repos/(?P<tenant>[^/]+)/(?P<doc>[^/]+)/summaries/latest$"),
         "_r_latest_summary"),
        ("POST", re.compile(
            r"^/repos/(?P<tenant>[^/]+)/(?P<doc>[^/]+)/summaries$"),
         "_r_upload_summary"),
        ("GET", re.compile(
            r"^/repos/(?P<tenant>[^/]+)/(?P<doc>[^/]+)/versions$"),
         "_r_versions"),
        ("GET", re.compile(
            r"^/repos/(?P<tenant>[^/]+)/(?P<doc>[^/]+)"
            r"/git/objects/(?P<sha>[^/]+)$"),
         "_r_object"),
        ("GET", re.compile(
            r"^/repos/(?P<tenant>[^/]+)/(?P<doc>[^/]+)"
            r"/git/blobs/(?P<sha>[^/]+)$"),
         "_r_blob"),
        ("GET", re.compile(
            r"^/repos/(?P<tenant>[^/]+)/(?P<doc>[^/]+)"
            r"/git/trees/(?P<sha>[^/]+)$"),
         "_r_tree"),
        ("GET", re.compile(
            r"^/repos/(?P<tenant>[^/]+)/(?P<doc>[^/]+)"
            r"/git/refs/(?P<ref>.+)$"),
         "_r_ref"),
    ]

    def __init__(self, upstream_url: Optional[str] = None,
                 store: Optional[Historian] = None,
                 host: str = "127.0.0.1", port: int = 0,
                 max_bytes: int = 256 * 1024 * 1024,
                 ref_ttl_s: float = 2.0,
                 logger: Optional[TelemetryLogger] = None,
                 metrics=None):
        if (upstream_url is None) == (store is None):
            raise ValueError(
                "exactly one of upstream_url (proxy mode) or store "
                "(shared-storage mode) is required")
        upstream = (RestUpstream(upstream_url) if upstream_url is not None
                    else StoreUpstream(store))
        self.tier = HistorianTier(upstream, max_bytes=max_bytes,
                                  ref_ttl_s=ref_ttl_s, logger=logger,
                                  metrics=metrics)
        service = self

        class Handler(BaseHTTPRequestHandler):
            protocol_version = "HTTP/1.1"

            def log_message(self, fmt, *args):  # quiet
                pass

            def do_GET(self):
                service._handle(self, "GET")

            def do_POST(self):
                service._handle(self, "POST")

        self._httpd = ThreadingHTTPServer((host, port), Handler)
        self._httpd.daemon_threads = True
        self.host, self.port = self._httpd.server_address
        self._thread: Optional[threading.Thread] = None

    # -- lifecycle ---------------------------------------------------------
    def start(self) -> "HistorianService":
        self._thread = threading.Thread(target=self._httpd.serve_forever,
                                        name="historian", daemon=True)
        self._thread.start()
        return self

    def stop(self) -> None:
        self._httpd.shutdown()
        self._httpd.server_close()
        if self._thread:
            self._thread.join(timeout=5)

    @property
    def url(self) -> str:
        return f"http://{self.host}:{self.port}"

    def stats(self) -> dict:
        return self.tier.stats()

    # -- dispatch ----------------------------------------------------------
    def _handle(self, handler, method: str) -> None:
        path, _, query = handler.path.partition("?")
        params = {name: values[-1] for name, values
                  in urllib.parse.parse_qs(query).items()}
        for route_method, pattern, name in self._ROUTES:
            if route_method != method:
                continue
            m = pattern.match(path)
            if m:
                groups = {k: urllib.parse.unquote(v)
                          for k, v in m.groupdict().items()}
                try:
                    getattr(self, name)(handler, params, **groups)
                except BrokenPipeError:
                    pass
                except SummaryConflict as exc:
                    _send_json(handler, 409, {"error": str(exc)})
                except UpstreamError as exc:
                    _send_json(handler, exc.status, {"error": exc.detail})
                except OSError as exc:
                    # Upstream unreachable: 503 tells callers to use their
                    # direct-GitStore fallback path.
                    _send_json(handler, 503, {"error": repr(exc)})
                except Exception as exc:  # noqa: BLE001 — route bug
                    increment("historian.route_errors")
                    try:
                        _send_json(handler, 500, {"error": repr(exc)})
                    except OSError:  # reply socket died mid-error
                        record_swallow("historian.route_reply")
                return
        _send_json(handler, 404, {"error": f"no route {method} {path}"})

    @staticmethod
    def _token(handler) -> Optional[str]:
        auth = handler.headers.get("Authorization", "")
        return auth[len("Bearer "):] if auth.startswith("Bearer ") else None

    # -- routes ------------------------------------------------------------
    def _r_ping(self, handler, params) -> None:
        _send_json(handler, 200, {"ok": True, "service": "historian"})

    def _r_stats(self, handler, params) -> None:
        _send_json(handler, 200, self.tier.stats())

    def _r_invalidate(self, handler, params, tenant: str, doc: str) -> None:
        body = _read_json(handler) or {}
        token = self._token(handler)
        self.tier.handle_summary_commit(
            tenant, doc, sha=body.get("sha"), ref=body.get("ref", "main"),
            token=token, prefetch=False)
        # Respond BEFORE the warm prefetch: notifiers (scribe's on_commit,
        # alfred's upload route) must not block on a whole-tree walk —
        # invalidation alone is what correctness needs. The prefetch then
        # runs on this handler thread with the response already on the
        # wire, and only for callers the upstream authorizes (otherwise
        # an unauthenticated invalidate would be a cache-bust DoS with
        # an upstream-fetch amplifier attached).
        _send_json(handler, 200, {"ok": True})
        sha = body.get("sha")
        if not sha:
            return
        try:
            self.tier.ensure_authorized(tenant, doc, token)
        except (UpstreamError, OSError):
            # Unauthorized (or upstream unreachable): the invalidate above
            # already happened — correctness holds — we only skip the warm
            # prefetch. Counted: a climbing rate means notifiers are
            # sending dead tokens and every reload is a cold miss.
            record_swallow("historian.unauthorized_prefetch")
            return
        except Exception:  # noqa: BLE001 — response already committed
            # The 200 is already on the wire (keep-alive socket): anything
            # escaping here would reach the route dispatcher and write a
            # SECOND response, desyncing the notifier's connection. E.g. a
            # malformed upstream body raises JSONDecodeError out of the
            # proxy-mode auth probe.
            record_swallow("historian.invalidate_prefetch_guard")
            return
        self.tier._prefetch(tenant, doc, sha, token)

    def _r_publish_catchup(self, handler, params, tenant: str,
                           doc: str) -> None:
        """Serving-tier artifact push (write-through). The body is
        arbitrary caller-supplied document state, so a READ token must
        not suffice (any subscriber holds one — a crafted high-seq
        artifact would poison every later connecting client): the
        publish requires the tier marker header, the same
        inside-the-service-boundary trust line the reference draws for
        internal gitrest traffic, ON TOP of upstream authorization."""
        body = _read_json(handler)
        token = self._token(handler)
        if not isinstance(body, dict) or "seq" not in body:
            _send_json(handler, 400, {"error": "not a catch-up artifact"})
            return
        if not handler.headers.get(TIER_HEADER):
            _send_json(handler, 403,
                       {"error": "catch-up publishes are serving-tier "
                                 "internal (missing tier marker)"})
            return
        self.tier.ensure_authorized(tenant, doc, token)
        wrote = self.tier.publish_catchup(tenant, doc, body)
        _send_json(handler, 200, {"ok": True, "published": wrote})

    def _r_catchup(self, handler, params, tenant: str, doc: str) -> None:
        out = self.tier.read_catchup(
            tenant, doc, token=self._token(handler),
            artifact_only=bool(params.get("artifactOnly")))
        if out.get("summary") is None and out.get("catchup") is None:
            _send_json(handler, 404, {"error": "no summary"})
            return
        _send_json(handler, 200, out)

    def _r_latest_summary(self, handler, params, tenant: str,
                          doc: str) -> None:
        tree = self.tier.read_summary_dict(
            tenant, doc, commit_sha=params.get("sha"),
            token=self._token(handler))
        if tree is None:
            _send_json(handler, 404, {"error": "no summary"})
            return
        _send_json(handler, 200, {"summary": tree})

    def _r_upload_summary(self, handler, params, tenant: str,
                          doc: str) -> None:
        body = _read_json(handler) or {}
        sha = self.tier.upload_summary(tenant, doc, body,
                                       token=self._token(handler))
        _send_json(handler, 201, {"sha": sha})

    def _r_versions(self, handler, params, tenant: str, doc: str) -> None:
        count = int(params.get("count", 1))
        _send_json(handler, 200, {"versions": self.tier.versions(
            tenant, doc, count, token=self._token(handler))})

    def _r_object(self, handler, params, tenant: str, doc: str,
                  sha: str) -> None:
        self._send_object(handler, tenant, doc, sha, kind=None)

    def _r_blob(self, handler, params, tenant: str, doc: str,
                sha: str) -> None:
        self._send_object(handler, tenant, doc, sha, kind="blob")

    def _r_tree(self, handler, params, tenant: str, doc: str,
                sha: str) -> None:
        self._send_object(handler, tenant, doc, sha, kind="tree")

    def _send_object(self, handler, tenant: str, doc: str, sha: str,
                     kind: Optional[str]) -> None:
        token = self._token(handler)
        self.tier.ensure_authorized(tenant, doc, token)
        wire = self.tier.get_object(tenant, doc, sha, token=token)
        if wire is None or (kind is not None and wire.get("kind") != kind):
            _send_json(handler, 404, {"error": f"no {kind or 'object'} "
                                               f"{sha!r}"})
            return
        _send_json(handler, 200, wire)

    def _r_ref(self, handler, params, tenant: str, doc: str,
               ref: str) -> None:
        sha = self.tier.get_ref(tenant, doc, ref,
                                token=self._token(handler))
        if sha is None:
            _send_json(handler, 404, {"error": f"no ref {ref!r}"})
            return
        _send_json(handler, 200, {"ref": ref, "sha": sha})


def _send_json(handler, status: int, payload: dict) -> None:
    body = json.dumps(payload).encode()
    handler.send_response(status)
    handler.send_header("Content-Type", "application/json")
    handler.send_header("Content-Length", str(len(body)))
    handler.end_headers()
    handler.wfile.write(body)


def _read_json(handler) -> Optional[dict]:
    length = int(handler.headers.get("Content-Length", 0))
    if not length:
        return None
    return json.loads(handler.rfile.read(length))


def main(argv=None) -> None:
    """Standalone entry: `python -m fluidframework_tpu.server.historian
    --upstream http://alfred:PORT` (proxy mode) or `--git var/git`
    (shared-storage mode)."""
    import argparse

    from .main import _wait_for_signal

    parser = argparse.ArgumentParser(
        prog="fluidframework_tpu.server.historian",
        description="Run the standalone summary-cache tier")
    parser.add_argument("--upstream", default=None,
                        help="alfred base URL (proxy mode)")
    parser.add_argument("--git", default=None,
                        help="shared git storage dir (store mode)")
    parser.add_argument("--host", default="127.0.0.1")
    parser.add_argument("--port", type=int, default=7081)
    parser.add_argument("--ref-ttl", type=float, default=2.0)
    parser.add_argument("--max-bytes", type=int, default=256 * 1024 * 1024)
    parser.add_argument("--monitor-port", type=int, default=0,
                        help="serve /health + /metrics here (0 = off)")
    args = parser.parse_args(argv)
    if (args.upstream is None) == (args.git is None):
        parser.error("exactly one of --upstream or --git is required")
    store = None
    if args.git is not None:
        from .durable import FileHistorian
        store = FileHistorian(args.git)
    service = HistorianService(upstream_url=args.upstream, store=store,
                               host=args.host, port=args.port,
                               max_bytes=args.max_bytes,
                               ref_ttl_s=args.ref_ttl)
    service.start()
    print(f"historian: serving cache tier on {service.url} "
          f"({'proxy' if args.upstream else 'store'} mode)", flush=True)
    monitor = None
    if args.monitor_port:
        from .monitor import ServiceMonitor
        monitor = ServiceMonitor(host=args.host, port=args.monitor_port)
        monitor.watch_historian("historian", service)
        monitor.start()
        print(f"historian: monitor on {monitor.url}", flush=True)
    _wait_for_signal()
    if monitor is not None:
        monitor.stop()
    service.stop()


if __name__ == "__main__":
    main()
