"""Read-tier serving state: the per-document catch-up artifact cache.

The write path got seven PRs of batching; this module is the read tier's
half of the first one (docs/read_path.md). A connecting client's catch-up
used to be summary + an op-tail replay — O(tail) work PER CLIENT. The
serving tier now maintains one constant-size artifact per document:

    { seq, msn, quorum snapshot, summary ref,
      clients: [wire ids], channels: [(store, channel, header, blob)] }

where each channel blob is the narrow int16 packed entry wire
(mergetree/catchup.py pack_entries_narrow) of that channel's full-fidelity
snapshot entries at `seq`. The artifact is refreshed from the per-lane
change generations at flush boundaries — ONE batched device dispatch per
refresh epoch covering every dirty document (TpuSequencerLambda
.catchup_snapshot) — so server cost scales with dirty docs, never with
connecting clients. Clients fetch summary + artifact in one round trip
(storage.get_catchup / the historian `/catchup` route), adopt, and replay
only the residue past `seq`.

Staleness contract (the adopter's side is loader/container.py):
  - an ABSENT artifact is a miss: the client falls back to tail replay.
  - a STALE artifact (seq behind the head) is still served and counted:
    adoption at `seq` plus residue replay is exactly as correct as a
    fresh artifact, just with a longer residue.
  - an artifact older than the summary the client loaded is useless and
    the CLIENT ignores it (the summary already covers more history).
Publishes ride LruTtlCache.put_if_newer keyed on `seq`, so a racing
refresh can never regress a fresher artifact.
"""

from __future__ import annotations

import json
from typing import Any, Dict, List, Optional, Tuple

from ..mergetree.catchup import (  # noqa: F401 — re-exported: this module
    pack_entries_narrow,           # OWNS the artifact format, so loader-
    translate_entry_clients,       # side adopters import the codec from
    unpack_entries_narrow,         # here (layering: loader may import
)                                  # server, not mergetree)
from ..telemetry import tracing
from ..telemetry import watermarks
from ..telemetry.counters import increment
from .cache import LruTtlCache


def artifact_nbytes(artifact: dict) -> int:
    """Cache-accounting size: the dominant term is the packed channel
    text + columns; JSON length over the whole artifact is close enough
    and computed once per publish."""
    try:
        return len(json.dumps(artifact))
    except (TypeError, ValueError):
        return 4096


class CatchupCache:
    """Bounded store of per-(tenant, document) catch-up artifacts.

    Counters (process-wide, /metrics.prom):
      catchup.delta_hit    reads served an artifact
      catchup.delta_miss   reads with no artifact (client tail-replays)
      catchup.delta_stale  hits whose artifact trails the current head
      catchup.published    artifacts (re)published
    """

    def __init__(self, max_entries: int = 65536,
                 max_bytes: int = 256 * 1024 * 1024,
                 ttl_s: Optional[float] = None,
                 partition_of=None):
        self.blobs = LruTtlCache(max_entries=max_entries,
                                 max_bytes=max_bytes, ttl_s=ttl_s)
        self.hits = 0
        self.misses = 0
        self.stale_hits = 0
        self.published = 0
        # doc_id -> ingest partition, for the catchup/adopted watermark
        # stamps (telemetry/watermarks.py). The cache itself is
        # partition-agnostic; owners that know the routing pass the
        # tier's partition_for so lag attributes to the right partition,
        # everyone else folds into partition 0.
        self.partition_of = partition_of or (lambda _doc: 0)

    def publish(self, tenant_id: str, document_id: str,
                artifact: dict) -> bool:
        """Write-through publish; loses quietly to a fresher artifact.
        Spanned (catchup.publish + the always-on histogram): the
        refresh epoch's per-doc publish cost attributes to a stage
        instead of hiding inside the epoch total."""
        with tracing.span("catchup.publish", hist="catchup.publish",
                          document=document_id) as sp:
            wrote = self.blobs.put_if_newer(
                (tenant_id, document_id), artifact,
                version=int(artifact["seq"]),
                nbytes=artifact_nbytes(artifact))
            if wrote:
                self.published += 1
                increment("catchup.published")
                # `catchup` watermark: ops up to the artifact's seq are
                # now adoptable in O(1) (per-doc high-water, replay-safe).
                # Default tenant key on purpose: every tier must stamp
                # the SAME tenant axis or the lag edges split — process
                # identity is the observatory's dimension, not tenant.
                watermarks.advance_doc(
                    watermarks.CATCHUP, self.partition_of(document_id),
                    document_id, int(artifact["seq"]))
            else:
                sp.set(lost_to_fresher=True)
        return wrote

    def get(self, tenant_id: str, document_id: str,
            head_seq: Optional[int] = None) -> Optional[dict]:
        """The read path: returns the artifact or None (miss). head_seq,
        when the caller knows it, classifies the hit as fresh/stale."""
        with tracing.span("catchup.get", hist="catchup.get",
                          document=document_id) as sp:
            held = self.blobs.get((tenant_id, document_id))
            if held is None:
                self.misses += 1
                increment("catchup.delta_miss")
                sp.set(miss=True)
                return None
            _version, artifact = held
            self.hits += 1
            increment("catchup.delta_hit")
            # `adopted` watermark: a served artifact is the adoption
            # frontier the read tier can vouch for (the loader-side swap
            # is client-local; the serve is the last server-visible hop).
            watermarks.advance_doc(
                watermarks.ADOPTED, self.partition_of(document_id),
                document_id, int(artifact["seq"]))
            if head_seq is not None and int(artifact["seq"]) < head_seq:
                self.stale_hits += 1
                increment("catchup.delta_stale")
                sp.set(stale=True)
        return artifact

    def peek_seq(self, tenant_id: str, document_id: str) -> Optional[int]:
        """Freshness probe without hit/miss accounting (the refresh-on-
        read gate must not skew the rates operators alert on)."""
        return self.blobs.peek_version((tenant_id, document_id))

    def invalidate(self, tenant_id: str, document_id: str) -> bool:
        return self.blobs.invalidate((tenant_id, document_id))

    def stats(self) -> dict:
        total = self.hits + self.misses
        return {
            "artifacts": len(self.blobs),
            "bytes": self.blobs.bytes,
            "hits": self.hits,
            "misses": self.misses,
            "staleHits": self.stale_hits,
            "hitRate": (self.hits / total) if total else 0.0,
            "published": self.published,
        }


class ArtifactPushThrough:
    """Worker-side catch-up refresh epochs for the MULTI-PROCESS
    topology (server/main.py `tpu-deli` stage). The in-process
    LocalServer joins sequencer snapshots with scribe checkpoints inside
    its own refresh_catchup; a deployed worker has no CatchupCache of
    its own — it builds the same artifacts and PUSHES them to the
    historian tier's `/historian/catchup` route, where connecting
    clients fetch summary + artifact in one round trip (the
    docs/read_path.md contract, now spanning processes).

    Epochs ride the worker's pump loop as a runner ticker (rate-limited
    to `interval_s`), each costing one batched device extraction over
    every dirty document together (TpuSequencerLambda.catchup_snapshot)
    — push cost scales with dirty docs per epoch, never with connecting
    clients. A doc whose scribe checkpoint trails the sequencer skips
    the epoch (stale-but-correct: its previous artifact keeps serving).
    The change generation is marked published ONLY after the publish
    callback reports success, so a dead historian leaves the doc dirty
    and the artifact retries next epoch instead of silently dropping."""

    def __init__(self, sequencers, scribe_checkpoints, historian,
                 tenant_id: str, publish, interval_s: float = 0.25,
                 clock=None):
        import time as _time

        self.sequencers = sequencers          # () -> live sequencer lambdas
        self.scribe_checkpoints = scribe_checkpoints
        self.historian = historian            # summary-ref source (get_ref)
        self.tenant_id = tenant_id
        self.publish = publish                # (tenant, doc, artifact) -> bool
        self.interval_s = float(interval_s)
        self.clock = clock or _time.monotonic
        self._last: Optional[float] = None
        self.epochs = 0
        self.published = 0
        self.skipped = 0
        self.failed = 0

    def pump(self, force: bool = False) -> int:
        """One rate-limited refresh epoch; returns artifacts pushed."""
        now = self.clock()
        if not force and self._last is not None \
                and now - self._last < self.interval_s:
            return 0
        self._last = now
        bodies: Dict[str, dict] = {}
        owner: Dict[str, Any] = {}
        for lam in self.sequencers():
            snap = getattr(lam, "catchup_snapshot", None)
            if snap is None:
                continue  # scalar deli: no lane state to extract from
            for doc_id, body in snap().items():
                bodies[doc_id] = body
                owner[doc_id] = lam
        if not bodies:
            return 0
        self.epochs += 1
        by_doc = {row["documentId"]: row
                  for row in self.scribe_checkpoints.find(
                      lambda d: d.get("documentId") in bodies)}
        pushed = 0
        for doc_id, body in bodies.items():
            row = by_doc.get(doc_id)
            if row is None or int(row["sequenceNumber"]) != body["seq"]:
                self.skipped += 1
                increment("catchup.publish_skipped")
                continue
            sha = self.historian.store(self.tenant_id,
                                       doc_id).get_ref("main")
            artifact = build_artifact(body, row["minimumSequenceNumber"],
                                      row["quorum"], sha)
            if self.publish(self.tenant_id, doc_id, artifact):
                pushed += 1
                self.published += 1
                increment("catchup.pushed")
                owner[doc_id].catchup_mark_published(doc_id, body["gen"])
            else:
                self.failed += 1
                increment("catchup.push_failed")
        return pushed

    def stats(self) -> dict:
        return {"epochs": self.epochs, "published": self.published,
                "skipped": self.skipped, "failed": self.failed}


def quorum_ordinals(quorum_snapshot: dict) -> Dict[str, int]:
    """wire client id -> quorum ordinal (its join sequence number) — the
    ordinal space a CLIENT's runtime uses for merge perspectives, derived
    from the same snapshot the artifact carries so the adopter and the
    protocol state can never disagree."""
    return {cid: int(m["sequenceNumber"])
            for cid, m in quorum_snapshot.get("members", [])}


def build_artifact(doc_body: dict, msn: int, quorum_snapshot: dict,
                   summary_sha: Optional[str]) -> dict:
    """Join a sequencer-side doc body (catchup_snapshot output: seq,
    clients, channels) with the protocol half into the published shape."""
    return {
        "v": 1,
        "seq": int(doc_body["seq"]),
        "msn": int(msn),
        "quorum": quorum_snapshot,
        "summarySha": summary_sha,
        "clients": list(doc_body["clients"]),
        "channels": doc_body["channels"],
    }
