"""Alfred: the network front door of the ordering service.

Capability parity with reference routerlicious-base Alfred
(`src/alfred/{app,runner,routes}`, socket handlers `connect_document`/
`submitOp` in `lambdas/src/alfred/index.ts:305-328`) plus the co-hosted
REST surfaces of Riddler (tenant CRUD/token validation,
`riddler/tenantManager.ts`) and historian/gitrest (git summary storage,
`server/historian`, `server/gitrest`). One `AlfredService` exposes:

  REST  GET  /api/v1/ping
        POST /documents/{tenant}                (create document)
        GET  /deltas/{tenant}/{doc}?from=&to=   (catch-up range query)
        POST /tenants/{tenant}                  (Riddler: create tenant)
        GET  /tenants/{tenant}/key              (Riddler: fetch secret)
        POST /tenants/{tenant}/validate         (Riddler: validate a JWT)
        POST /repos/{tenant}/{doc}/summaries    (upload summary tree)
        GET  /repos/{tenant}/{doc}/summaries/latest?sha=
        GET  /repos/{tenant}/{doc}/versions?count=
        GET  /repos/{tenant}/{doc}/git/commits?count=
  WS    GET  /socket  (upgrade)  -> connect_document / submitOp / op / nack

Behind the door each tenant gets a `LocalServer` core — the *real*
Deli/Scribe/Scriptorium/Broadcaster lambda pipeline (server/local_server.py)
— so the network path and the in-process test path exercise identical
sequencing code, exactly like the reference where LocalOrderer and the
Kafka deployment share lambda implementations.

The delta-stream wire protocol is JSON text frames:
  C->S {"type": "connect_document", "tenantId", "documentId", "token", "client"}
  S->C {"type": "connected", "clientId", "sequenceNumber"}
  C->S {"type": "submitOp", "messages": [DocumentMessage...]}
  C->S {"type": "submitSignal", "content": ...}   (transient; no sequencing)
  S->C {"type": "op", "message": SequencedDocumentMessage}
  S->C {"type": "nack", "nack": Nack}
  S->C {"type": "signal", "clientId", "content"}
"""

from __future__ import annotations

import hmac
import json
import re
import threading
import urllib.error
import urllib.parse
import urllib.request
import uuid
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Dict, Optional, Tuple

from ..protocol.summary import summary_tree_from_dict, summary_tree_to_dict
from ..telemetry import tracing
from ..telemetry.counters import increment, record_swallow
from .admission import admission_from_config
from .auth import AuthError, TenantManager
from .historian import TIER_HEADER, git_object_to_wire, notify_summary_commit
from .local_server import LocalServer
from .websocket import WebSocketClosed, upgrade_server_socket
from .wire import (
    document_message_from_dict,
    nack_to_dict,
    sequenced_message_to_dict,
)


class AlfredService:
    """The front-door service. Thread-safe; one instance serves many
    tenants/documents over one listening port."""

    def __init__(self, tenants: Optional[TenantManager] = None,
                 host: str = "127.0.0.1", port: int = 0,
                 require_auth: bool = True,
                 partitions: int = 1,
                 admin_key: Optional[str] = None,
                 config=None,
                 historian_url: Optional[str] = None):
        """config: the nconf-style provider handed to each tenant core
        (throttling, op-size ceiling, deli checkpoint/eviction knobs).

        historian_url: a standalone summary-cache tier
        (server/historian.py). When set, latest-summary reads delegate to
        it (unless the request came FROM the tier — TIER_HEADER marks
        those) and summary commits notify it for invalidation + warm
        prefetch. When unset or unreachable, git routes serve straight
        from the GitStore — the degradation path."""
        self.tenants = tenants or TenantManager()
        self.config = config
        self.historian_url = historian_url
        if self.historian_url is None and config is not None:
            self.historian_url = config.get("historian.url")
        self.require_auth = require_auth
        # Riddler's tenant CRUD/key routes are operator-only (the reference
        # deploys riddler on an internal network); when auth is on they
        # require this key in an X-Admin-Key header.
        self.admin_key = admin_key or uuid.uuid4().hex
        self.partitions = partitions
        self._cores: Dict[str, LocalServer] = {}
        self._cores_lock = threading.Lock()
        # ONE admission controller across every tenant core: overload is
        # a process-level condition (the cores share this process's CPU
        # and memory), and sharing the controller is what makes the
        # per-tenant credit split an actual fairness guarantee instead of
        # per-core honor system (server/admission.py).
        self.admission = admission_from_config(config)
        service = self

        class Handler(BaseHTTPRequestHandler):
            protocol_version = "HTTP/1.1"

            def log_message(self, fmt, *args):  # quiet
                pass

            def do_GET(self):
                if self.headers.get("Upgrade", "").lower() == "websocket":
                    service._handle_websocket(self)
                    self.close_connection = True
                    return
                service._handle_rest(self, "GET")

            def do_POST(self):
                service._handle_rest(self, "POST")

        self._httpd = ThreadingHTTPServer((host, port), Handler)
        self._httpd.daemon_threads = True
        self.host, self.port = self._httpd.server_address
        self._thread: Optional[threading.Thread] = None

    # -- lifecycle ---------------------------------------------------------
    def start(self) -> "AlfredService":
        self._thread = threading.Thread(target=self._httpd.serve_forever,
                                        name="alfred", daemon=True)
        self._thread.start()
        return self

    def stop(self) -> None:
        self._httpd.shutdown()
        self._httpd.server_close()
        if self._thread:
            self._thread.join(timeout=5)

    @property
    def url(self) -> str:
        return f"http://{self.host}:{self.port}"

    def attach_historian(self, historian_url: Optional[str]) -> None:
        """Point this alfred at a summary-cache tier after construction
        (the tier usually needs alfred's URL first, so the wiring is
        two-phase). Existing cores gain the commit notifier too."""
        # Atomic reference publish of an immutable endpoint string: the
        # HTTP request threads read it lock-free and tolerate either
        # epoch (a request raced with attachment simply serves direct).
        # fluidlint: disable=SHARED_STATE_NO_LOCK — single-writer
        # publish of an immutable str; readers tolerate either epoch
        self.historian_url = historian_url
        if historian_url:
            with self._cores_lock:
                for tenant_id, core in self._cores.items():
                    self._register_commit_notifier(core, tenant_id)

    def _register_commit_notifier(self, core: LocalServer,
                                  tenant_id: str) -> None:
        # Scribe-acked commits advance refs in-process; the cache tier
        # must hear about them (invalidate + warm prefetch).
        core.summary_commit_listeners.append(
            lambda doc_id, sha, t=tenant_id:
            self.historian_url and notify_summary_commit(
                self.historian_url, t, doc_id, sha))

    def core(self, tenant_id: str) -> LocalServer:
        """The per-tenant ordering core (lazily created)."""
        with self._cores_lock:
            if tenant_id not in self._cores:
                core = LocalServer(
                    tenant_id=tenant_id, partitions=self.partitions,
                    config=self.config, admission=self.admission)
                if self.historian_url:
                    self._register_commit_notifier(core, tenant_id)
                self._cores[tenant_id] = core
            return self._cores[tenant_id]

    # -- auth --------------------------------------------------------------
    def _check_auth(self, handler, tenant_id: str,
                    document_id: Optional[str], scope: Optional[str],
                    token: Optional[str] = None) -> Optional[dict]:
        """Returns claims (or {} when auth is off); None after sending an
        error response."""
        if not self.require_auth:
            return {}
        if token is None:
            auth = handler.headers.get("Authorization", "")
            if auth.startswith("Bearer "):
                token = auth[len("Bearer "):]
        if not token:
            _send_json(handler, 401, {"error": "missing token"})
            return None
        try:
            return self.tenants.validate_token(tenant_id, token,
                                               document_id, scope)
        except AuthError as exc:
            _send_json(handler, 403, {"error": str(exc)})
            return None

    def _validate_ws_token(self, tenant_id: str, document_id: str,
                           token: Optional[str]) -> Optional[str]:
        """Returns an error string or None if admitted."""
        if not self.require_auth:
            return None
        if not token:
            return "missing token"
        try:
            self.tenants.validate_token(tenant_id, token, document_id,
                                        "doc:write")
            return None
        except AuthError as exc:
            return str(exc)

    # -- REST --------------------------------------------------------------
    _ROUTES = [
        ("GET", re.compile(r"^/api/v1/ping$"), "_r_ping"),
        ("GET", re.compile(
            r"^/api/v1/session/(?P<tenant>[^/]+)/(?P<doc>[^/]+)$"),
         "_r_join_session"),
        ("POST", re.compile(r"^/documents/(?P<tenant>[^/]+)$"), "_r_create_doc"),
        ("GET", re.compile(r"^/documents/(?P<tenant>[^/]+)/(?P<doc>[^/]+)$"),
         "_r_get_doc"),
        ("GET", re.compile(
            r"^/deltas/raw/(?P<tenant>[^/]+)/(?P<doc>[^/]+)$"),
         "_r_raw_deltas"),
        ("POST", re.compile(
            r"^/api/(?P<tenant>[^/]+)/(?P<doc>[^/]+)/blobs$"),
         "_r_create_blob"),
        ("GET", re.compile(r"^/deltas/(?P<tenant>[^/]+)/(?P<doc>[^/?]+)$"),
         "_r_deltas"),
        ("POST", re.compile(r"^/tenants/(?P<tenant>[^/]+)/validate$"),
         "_r_validate"),
        ("GET", re.compile(r"^/tenants/(?P<tenant>[^/]+)/key$"), "_r_key"),
        ("POST", re.compile(r"^/tenants/(?P<tenant>[^/]+)$"), "_r_create_tenant"),
        ("POST", re.compile(
            r"^/repos/(?P<tenant>[^/]+)/(?P<doc>[^/]+)/summaries$"),
         "_r_upload_summary"),
        ("GET", re.compile(
            r"^/repos/(?P<tenant>[^/]+)/(?P<doc>[^/]+)/summaries/latest$"),
         "_r_latest_summary"),
        ("GET", re.compile(
            r"^/repos/(?P<tenant>[^/]+)/(?P<doc>[^/]+)/versions$"),
         "_r_versions"),
        ("GET", re.compile(
            r"^/repos/(?P<tenant>[^/]+)/(?P<doc>[^/]+)/git/commits$"),
         "_r_commits"),
        # gitrest object surface (what the historian tier proxies).
        ("GET", re.compile(
            r"^/repos/(?P<tenant>[^/]+)/(?P<doc>[^/]+)"
            r"/git/objects/(?P<sha>[^/]+)$"),
         "_r_git_object"),
        ("GET", re.compile(
            r"^/repos/(?P<tenant>[^/]+)/(?P<doc>[^/]+)"
            r"/git/blobs/(?P<sha>[^/]+)$"),
         "_r_git_blob"),
        ("GET", re.compile(
            r"^/repos/(?P<tenant>[^/]+)/(?P<doc>[^/]+)"
            r"/git/trees/(?P<sha>[^/]+)$"),
         "_r_git_tree"),
        ("GET", re.compile(
            r"^/repos/(?P<tenant>[^/]+)/(?P<doc>[^/]+)"
            r"/git/refs/(?P<ref>.+)$"),
         "_r_git_ref"),
    ]

    def _handle_rest(self, handler, method: str) -> None:
        path, _, query = handler.path.partition("?")
        params = _parse_query(query)
        for route_method, pattern, name in self._ROUTES:
            if route_method != method:
                continue
            m = pattern.match(path)
            if m:
                # Path params arrive percent-encoded (the driver encodes
                # ids); decode so REST and websocket paths key identically.
                groups = {k: urllib.parse.unquote(v)
                          for k, v in m.groupdict().items()}
                try:
                    getattr(self, name)(handler, params, **groups)
                except BrokenPipeError:
                    record_swallow("alfred.client_gone")
                except Exception as exc:  # route bug -> 500, keep serving
                    increment("alfred.route_errors")
                    try:
                        _send_json(handler, 500, {"error": repr(exc)})
                    except OSError:  # reply socket died mid-error
                        record_swallow("alfred.route_reply")
                return
        _send_json(handler, 404, {"error": f"no route {method} {path}"})

    def _r_ping(self, handler, params) -> None:
        _send_json(handler, 200, {"ok": True})

    def _r_join_session(self, handler, params, tenant: str,
                        doc: str) -> None:
        """Session discovery (the odsp-driver joinSession flow,
        odsp-driver/src: fetch the socket endpoint before connecting):
        returns where the delta-stream socket for this document lives and
        how long the discovery may be cached. One alfred serves every
        document here, but the indirection is the contract that lets a
        deployment shard documents across socket front-ends."""
        claims = self._check_auth(handler, tenant, doc, "doc:read")
        if claims is None:
            return
        # Advertise a host the CLIENT can dial: the bind address is useless
        # when alfred listens on a wildcard, so prefer what the client
        # already reached us by (its Host header).
        host = self.host
        if host in ("0.0.0.0", "::", ""):
            req_host = handler.headers.get("Host", "")
            if req_host.startswith("["):  # [v6]:port or bare [v6]
                req_host = req_host.partition("]")[0].lstrip("[")
            elif ":" in req_host:
                req_host = req_host.rsplit(":", 1)[0]
            host = req_host or "127.0.0.1"
        _send_json(handler, 200, {
            "socketHost": host,
            "socketPort": self.port,
            "socketPath": "/socket-mux",
            "tenantId": tenant,
            "documentId": doc,
            "sessionExpiryMs": 600_000,
        })

    def _check_admin(self, handler) -> bool:
        """Operator gate for riddler routes. Sends the error response when
        rejecting."""
        if not self.require_auth:
            return True
        supplied = handler.headers.get("X-Admin-Key", "")
        if hmac.compare_digest(supplied, self.admin_key):
            return True
        _send_json(handler, 403, {"error": "admin key required"})
        return False

    def _r_create_tenant(self, handler, params, tenant: str) -> None:
        if not self._check_admin(handler):
            return
        body = _read_json(handler) or {}
        try:
            t = self.tenants.create_tenant(tenant, key=body.get("key"))
        except ValueError as exc:
            _send_json(handler, 409, {"error": str(exc)})
            return
        _send_json(handler, 201, {"id": t.id, "key": t.key})

    def _r_key(self, handler, params, tenant: str) -> None:
        if not self._check_admin(handler):
            return
        try:
            key = self.tenants.get_key(tenant)
        except AuthError as exc:
            _send_json(handler, 404, {"error": str(exc)})
            return
        _send_json(handler, 200, {"key": key})

    def _r_validate(self, handler, params, tenant: str) -> None:
        body = _read_json(handler) or {}
        try:
            claims = self.tenants.validate_token(tenant, body.get("token", ""))
        except AuthError as exc:
            _send_json(handler, 403, {"error": str(exc)})
            return
        _send_json(handler, 200, {"claims": claims})

    def _r_create_doc(self, handler, params, tenant: str) -> None:
        body = _read_json(handler) or {}
        doc_id = body.get("id") or f"doc-{uuid.uuid4().hex[:12]}"
        # The token must be bound to the document being created (or be a
        # wildcard token) — a docA-scoped token must not create/overwrite
        # docB's attach summary.
        claims = self._check_auth(handler, tenant, doc_id, "doc:write")
        if claims is None:
            return
        core = self.core(tenant)
        # Existence registry: a summary-less create must still be readable
        # back immediately (create-then-GET consistency).
        core.db.collection("documents").upsert(
            lambda d, _id=doc_id: d.get("documentId") == _id,
            {"documentId": doc_id, "tenantId": tenant})
        if body.get("summary") is not None:
            store = core.storage(doc_id)
            if store.get_ref("main") is not None:
                # The document already has a load target; repointing it at
                # a fresh attach summary would orphan the existing history.
                _send_json(handler, 409,
                           {"error": f"document {doc_id!r} exists"})
                return
            # Attach-with-summary: the initial summary becomes the load
            # target immediately (no scribe ack needed for attach).
            tree = summary_tree_from_dict(body["summary"])
            store.write_summary(tree, message="attach", advance_ref=True)
        _send_json(handler, 201, {"id": doc_id})

    def _r_deltas(self, handler, params, tenant: str, doc: str) -> None:
        claims = self._check_auth(handler, tenant, doc, "doc:read")
        if claims is None:
            return
        from_seq = int(params.get("from", 0))
        to_seq = int(params["to"]) if "to" in params else None
        rows = self.core(tenant).get_deltas(doc, from_seq, to_seq)
        _send_json(handler, 200, {"deltas": rows})

    def _r_get_doc(self, handler, params, tenant: str, doc: str) -> None:
        """Document existence + metadata (reference alfred
        routes/api/documents.ts:14 getDocument)."""
        claims = self._check_auth(handler, tenant, doc, "doc:read")
        if claims is None:
            return
        core = self.core(tenant)
        head = core.storage(doc).get_ref("main")
        seq = core.sequence_number(doc)
        registered = core.db.collection("documents").find_one(
            lambda d: d.get("documentId") == doc) is not None
        if head is None and seq == 0 and not registered:
            _send_json(handler, 404, {"error": f"document {doc!r} not found"})
            return
        _send_json(handler, 200, {
            "id": doc, "tenantId": tenant, "sequenceNumber": seq,
            "headSummary": head})

    def _r_raw_deltas(self, handler, params, tenant: str, doc: str) -> None:
        """Raw (pre-sequencing) op stream persisted by the copier
        (reference alfred routes/api/deltas.ts:183 /deltas/raw)."""
        claims = self._check_auth(handler, tenant, doc, "doc:read")
        if claims is None:
            return
        core = self.core(tenant)
        from_off = int(params.get("from", -1))
        limit = int(params.get("limit", 1000))
        rows = core.raw_deltas.find(
            lambda d: d.get("documentId") == doc
            and d.get("offset", 0) > from_off)
        rows.sort(key=lambda d: d.get("offset", 0))
        _send_json(handler, 200, {"rawDeltas": rows[:limit]})

    def _r_create_blob(self, handler, params, tenant: str,
                       doc: str) -> None:
        """Attachment blob upload (reference alfred api.ts:59 createBlob):
        content-addressed into the document's git store; the returned sha
        is referenced from summaries/ops as an attachment handle."""
        claims = self._check_auth(handler, tenant, doc, "doc:write")
        if claims is None:
            return
        body = _read_json(handler) or {}
        content = body.get("content")
        if not isinstance(content, str):
            _send_json(handler, 400, {"error": "content (base64) required"})
            return
        import base64
        try:
            raw = base64.b64decode(content, validate=True)
        except ValueError:  # binascii.Error: malformed payload
            _send_json(handler, 400, {"error": "content is not base64"})
            return
        sha = self.core(tenant).storage(doc).put_blob(raw)
        _send_json(handler, 201, {"sha": sha, "size": len(raw),
                                  "url": f"/blobs/{tenant}/{doc}/{sha}"})

    def _r_upload_summary(self, handler, params, tenant: str,
                          doc: str) -> None:
        claims = self._check_auth(handler, tenant, doc, "summary:write")
        if claims is None:
            return
        body = _read_json(handler) or {}
        tree = summary_tree_from_dict(body["summary"])
        store = self.core(tenant).storage(doc)
        initial = bool(body.get("initial"))
        if initial and store.get_ref("main") is not None:
            # Same guard as create: only the attach of a NEW document may
            # set the load target directly; later summaries are proposals
            # that scribe acks (advance_ref stays False for them).
            _send_json(handler, 409, {"error": f"document {doc!r} exists"})
            return
        sha = store.write_summary(tree, base_commit=body.get("parent"),
                                  advance_ref=initial)
        if self.historian_url and not handler.headers.get(TIER_HEADER):
            # Direct upload bypassed the cache tier: tell it the commit
            # landed so a stale latest pointer never outlives this write
            # (and the new tree warms ahead of the scribe ack).
            notify_summary_commit(self.historian_url, tenant, doc, sha)
        _send_json(handler, 201, {"sha": sha})

    def _r_latest_summary(self, handler, params, tenant: str,
                          doc: str) -> None:
        claims = self._check_auth(handler, tenant, doc, "doc:read")
        if claims is None:
            return
        if (self.historian_url and not handler.headers.get(TIER_HEADER)
                and self._delegate_latest(handler, params, tenant, doc)):
            return
        core = self.core(tenant)
        tree = core.historian.read_summary(tenant, doc,
                                           commit_sha=params.get("sha"))
        if tree is None:
            _send_json(handler, 404, {"error": "no summary"})
            return
        _send_json(handler, 200, {"summary": summary_tree_to_dict(tree)})

    def _delegate_latest(self, handler, params, tenant: str,
                         doc: str) -> bool:
        """Serve the latest-summary read through the historian tier.
        Returns True when a response was sent; False (historian down)
        lets the caller fall back to the direct GitStore path."""
        path = (f"/repos/{urllib.parse.quote(tenant, safe='')}"
                f"/{urllib.parse.quote(doc, safe='')}/summaries/latest")
        if "sha" in params:
            path += "?sha=" + urllib.parse.quote(params["sha"], safe="")
        req = urllib.request.Request(self.historian_url.rstrip("/") + path)
        auth = handler.headers.get("Authorization")
        if auth:
            req.add_header("Authorization", auth)
        try:
            with urllib.request.urlopen(req, timeout=30.0) as resp:
                _send_json(handler, resp.status,
                           json.loads(resp.read() or b"{}"))
            return True
        except urllib.error.HTTPError as exc:
            if exc.code == 503:
                return False  # tier's own upstream is down: serve direct
            _send_json(handler, exc.code, _error_payload(exc))
            return True
        except OSError:
            return False

    def _r_versions(self, handler, params, tenant: str, doc: str) -> None:
        claims = self._check_auth(handler, tenant, doc, "doc:read")
        if claims is None:
            return
        count = int(params.get("count", 1))
        shas = [c.sha for c in
                self.core(tenant).storage(doc).list_commits(limit=count)]
        _send_json(handler, 200, {"versions": shas})

    def _r_commits(self, handler, params, tenant: str, doc: str) -> None:
        claims = self._check_auth(handler, tenant, doc, "doc:read")
        if claims is None:
            return
        count = int(params.get("count", 10))
        commits = self.core(tenant).storage(doc).list_commits(limit=count)
        _send_json(handler, 200, {"commits": [
            {"sha": c.sha, "tree": c.tree_sha, "parents": c.parents,
             "message": c.message, "timestamp": c.timestamp}
            for c in commits]})

    # -- gitrest object surface (consumed by server/historian.py) ----------
    def _r_git_object(self, handler, params, tenant: str, doc: str,
                      sha: str) -> None:
        self._send_git_object(handler, tenant, doc, sha, kind=None)

    def _r_git_blob(self, handler, params, tenant: str, doc: str,
                    sha: str) -> None:
        self._send_git_object(handler, tenant, doc, sha, kind="blob")

    def _r_git_tree(self, handler, params, tenant: str, doc: str,
                    sha: str) -> None:
        self._send_git_object(handler, tenant, doc, sha, kind="tree")

    def _send_git_object(self, handler, tenant: str, doc: str, sha: str,
                         kind: Optional[str]) -> None:
        claims = self._check_auth(handler, tenant, doc, "doc:read")
        if claims is None:
            return
        obj = self.core(tenant).storage(doc).get(sha)
        if obj is None:
            _send_json(handler, 404,
                       {"error": f"no object {sha!r}"})
            return
        wire = git_object_to_wire(obj)
        if kind is not None and wire.get("kind") != kind:
            _send_json(handler, 404,
                       {"error": f"object {sha!r} is a "
                                 f"{wire.get('kind')}, not a {kind}"})
            return
        _send_json(handler, 200, wire)

    def _r_git_ref(self, handler, params, tenant: str, doc: str,
                   ref: str) -> None:
        claims = self._check_auth(handler, tenant, doc, "doc:read")
        if claims is None:
            return
        sha = self.core(tenant).storage(doc).get_ref(ref)
        if sha is None:
            _send_json(handler, 404, {"error": f"no ref {ref!r}"})
            return
        _send_json(handler, 200, {"ref": ref, "sha": sha})

    # -- websocket delta stream -------------------------------------------
    def _handle_websocket(self, handler) -> None:
        key = handler.headers.get("Sec-WebSocket-Key")
        if not key:
            _send_json(handler, 400, {"error": "bad upgrade"})
            return
        handler.wfile.flush()
        if handler.path.partition("?")[0] == "/socket-mux":
            self._handle_websocket_mux(handler, key)
            return
        ws = upgrade_server_socket(handler.connection, key)
        conn = None
        try:
            # First message must be connect_document.
            hello = json.loads(ws.recv())
            if hello.get("type") != "connect_document":
                ws.send_text(json.dumps(
                    {"type": "error", "error": "expected connect_document"}))
                return
            tenant_id = hello.get("tenantId", "")
            document_id = hello.get("documentId", "")
            err = self._validate_ws_token(tenant_id, document_id,
                                          hello.get("token"))
            if err is not None:
                ws.send_text(json.dumps({"type": "error", "error": err}))
                return
            core = self.core(tenant_id)
            conn = core.connect(document_id, hello.get("client"))

            def on_op(msg, ws=ws):
                try:
                    ws.send_text(json.dumps(
                        {"type": "op",
                         "message": sequenced_message_to_dict(msg)}))
                except (OSError, WebSocketClosed):
                    pass  # reader loop will notice the dead socket

            def on_nack(nack, ws=ws):
                try:
                    ws.send_text(json.dumps(
                        {"type": "nack", "nack": nack_to_dict(nack)}))
                except (OSError, WebSocketClosed):
                    pass

            def on_signal(sig, ws=ws):
                try:
                    ws.send_text(json.dumps(
                        {"type": "signal", "clientId": sig.client_id,
                         "content": sig.content}))
                except (OSError, WebSocketClosed):
                    pass

            conn.on("op", on_op)
            conn.on("nack", on_nack)
            conn.on("signal", on_signal)
            ws.send_text(json.dumps({
                "type": "connected",
                "clientId": conn.client_id,
                "sequenceNumber": core.sequence_number(document_id),
            }))
            while True:
                msg = json.loads(ws.recv())
                mtype = msg.get("type")
                if mtype == "submitOp":
                    messages = [document_message_from_dict(d)
                                for d in msg.get("messages", [])]
                    oversized = _oversized_of(messages, core.max_op_bytes)
                    if oversized is not None:
                        on_nack(oversized)
                    else:
                        # Network ingest hop: the wire context (stamped
                        # by the driver into metadata) parents alfred's
                        # span, and the in-process pipeline nests below.
                        with tracing.span(
                                "alfred.ingest",
                                parent=tracing.first_message_context(
                                    messages),
                                document=document_id):
                            conn.submit(messages)
                elif mtype == "submitSignal":
                    conn.submit_signal(msg.get("content"))
                elif mtype == "disconnect":
                    break
                else:
                    ws.send_text(json.dumps(
                        {"type": "error",
                         "error": f"unknown message {mtype!r}"}))
        except (WebSocketClosed, OSError, json.JSONDecodeError):
            pass
        finally:
            if conn is not None:
                conn.disconnect()
            ws.close()

    def _handle_websocket_mux(self, handler, key: str) -> None:
        """Multiplexed delta stream: many documents share ONE websocket
        (the odsp-driver socket-reference pattern — one physical socket per
        endpoint, documents keyed by a client-chosen connection id `cid`).
        Frames are the legacy protocol plus a `cid` field; per-document
        errors answer on the cid instead of killing the shared socket.

          C->S {"type": "connect_document", "cid", "tenantId",
                "documentId", "token", "client"}
          S->C {"type": "connected", "cid", "clientId", "sequenceNumber"}
          S->C {"type": "connect_error", "cid", "error"}
          C->S {"type": "submitOp"|"submitSignal", "cid", ...}
          C->S {"type": "disconnect_document", "cid"}
          C->S {"type": "disconnect"}   (closes every document + socket)
        """
        ws = upgrade_server_socket(handler.connection, key)
        conns: Dict[int, object] = {}

        def send(payload: dict) -> None:
            try:
                ws.send_text(json.dumps(payload))
            except (OSError, WebSocketClosed):
                pass  # reader loop will notice the dead socket

        try:
            while True:
                msg = json.loads(ws.recv())
                if msg.get("type") == "disconnect":
                    break
                try:
                    self._handle_mux_frame(msg, conns, send)
                except (WebSocketClosed, OSError):
                    raise  # transport dead: tear the socket down
                except Exception as exc:  # noqa: BLE001 — isolate per doc
                    # One document's bad frame must never kill the shared
                    # socket for its siblings: answer on the cid.
                    increment("alfred.mux_frame_errors")
                    send({"type": "error", "cid": msg.get("cid"),
                          "error": repr(exc)})
        except (WebSocketClosed, OSError, json.JSONDecodeError):
            pass
        finally:
            for conn in conns.values():
                conn.disconnect()
            ws.close()

    def _handle_mux_frame(self, msg: dict, conns: Dict,
                          send) -> None:
        mtype = msg.get("type")
        if mtype == "connect_document":
            cid = msg.get("cid")
            tenant_id = msg.get("tenantId", "")
            document_id = msg.get("documentId", "")
            err = self._validate_ws_token(tenant_id, document_id,
                                          msg.get("token"))
            if err is not None:
                send({"type": "connect_error", "cid": cid, "error": err})
                return
            if cid in conns:
                send({"type": "connect_error", "cid": cid,
                      "error": "cid already connected"})
                return
            try:
                core = self.core(tenant_id)
                conn = core.connect(document_id, msg.get("client"))
            except Exception as exc:  # noqa: BLE001 — fail the handshake
                increment("alfred.connect_errors")
                # Answer with connect_error, not the generic error frame:
                # the client routes only connect_error/connected to the
                # pending handshake, so anything else leaves
                # connect_document blocked for its full timeout.
                send({"type": "connect_error", "cid": cid,
                      "error": repr(exc)})
                return
            conns[cid] = conn
            conn.on("op", lambda m, c=cid: send(
                {"type": "op", "cid": c,
                 "message": sequenced_message_to_dict(m)}))
            conn.on("nack", lambda n, c=cid: send(
                {"type": "nack", "cid": c, "nack": nack_to_dict(n)}))
            conn.on("signal", lambda s, c=cid: send(
                {"type": "signal", "cid": c,
                 "clientId": s.client_id, "content": s.content}))
            send({"type": "connected", "cid": cid,
                  "clientId": conn.client_id,
                  "sequenceNumber": core.sequence_number(document_id)})
            return
        cid = msg.get("cid")
        conn = conns.get(cid)
        if conn is None:
            send({"type": "error", "cid": cid,
                  "error": f"unknown cid {cid!r}"})
            return
        if mtype == "submitOp":
            messages = [document_message_from_dict(d)
                        for d in msg.get("messages", [])]
            oversized = _oversized_of(messages,
                                      self.core(conn.tenant_id)
                                      .max_op_bytes)
            if oversized is not None:
                send({"type": "nack", "cid": cid,
                      "nack": nack_to_dict(oversized)})
            else:
                with tracing.span(
                        "alfred.ingest",
                        parent=tracing.first_message_context(messages),
                        document=conn.document_id):
                    conn.submit(messages)
        elif mtype == "submitSignal":
            conn.submit_signal(msg.get("content"))
        elif mtype == "disconnect_document":
            conns.pop(cid).disconnect()
            send({"type": "document_disconnected", "cid": cid})
        else:
            send({"type": "error", "cid": cid,
                  "error": f"unknown message {mtype!r}"})


def _oversized_of(messages, limit: int):
    """Exact wire-side size screen: the Nack for the first message over
    the ceiling, or None when all fit (reference alfred maxMessageSize)."""
    from ..protocol.messages import (Nack, NackContent, NACK_TOO_LARGE,
                                     op_size_exact)
    if not limit:
        return None
    for m in messages:
        if op_size_exact(m) > limit:
            return Nack(m, -1, NackContent(
                NACK_TOO_LARGE, f"op exceeds {limit} bytes"))
    return None


def _error_payload(exc: urllib.error.HTTPError) -> dict:
    try:
        return json.loads(exc.read() or b"{}")
    except (ValueError, OSError):
        return {"error": f"historian HTTP {exc.code}"}


def _send_json(handler, status: int, payload: dict) -> None:
    body = json.dumps(payload).encode()
    handler.send_response(status)
    handler.send_header("Content-Type", "application/json")
    handler.send_header("Content-Length", str(len(body)))
    handler.end_headers()
    handler.wfile.write(body)


def _read_json(handler) -> Optional[dict]:
    length = int(handler.headers.get("Content-Length", 0))
    if not length:
        return None
    return json.loads(handler.rfile.read(length))


def _parse_query(query: str) -> Dict[str, str]:
    return {name: values[-1]
            for name, values in urllib.parse.parse_qs(query).items()}
