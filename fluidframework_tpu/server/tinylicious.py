"""Tinylicious: the single-process dev ordering service.

Capability parity with reference server/tinylicious
(`src/{app,routes,services}`): everything a developer needs on one port —
alfred REST + websocket delta stream + historian storage routes + an open
default tenant — with zero external services. Auth is optional (the
reference tinylicious accepts any token); pass require_auth=True to get
production riddler behavior with the well-known dev key.
"""

from __future__ import annotations

from typing import Optional

from .alfred import AlfredService
from .auth import TenantManager, generate_token

DEFAULT_TENANT = "tinylicious"
DEFAULT_KEY = "12345"  # well-known dev key, like the reference's fixed key


class Tinylicious:
    """One-call dev server: `with Tinylicious() as t: ...` or
    start()/stop()."""

    def __init__(self, host: str = "127.0.0.1", port: int = 0,
                 require_auth: bool = False, partitions: int = 1,
                 admin_key: Optional[str] = None, config=None):
        self.tenants = TenantManager()
        self.tenants.create_tenant(DEFAULT_TENANT, key=DEFAULT_KEY)
        self.service = AlfredService(self.tenants, host=host, port=port,
                                     require_auth=require_auth,
                                     partitions=partitions,
                                     admin_key=admin_key, config=config)

    @property
    def admin_key(self) -> str:
        return self.service.admin_key

    def attach_historian(self, historian_url: Optional[str]) -> None:
        """Wire a summary-cache tier (server/historian.py) in front of
        this server's git storage: latest-summary reads delegate to it
        and scribe-acked commits notify it."""
        self.service.attach_historian(historian_url)

    def start(self) -> "Tinylicious":
        self.service.start()
        return self

    def stop(self) -> None:
        self.service.stop()

    def __enter__(self) -> "Tinylicious":
        return self.start()

    def __exit__(self, *exc) -> None:
        self.stop()

    @property
    def url(self) -> str:
        return self.service.url

    @property
    def port(self) -> int:
        return self.service.port

    def token_provider(self, tenant_id: Optional[str] = None):
        """A TokenProvider for the dev tenant (or any registered tenant)."""
        tid = tenant_id or DEFAULT_TENANT
        key = self.tenants.get_key(tid)

        def provider(tenant: str, document_id: str) -> str:
            return generate_token(key, tenant, document_id)

        return provider
