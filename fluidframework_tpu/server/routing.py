"""Restart-stable document routing shared by every sharded tier.

One hash, two consumers: the broadcaster's fan-out shards
(server/lambdas/broadcaster.py) and the ingest tier's partition router
(server/sharding.py) both assign a document a "home" by the SAME md5
scheme, so the two tiers can never disagree about where a document's
traffic lives — the broadcast shard draining a document's deliveries is
always derivable from the partition sequencing it (and vice versa) by
taking the digest modulo the respective shard count.

md5 rather than ``hash()`` because Python's string hash is seeded per
process: a restart would re-home every document, breaking per-document
ordering for durable logs and run-twice determinism in the soak suite.
md5 rather than crc32 (the broker's internal key hash) because the md5
scheme is the one the broadcaster shipped with (docs/read_path.md) and
re-homing broadcast shards to match the broker would invalidate
existing shard-affinity expectations; the ingest tier instead produces
to EXPLICIT partitions (`MessageLog.send_to`) so the broker's own key
hash never routes a sharded tenant's documents.

Dependency-free (stdlib only): imported by lambdas and the server tier
alike without cycles.
"""

from __future__ import annotations

import hashlib
import threading
from typing import Dict


def doc_shard(document_id: str, shards: int) -> int:
    """The stable home of a document among ``shards`` slots.

    Little-endian first 4 digest bytes, modulo the slot count — byte
    order is pinned so the mapping is identical across hosts and
    restarts (run-twice determinism; see the broadcaster's routing
    stability tests in tests/test_broadcaster.py)."""
    if shards <= 1:
        return 0
    digest = hashlib.md5(str(document_id).encode()).digest()
    return int.from_bytes(digest[:4], "little") % shards


class PartitionRouter:
    """Doc -> ingest-partition routing for one topic's partition count.

    The BASE mapping is restart-stable by construction (pure function of
    the document id and the partition count). On top of it sits a
    routing-EPOCH override table for live rebalancing
    (docs/ingest_sharding.md): `install_override(doc, partition)` bumps
    the epoch and re-homes ONE document's raw-topic traffic without
    touching anything else — the sharded ingest tier
    (server/sharding.py SequencerShardSet.rebalance_doc) pairs the bump
    with an explicit handoff record on the source partition, so
    ownership transfers with no drain-to-barrier fleet restart.
    Overrides apply to the RAW (sequencing-input) side only; emit-side
    routing (deltas/broadcast) stays on `base_partition_for`, so a
    document's output stream never changes partitions and per-doc
    delivery order is total within one partition by construction.

    Changing the partition COUNT still re-homes (1 - 1/N) of documents
    and keeps the drain-to-a-checkpoint-barrier procedure, exactly like
    a Kafka repartition."""

    def __init__(self, partitions: int):
        self.partitions = max(1, int(partitions))
        self.epoch = 0
        self._overrides: Dict[str, int] = {}
        self._lock = threading.Lock()

    def base_partition_for(self, document_id: str) -> int:
        """The epoch-0 md5 home — rebalance-invariant; the emit-side
        (deltas/broadcast) routing anchor."""
        return doc_shard(document_id, self.partitions)

    def partition_for(self, document_id: str) -> int:
        """The document's CURRENT raw-side owner (override-aware)."""
        with self._lock:
            override = self._overrides.get(document_id)
        if override is not None:
            return override
        return doc_shard(document_id, self.partitions)

    def install_override(self, document_id: str, partition: int) -> int:
        """Re-home one document's raw traffic; returns the new routing
        epoch. Atomic w.r.t. partition_for: a submit either routes by
        the old owner (and is sequenced before the handoff marker the
        tier appends AFTER this bump) or by the new one."""
        if not 0 <= int(partition) < self.partitions:
            raise ValueError(
                f"override partition {partition} out of range "
                f"[0, {self.partitions})")
        with self._lock:
            self.epoch += 1
            self._overrides[str(document_id)] = int(partition)
            return self.epoch

    def overrides_targeting(self, partition: int) -> list:
        """Documents whose CURRENT override homes them on `partition` —
        the build-time seed for a partition's awaiting-adoption set."""
        with self._lock:
            return sorted(doc for doc, p in self._overrides.items()
                          if p == int(partition))

    def snapshot(self) -> dict:
        """Persistable override state (the tier stores it in the shared
        checkpoint collection so a restarted process re-derives the same
        routes — restart stability now includes live-rebalance moves)."""
        with self._lock:
            return {"epoch": self.epoch, "overrides": dict(self._overrides)}

    def restore(self, dump: dict) -> None:
        with self._lock:
            self.epoch = max(self.epoch, int(dump.get("epoch", 0)))
            for doc, p in dict(dump.get("overrides", {})).items():
                if 0 <= int(p) < self.partitions:
                    self._overrides[str(doc)] = int(p)

    def assignment(self, document_ids) -> dict:
        """{partition: [document_id, ...]} for a document set (bench &
        monitor convenience; deterministic order preserved)."""
        out: dict = {p: [] for p in range(self.partitions)}
        for doc_id in document_ids:
            out[self.partition_for(doc_id)].append(doc_id)
        return out
