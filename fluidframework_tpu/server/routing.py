"""Restart-stable document routing shared by every sharded tier.

One hash, two consumers: the broadcaster's fan-out shards
(server/lambdas/broadcaster.py) and the ingest tier's partition router
(server/sharding.py) both assign a document a "home" by the SAME md5
scheme, so the two tiers can never disagree about where a document's
traffic lives — the broadcast shard draining a document's deliveries is
always derivable from the partition sequencing it (and vice versa) by
taking the digest modulo the respective shard count.

md5 rather than ``hash()`` because Python's string hash is seeded per
process: a restart would re-home every document, breaking per-document
ordering for durable logs and run-twice determinism in the soak suite.
md5 rather than crc32 (the broker's internal key hash) because the md5
scheme is the one the broadcaster shipped with (docs/read_path.md) and
re-homing broadcast shards to match the broker would invalidate
existing shard-affinity expectations; the ingest tier instead produces
to EXPLICIT partitions (`MessageLog.send_to`) so the broker's own key
hash never routes a sharded tenant's documents.

Dependency-free (stdlib only): imported by lambdas and the server tier
alike without cycles.
"""

from __future__ import annotations

import hashlib


def doc_shard(document_id: str, shards: int) -> int:
    """The stable home of a document among ``shards`` slots.

    Little-endian first 4 digest bytes, modulo the slot count — byte
    order is pinned so the mapping is identical across hosts and
    restarts (run-twice determinism; see the broadcaster's routing
    stability tests in tests/test_broadcaster.py)."""
    if shards <= 1:
        return 0
    digest = hashlib.md5(str(document_id).encode()).digest()
    return int.from_bytes(digest[:4], "little") % shards


class PartitionRouter:
    """Doc -> ingest-partition routing for one topic's partition count.

    Restart-stable by construction (pure function of the document id and
    the partition count); rebalancing therefore means CHANGING the
    partition count, which re-homes (1 - 1/N) of documents — the
    rebalance contract (docs/ingest_sharding.md) requires draining the
    old topology to a checkpoint barrier first, exactly like a Kafka
    repartition."""

    def __init__(self, partitions: int):
        self.partitions = max(1, int(partitions))

    def partition_for(self, document_id: str) -> int:
        return doc_shard(document_id, self.partitions)

    def assignment(self, document_ids) -> dict:
        """{partition: [document_id, ...]} for a document set (bench &
        monitor convenience; deterministic order preserved)."""
        out: dict = {p: [] for p in range(self.partitions)}
        for doc_id in document_ids:
            out[self.partition_for(doc_id)].append(doc_id)
        return out
