"""Python face of the native wire->tensor pump (native/src/wirepump.cpp).

One `parse()` call turns a flush's worth of raw boxcar bytes into numpy
columns + a text arena + intern deltas; everything downstream
(tpu_sequencer._flush_fast) is vectorized numpy + device dispatch. The
reference's analog is the native kafka consume -> deli ticket hot loop
(deli/lambda.ts:142); here the parse/intern half is C++ and the ticket
half is the device kernel.
"""

from __future__ import annotations

import ctypes
from typing import List, NamedTuple, Optional

import numpy as np

# Column indices — MUST match the Col enum in native/src/wirepump.cpp.
DOC, KIND, CLIENT, CSEQ, REFSEQ, FAMILY, CHAN, MKIND, POS1, POS2, \
    TEXTOFF, TEXTLEN, CHARLEN, FLAGS, BUF, MSTART, MEND, PSTART, PEND = \
    range(19)
NF = 19

F_FALLBACK, F_MARKER, F_PROPS, F_VALUE, F_RUN, F_ITEMS = \
    1, 2, 4, 8, 16, 32
FAM_NONE, FAM_MERGE, FAM_LWW = 0, 1, 2


class Parsed(NamedTuple):
    """One flush's parsed staging."""

    cols: np.ndarray          # [NF, n] int32
    arena: bytes              # unescaped insert text payloads
    bufs: List[bytes]         # the raw inputs (spans index into these)
    new_docs: list            # [(ord, doc_id)]
    new_clients: list         # [(doc_ord, ord, client_id)]
    new_channels: list        # [(ord, doc_ord, store, channel)]
    new_keys: list            # [(ord, key)]

    @property
    def n(self) -> int:
        return self.cols.shape[1]


_LIB = None


def _load():
    global _LIB
    if _LIB is None:
        try:
            from ..native.build import ensure_built
            lib = ctypes.PyDLL(ensure_built("wirepump"))
            lib.pump_new.restype = ctypes.c_void_p
            lib.pump_free.argtypes = [ctypes.c_void_p]
            lib.pump_parse.argtypes = [ctypes.c_void_p, ctypes.py_object]
            lib.pump_parse.restype = ctypes.c_long
            lib.pump_fill.argtypes = [ctypes.c_void_p, ctypes.c_void_p,
                                      ctypes.c_long]
            lib.pump_fill.restype = ctypes.c_long
            lib.pump_arena_size.argtypes = [ctypes.c_void_p]
            lib.pump_arena_size.restype = ctypes.c_long
            lib.pump_fill_arena.argtypes = [ctypes.c_void_p,
                                            ctypes.c_void_p, ctypes.c_long]
            lib.pump_fill_arena.restype = ctypes.c_long
            for name in ("pump_take_new_docs", "pump_take_new_clients",
                         "pump_take_new_channels", "pump_take_new_keys"):
                fn = getattr(lib, name)
                fn.argtypes = [ctypes.c_void_p]
                fn.restype = ctypes.py_object
            lib.pump_preload_doc.argtypes = [ctypes.c_void_p,
                                             ctypes.c_char_p]
            lib.pump_preload_doc.restype = ctypes.c_long
            lib.pump_preload_client.argtypes = [
                ctypes.c_void_p, ctypes.c_long, ctypes.c_char_p,
                ctypes.c_long]
            lib.pump_preload_client.restype = ctypes.c_long
            lib.pump_nf.restype = ctypes.c_long
            if lib.pump_nf() != NF:
                raise RuntimeError("wirepump NF mismatch — rebuild needed")
            _LIB = lib
        except (ImportError, OSError, RuntimeError, AttributeError):
            # No toolchain (NativeBuildError is a RuntimeError), missing
            # symbol, or NF mismatch: pump unavailable, object path only.
            from ..telemetry.counters import record_swallow
            record_swallow("pump.unavailable")
            _LIB = False
    return _LIB or None


def available() -> bool:
    return _load() is not None


class WirePump:
    """Stateful pump: holds the intern tables for one sequencer lambda."""

    def __init__(self):
        lib = _load()
        if lib is None:
            raise RuntimeError("native wirepump unavailable")
        self._lib = lib
        self._ctx = lib.pump_new()

    def __del__(self):
        ctx = getattr(self, "_ctx", None)
        if ctx:
            self._lib.pump_free(ctx)
            self._ctx = None

    def parse(self, bufs: List[bytes]) -> Parsed:
        lib = self._lib
        n = lib.pump_parse(self._ctx, bufs)
        if n < 0:
            raise ValueError(f"pump_parse failed rc={n}")
        cols = np.empty((NF, n), np.int32)
        if n and lib.pump_fill(self._ctx, cols.ctypes.data, n) != 0:
            raise RuntimeError("pump_fill size mismatch")
        asize = lib.pump_arena_size(self._ctx)
        arena = ctypes.create_string_buffer(asize)
        if asize and lib.pump_fill_arena(self._ctx, arena, asize) != 0:
            raise RuntimeError("pump_fill_arena size mismatch")
        return Parsed(
            cols=cols,
            arena=arena.raw[:asize],
            bufs=bufs,
            new_docs=lib.pump_take_new_docs(self._ctx),
            new_clients=lib.pump_take_new_clients(self._ctx),
            new_channels=lib.pump_take_new_channels(self._ctx),
            new_keys=lib.pump_take_new_keys(self._ctx),
        )

    # -- checkpoint-restore preloads ---------------------------------------
    def preload_doc(self, doc_id: str) -> int:
        """Intern a restored document; returns its pump ordinal. The
        caller must treat it as 'new' (it will not reappear in new_docs)."""
        return int(self._lib.pump_preload_doc(
            self._ctx, doc_id.encode("utf-8")))

    def preload_client(self, doc_ord: int, client_id: str,
                       ordinal: int) -> None:
        rc = self._lib.pump_preload_client(
            self._ctx, doc_ord, client_id.encode("utf-8"), ordinal)
        if rc != 0:
            raise ValueError(f"preload_client({doc_ord}) rc={rc}")
