"""The ordered log: topics, partitions, offsets, consumer checkpoints.

Capability parity with the reference's Kafka backbone (services-core
IProducer/IConsumer/IQueuedMessage, queue.ts) and its in-memory stand-in
LocalKafka (memory-orderer/src/localKafka.ts). Messages are boxcars keyed
by document; documents hash to partitions; consumers poll per partition and
commit offsets, so a crashed lambda replays from its last checkpoint
idempotently (kafka-service/README design).

A C++ shared-memory implementation with the same interface lives in
fluidframework_tpu.native.oplog (the librdkafka-equivalent native path);
this module is the always-available pure-Python engine and the fallback.
"""

from __future__ import annotations

import threading
from dataclasses import dataclass, field
from typing import Any, Callable, Dict, List, Optional

from ..telemetry.counters import record_swallow


@dataclass
class QueuedMessage:
    topic: str
    partition: int
    offset: int
    key: str
    value: Any


class Partition:
    def __init__(self, topic: str, index: int, base_offset: int = 0):
        self.topic = topic
        self.index = index
        # First offset held in memory. Always 0 for the in-memory engines;
        # a durable log opened in replay="committed" mode keeps only the
        # uncheckpointed suffix resident and serves older offsets from its
        # segment files (server/durable.py poll override).
        self.base_offset = base_offset
        self.messages: List[QueuedMessage] = []
        self.lock = threading.Lock()
        self.listeners: List[Callable[[QueuedMessage], None]] = []

    def append(self, key: str, value: Any) -> QueuedMessage:
        with self.lock:
            msg = QueuedMessage(self.topic, self.index,
                                self.base_offset + len(self.messages),
                                key, value)
            self.messages.append(msg)
            listeners = list(self.listeners)
        for fn in listeners:
            fn(msg)
        return msg

    def read(self, offset: int, limit: int = 1000) -> List[QueuedMessage]:
        with self.lock:
            lo = max(offset - self.base_offset, 0)
            return self.messages[lo:lo + limit]

    @property
    def end_offset(self) -> int:
        with self.lock:
            return self.base_offset + len(self.messages)


class Topic:
    def __init__(self, name: str, partitions: int):
        self.name = name
        self.partitions = [Partition(name, i) for i in range(partitions)]

    def partition_for(self, key: str) -> Partition:
        # STABLE hash: Python's hash() is randomized per process, which
        # would re-route a document to a different partition after a broker
        # restart — breaking per-document ordering for durable logs.
        import zlib
        digest = zlib.crc32(key.encode("utf-8"))
        return self.partitions[digest % len(self.partitions)]


class MessageLog:
    """Broker: named topics with N partitions each + consumer-group offsets."""

    def __init__(self, default_partitions: int = 1):
        self.topics: Dict[str, Topic] = {}
        self.default_partitions = default_partitions
        # (group, topic, partition) -> committed offset
        self.checkpoints: Dict[tuple, int] = {}
        self._lock = threading.Lock()

    def topic(self, name: str, partitions: Optional[int] = None) -> Topic:
        with self._lock:
            if name not in self.topics:
                self.topics[name] = Topic(
                    name, partitions or self.default_partitions)
            return self.topics[name]

    # -- producer ----------------------------------------------------------
    def send(self, topic: str, key: str, value: Any) -> QueuedMessage:
        return self.topic(topic).partition_for(key).append(key, value)

    def send_to(self, topic: str, partition: int, key: str,
                value: Any) -> QueuedMessage:
        """Produce to an EXPLICIT partition (bypassing key hashing) — for
        records that span many keys, like a sequencer window, which must
        land on the partition its source documents hash to."""
        return self.topic(topic).partitions[partition].append(key, value)

    def send_to_many(self, topic: str, partition: int,
                     items: List[tuple]) -> List[QueuedMessage]:
        """Batched explicit-partition produce: append [(key, value), ...]
        to one partition in order. On this engine it is a convenience
        loop; on the durable engine the whole batch rides ONE group
        commit (one write+fsync), and on the gRPC engine it is one round
        trip — the producer-side half of the million-msgs/s broker path.
        Per-partition order is the list order, exactly as if the caller
        had issued send_to() per item."""
        part = self.topic(topic).partitions[partition]
        return [part.append(key, value) for key, value in items]

    # -- consumer ----------------------------------------------------------
    def poll(self, group: str, topic: str, partition: int = 0,
             limit: int = 1000) -> List[QueuedMessage]:
        start = self.committed(group, topic, partition)
        return self.topic(topic).partitions[partition].read(start, limit)

    def read_from(self, topic: str, partition: int, offset: int,
                  limit: int = 1000) -> List[QueuedMessage]:
        """Group-independent read from an explicit offset — the replay
        surface crash recovery uses when it must re-read records BELOW a
        group's committed offset (rebalance buffer recovery in
        server/sharding.py). The durable engine overrides this to serve
        offsets below the resident window from its segment index."""
        return self.topic(topic).partitions[partition].read(offset, limit)

    def commit(self, group: str, topic: str, partition: int,
               offset: int) -> None:
        """Commit 'processed through offset' (next poll starts at offset+1)."""
        with self._lock:
            key = (group, topic, partition)
            if offset + 1 > self.checkpoints.get(key, 0):
                self.checkpoints[key] = offset + 1

    def commit_many(self, group: str, topic: str,
                    offsets: Dict[int, int]) -> None:
        """Batched cross-partition ack: commit {partition: offset} for a
        whole consumer group in ONE lock acquisition — the sharded
        ingest tier (server/sharding.py AckBatcher) flushes a pump
        round's per-partition checkpoints through here instead of N
        broker round-trips. Same never-regress semantics as commit()."""
        with self._lock:
            for partition, offset in offsets.items():
                key = (group, topic, partition)
                if offset + 1 > self.checkpoints.get(key, 0):
                    self.checkpoints[key] = offset + 1

    def committed(self, group: str, topic: str, partition: int) -> int:
        return self.checkpoints.get((group, topic, partition), 0)

    def subscribe(self, topic: str, partition: int,
                  fn: Callable[[QueuedMessage], None]) -> None:
        self.topic(topic).partitions[partition].listeners.append(fn)

    def unsubscribe(self, topic: str, partition: int,
                    fn: Callable[[QueuedMessage], None]) -> None:
        """Removal path for subscribe: a consumer that rebalances away
        must drop its listener or the broker pins it (and everything the
        closure captured) for the process lifetime."""
        listeners = self.topic(topic).partitions[partition].listeners
        if fn in listeners:
            listeners.remove(fn)


def make_message_log(default_partitions: int = 1,
                     native: Optional[bool] = None):
    """Broker factory. native=True requires the C++ engine (raises if the
    toolchain is unavailable); native=None auto-selects it when it builds;
    native=False pins the pure-Python engine."""
    if native is False:
        return MessageLog(default_partitions)
    try:
        from ..native.oplog import NativeMessageLog, is_available
        if native or is_available():
            return NativeMessageLog(default_partitions)
    except (ImportError, OSError, RuntimeError, AttributeError):
        # NativeBuildError is a RuntimeError; OSError covers a missing/
        # unloadable .so; AttributeError a stale .so missing a symbol
        # (ctypes binding happens outside oplog._load's own guard). With
        # native=None this is the documented auto-fallback — counted so a
        # fleet that should be native shows the silent downgrade on
        # /healthz.
        if native:
            raise
        record_swallow("log.native_fallback")
    return MessageLog(default_partitions)
