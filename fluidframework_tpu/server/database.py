"""In-memory database collections (reference services-core ICollection /
IDatabaseManager over MongoDB). Scriptorium's delta store and deli/scribe
checkpoints live here; inserts are idempotent on unique keys the way the
reference relies on dup-key 11000 being ignored on replay
(scriptorium/lambda.ts:92-99)."""

from __future__ import annotations

import threading
from typing import Any, Callable, Dict, List, Optional, Tuple


class Collection:
    def __init__(self, unique_key: Optional[Callable[[dict], Any]] = None):
        self._docs: List[dict] = []
        self._unique: Dict[Any, int] = {}
        self._unique_key = unique_key
        self._lock = threading.Lock()

    def insert_one(self, doc: dict) -> bool:
        """False if a doc with the same unique key exists (idempotent replay)."""
        with self._lock:
            if self._unique_key is not None:
                key = self._unique_key(doc)
                if key in self._unique:
                    return False
                self._unique[key] = len(self._docs)
            self._docs.append(dict(doc))
            return True

    def insert_many(self, docs: List[dict]) -> int:
        return sum(1 for d in docs if self.insert_one(d))

    def find(self, predicate: Callable[[dict], bool]) -> List[dict]:
        with self._lock:
            return [dict(d) for d in self._docs if predicate(d)]

    def find_one(self, predicate: Callable[[dict], bool]) -> Optional[dict]:
        with self._lock:
            for d in self._docs:
                if predicate(d):
                    return dict(d)
        return None

    def upsert(self, match: Callable[[dict], bool], doc: dict) -> None:
        with self._lock:
            for i, d in enumerate(self._docs):
                if match(d):
                    self._docs[i] = dict(doc)
                    return
            self._docs.append(dict(doc))

    def __len__(self) -> int:
        with self._lock:
            return len(self._docs)


class DatabaseManager:
    """Named collections per (tenant, document) style keys."""

    def __init__(self):
        self._collections: Dict[str, Collection] = {}
        self._lock = threading.Lock()

    def collection(self, name: str,
                   unique_key: Optional[Callable[[dict], Any]] = None
                   ) -> Collection:
        with self._lock:
            if name not in self._collections:
                self._collections[name] = Collection(unique_key)
            return self._collections[name]
