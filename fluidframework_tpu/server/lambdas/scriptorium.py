"""Scriptorium: persists sequenced deltas (reference scriptorium/lambda.ts:
16-103 — batched Mongo insertMany, idempotent on duplicate keys, traces
stripped before persisting)."""

from __future__ import annotations

from dataclasses import asdict
from typing import List

from ...protocol.messages import SequencedDocumentMessage
from ..database import Collection
from ..log import QueuedMessage
from .base import IPartitionLambda, LambdaContext


class ScriptoriumLambda(IPartitionLambda):
    def __init__(self, context: LambdaContext, deltas: Collection):
        self.context = context
        self.deltas = deltas

    def handler(self, message: QueuedMessage) -> None:
        doc_id, sequenced = message.value
        record = asdict(sequenced)
        record["traces"] = []  # strip latency traces before persisting
        record["documentId"] = doc_id
        # The in-memory collection makes the reference's batched async
        # insertMany a synchronous insert; duplicates (replay) are ignored.
        self.deltas.insert_one(record)
        self.context.checkpoint(message.offset)


def delta_key(doc: dict):
    return (doc["documentId"], doc["sequence_number"])
