"""Scriptorium: persists sequenced deltas (reference scriptorium/lambda.ts:
16-103 — batched Mongo insertMany, idempotent on duplicate keys, traces
stripped before persisting)."""

from __future__ import annotations

from dataclasses import asdict
from typing import List

from ...protocol.messages import SequencedDocumentMessage
from ..database import Collection
from ..log import QueuedMessage
from .base import IPartitionLambda, LambdaContext


class ScriptoriumLambda(IPartitionLambda):
    def __init__(self, context: LambdaContext, deltas: Collection):
        self.context = context
        self.deltas = deltas

    def handler(self, message: QueuedMessage) -> None:
        value = message.value
        if hasattr(value, "messages"):
            # A SequencedWindow (tpu_sequencer fast path): ONE log record
            # per flush; persist every admitted message it carries — the
            # reference's insertMany batch, naturally window-sized.
            for doc_id, sequenced in value.messages():
                self._persist(doc_id, sequenced)
            self.context.checkpoint(message.offset)
            return
        doc_id, sequenced = value
        self._persist(doc_id, sequenced)
        self.context.checkpoint(message.offset)

    def _persist(self, doc_id: str,
                 sequenced: SequencedDocumentMessage) -> None:
        record = asdict(sequenced)
        record["traces"] = []  # strip latency traces before persisting
        record["documentId"] = doc_id
        # The in-memory collection makes the reference's batched async
        # insertMany a synchronous insert; duplicates (replay) are ignored.
        self.deltas.insert_one(record)


def delta_key(doc: dict):
    return (doc["documentId"], doc["sequence_number"])


def query_deltas(deltas: Collection, document_id: str, from_seq: int = 0,
                 to_seq=None) -> List[dict]:
    """Catch-up range query over the delta store: rows with
    from_seq < seq <= to_seq, ordered (alfred's delta REST semantics)."""
    hi = to_seq if to_seq is not None else 2 ** 62
    out = deltas.find(
        lambda d: d["documentId"] == document_id
        and from_seq < d["sequence_number"] <= hi)
    out.sort(key=lambda d: d["sequence_number"])
    return out
