"""Broadcaster: fans sequenced ops out to connected clients per document
room (reference broadcaster/lambda.ts — socket.io rooms batched per
tenantId/documentId)."""

from __future__ import annotations

from typing import Callable, Dict, List

from ...protocol.messages import SequencedDocumentMessage
from ..log import QueuedMessage
from .base import IPartitionLambda, LambdaContext


class BroadcasterLambda(IPartitionLambda):
    def __init__(self, context: LambdaContext,
                 rooms: Dict[str, List[Callable]] = None):
        self.context = context
        # document id -> list of listener callbacks (the "room"). The dict
        # may be owned by the hosting server so membership survives a
        # crash-restart of this lambda (connection state is not log-derived).
        self.rooms: Dict[str, List[Callable[[SequencedDocumentMessage], None]]] \
            = rooms if rooms is not None else {}

    def join_room(self, document_id: str,
                  listener: Callable[[SequencedDocumentMessage], None]) -> None:
        self.rooms.setdefault(document_id, []).append(listener)

    def leave_room(self, document_id: str, listener) -> None:
        room = self.rooms.get(document_id)
        if room and listener in room:
            room.remove(listener)

    def handler(self, message: QueuedMessage) -> None:
        value = message.value
        if hasattr(value, "messages"):
            # SequencedWindow: one record per flush; fan out per room.
            for doc_id, sequenced in value.messages():
                for listener in list(self.rooms.get(doc_id, [])):
                    listener(sequenced)
            self.context.checkpoint(message.offset)
            return
        doc_id, sequenced = value
        for listener in list(self.rooms.get(doc_id, [])):
            listener(sequenced)
        self.context.checkpoint(message.offset)
