"""Broadcaster: fans sequenced ops out to connected clients per document
room (reference broadcaster/lambda.ts — socket.io rooms batched per
tenantId/documentId), with optional doc-hash-sharded fan-out workers.

Inline mode (shards=0, the default) delivers on the pump thread —
synchronous and deterministic, what every in-process test relies on.
Sharded mode (docs/read_path.md) is the million-reader shape: one hot
document, or a reconnect avalanche resubscribing thousands of listeners,
must not serialize EVERY room's delivery through one pump thread. Each
document hashes to a fixed shard (per-doc delivery order is preserved —
one FIFO queue per shard), shard queues are bounded, and an overloaded
shard sheds its OLDEST entries: dropped fan-outs are safe by the read
path's own contract — a client that misses a broadcast sees the gap on
the next delivered op and refetches from delta storage (DeltaManager gap
detection), and the shed count feeds admission/monitoring so the
condition is visible instead of silent.
"""

from __future__ import annotations

import threading
import time
from collections import deque
from typing import Callable, Dict, List, Optional

from ...protocol.messages import SequencedDocumentMessage
from ...telemetry import tracing
from ...telemetry import watermarks
from ...telemetry.counters import gauge, increment
from ..log import QueuedMessage
from ..routing import doc_shard
from .base import IPartitionLambda, LambdaContext


def shard_for(document_id: str, shards: int) -> int:
    """Stable doc -> shard routing: the SHARED md5 scheme
    (server/routing.py doc_shard) the ingest partition router also uses,
    so the broadcast shard and the sequencing partition of a document
    can never disagree. md5, not hash(): per-process seed randomization
    would re-shard every restart and break run-twice determinism in the
    soak suite."""
    return doc_shard(document_id, shards)


class _Shard:
    """One fan-out worker: a bounded FIFO of (doc_id, message) + the
    thread draining it. Bounded-queue policy: shed from the HEAD (oldest
    first) — the freshest ops are the ones that close a reader's gap."""

    def __init__(self, index: int, queue_limit: int,
                 deliver: Callable[[str, SequencedDocumentMessage], None]):
        self.index = index
        self.queue_limit = queue_limit
        self.deliver = deliver
        self.queue: deque = deque()
        self.cond = threading.Condition()
        self.shed = 0
        self.delivered = 0
        self.busy = False  # worker inside deliver() (drain() waits on it)
        self.closed = False
        self.thread = threading.Thread(
            target=self._run, name=f"broadcaster-shard-{index}",
            daemon=True)
        self.thread.start()

    def put(self, doc_id: str, message: SequencedDocumentMessage) -> None:
        with self.cond:
            if self.closed:
                return
            while len(self.queue) >= self.queue_limit:
                self.queue.popleft()
                self.shed += 1
                increment("broadcaster.shed")
            self.queue.append((doc_id, message, time.perf_counter()))
            self.cond.notify()

    def _run(self) -> None:
        while True:
            with self.cond:
                while not self.queue and not self.closed:
                    self.cond.wait(timeout=0.5)
                if self.closed and not self.queue:
                    return
                doc_id, message, t_enq = self.queue.popleft()
                self.busy = True
            try:
                self.deliver(doc_id, message)
            except Exception:  # noqa: BLE001 — a listener bug must not kill the shard
                from ...telemetry.counters import record_swallow
                record_swallow("broadcaster.shard_deliver")
            finally:
                # Shard-worker span (docs/observability.md): enqueue →
                # delivered, so the span measures queue DWELL + fan-out —
                # the figure a backed-up shard actually adds to reader
                # latency. Pre-measured record_span joined to the op's
                # wire context (same pattern as _fan_out); the histogram
                # fills even with tracing off.
                tracing.record_span(
                    "broadcaster.shard", tracing.message_context(message),
                    t_enq, time.perf_counter(),
                    hist="broadcaster.shard_dwell", shard=self.index,
                    document=doc_id)
                with self.cond:
                    self.busy = False
                    self.delivered += 1
                    if not self.queue:
                        self.cond.notify_all()  # wake drain() waiters

    def depth(self) -> int:
        with self.cond:
            return len(self.queue)

    def drain(self, timeout: float) -> bool:
        deadline = time.monotonic() + timeout
        with self.cond:
            while self.queue or self.busy:
                remaining = deadline - time.monotonic()
                if remaining <= 0:
                    return False
                self.cond.wait(timeout=min(remaining, 0.05))
        return True

    def close(self) -> None:
        with self.cond:
            self.closed = True
            self.cond.notify_all()


class BroadcasterLambda(IPartitionLambda):
    def __init__(self, context: LambdaContext,
                 rooms: Dict[str, List[Callable]] = None,
                 shards: int = 0, queue_limit: int = 1024):
        self.context = context
        # document id -> list of listener callbacks (the "room"). The dict
        # may be owned by the hosting server so membership survives a
        # crash-restart of this lambda (connection state is not log-derived).
        self.rooms: Dict[str, List[Callable[[SequencedDocumentMessage], None]]] \
            = rooms if rooms is not None else {}
        self.queue_limit = queue_limit
        self.closed = False  # crash-restart superseded (see close())
        self.shards: List[_Shard] = [
            _Shard(i, queue_limit, self._fan_out)
            for i in range(max(0, int(shards)))]

    def join_room(self, document_id: str,
                  listener: Callable[[SequencedDocumentMessage], None]) -> None:
        self.rooms.setdefault(document_id, []).append(listener)

    def leave_room(self, document_id: str, listener) -> None:
        room = self.rooms.get(document_id)
        if room and listener in room:
            room.remove(listener)

    def handler(self, message: QueuedMessage) -> None:
        value = message.value
        if hasattr(value, "messages"):
            # SequencedWindow: one record per flush; fan out per room.
            for doc_id, sequenced in value.messages():
                self._route(doc_id, sequenced)
            self.context.checkpoint(message.offset)
            return
        doc_id, sequenced = value
        self._route(doc_id, sequenced)
        # Sharded mode checkpoints at ENQUEUE: fan-out is at-most-once
        # past this offset (a crash loses queued deliveries, exactly
        # like a shed — readers recover via the catch-up fetch), which
        # keeps a slow room from stalling the whole partition's pump.
        self.context.checkpoint(message.offset)

    def _route(self, doc_id: str,
               sequenced: SequencedDocumentMessage) -> None:
        if not self.shards:
            self._fan_out(doc_id, sequenced)
            return
        self.shards[shard_for(doc_id, len(self.shards))].put(doc_id,
                                                             sequenced)

    def _fan_out(self, doc_id: str,
                 sequenced: SequencedDocumentMessage) -> None:
        # Traced ops record the fan-out hop (metadata survived ticketing
        # via from_document_message, so the span joins the op's trace);
        # untraced ops take the bare loop. Pre-measured record_span (not
        # a context-manager Span) keeps the per-op cost off the fan-out
        # hot path's <2% tracing-overhead budget.
        ctx = tracing.message_context(sequenced)
        if ctx is None:
            self._deliver_room(doc_id, sequenced)
            self._mark_delivered(doc_id, sequenced)
            return
        t0 = time.perf_counter()
        self._deliver_room(doc_id, sequenced)
        tracing.record_span("broadcaster.fanout", ctx, t0,
                            time.perf_counter(), document=doc_id,
                            seq=sequenced.sequence_number,
                            shard=(shard_for(doc_id, len(self.shards))
                                   if self.shards else -1))
        self._mark_delivered(doc_id, sequenced)

    def _mark_delivered(self, doc_id: str,
                        sequenced: SequencedDocumentMessage) -> None:
        # `broadcast` watermark (telemetry/watermarks.py): per-doc seq
        # high-water, so replays and shed-then-covered gaps fold to the
        # honest delivered frontier. One guarded dict update per op —
        # inside the fan-out path's existing per-op budget.
        # Embedder/test contexts are single-partition and carry no
        # partition id; fold their marks to p0.
        watermarks.advance_doc(watermarks.BROADCAST,
                               getattr(self.context, "partition", 0),
                               doc_id, sequenced.sequence_number)

    def _deliver_room(self, doc_id: str,
                      sequenced: SequencedDocumentMessage) -> None:
        if not self.shards:
            # Inline mode: exceptions propagate to the pump exactly as
            # they always did (in-process listeners are trusted).
            for listener in list(self.rooms.get(doc_id, [])):
                listener(sequenced)
            return
        # Sharded mode: per-LISTENER isolation — one subscriber's bug
        # must not starve the rest of the room (there is no pump-level
        # caller left to surface it to; the swallow counter is the
        # visibility).
        for listener in list(self.rooms.get(doc_id, [])):
            try:
                listener(sequenced)
            except Exception:  # noqa: BLE001 — counted, see above
                from ...telemetry.counters import record_swallow
                record_swallow("broadcaster.listener")

    # -- read-tier introspection (monitor.watch_readpath) ------------------
    def queue_depth(self) -> int:
        return sum(s.depth() for s in self.shards)

    def queue_depths(self) -> List[int]:
        """Per-shard backlog; also refreshes the per-shard depth gauges
        every time a probe reads it (broadcaster.queue_depth.shard<i>)."""
        depths = [s.depth() for s in self.shards]
        for i, d in enumerate(depths):
            gauge(f"broadcaster.queue_depth.shard{i}", d)
        return depths

    def shed_count(self) -> int:
        return sum(s.shed for s in self.shards)

    def stats(self) -> dict:
        return {
            "shards": len(self.shards),
            "queueLimit": self.queue_limit,
            "queueDepths": self.queue_depths(),
            "shed": self.shed_count(),
            "delivered": sum(s.delivered for s in self.shards),
            "rooms": len(self.rooms),
        }

    def drain(self, timeout: float = 10.0) -> bool:
        """Block until every shard queue is empty (inline mode: no-op)."""
        ok = True
        for s in self.shards:
            ok = s.drain(timeout) and ok
        return ok

    def close(self) -> None:
        """Crash-restart/shutdown: shard workers DRAIN their queues and
        exit — enqueued messages are already past the checkpoint, so the
        replacement lambda never replays them; dropping them here would
        lose the at-least-once leg. The hosting server prunes closed
        instances from its registry (LocalServer._build_broadcaster)."""
        self.closed = True
        for s in self.shards:
            s.close()
