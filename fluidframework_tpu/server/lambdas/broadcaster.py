"""Broadcaster: fans sequenced ops out to connected clients per document
room (reference broadcaster/lambda.ts — socket.io rooms batched per
tenantId/documentId)."""

from __future__ import annotations

import time
from typing import Callable, Dict, List

from ...protocol.messages import SequencedDocumentMessage
from ...telemetry import tracing
from ..log import QueuedMessage
from .base import IPartitionLambda, LambdaContext


class BroadcasterLambda(IPartitionLambda):
    def __init__(self, context: LambdaContext,
                 rooms: Dict[str, List[Callable]] = None):
        self.context = context
        # document id -> list of listener callbacks (the "room"). The dict
        # may be owned by the hosting server so membership survives a
        # crash-restart of this lambda (connection state is not log-derived).
        self.rooms: Dict[str, List[Callable[[SequencedDocumentMessage], None]]] \
            = rooms if rooms is not None else {}

    def join_room(self, document_id: str,
                  listener: Callable[[SequencedDocumentMessage], None]) -> None:
        self.rooms.setdefault(document_id, []).append(listener)

    def leave_room(self, document_id: str, listener) -> None:
        room = self.rooms.get(document_id)
        if room and listener in room:
            room.remove(listener)

    def handler(self, message: QueuedMessage) -> None:
        value = message.value
        if hasattr(value, "messages"):
            # SequencedWindow: one record per flush; fan out per room.
            for doc_id, sequenced in value.messages():
                self._fan_out(doc_id, sequenced)
            self.context.checkpoint(message.offset)
            return
        doc_id, sequenced = value
        self._fan_out(doc_id, sequenced)
        self.context.checkpoint(message.offset)

    def _fan_out(self, doc_id: str,
                 sequenced: SequencedDocumentMessage) -> None:
        # Traced ops record the fan-out hop (metadata survived ticketing
        # via from_document_message, so the span joins the op's trace);
        # untraced ops take the bare loop. Pre-measured record_span (not
        # a context-manager Span) keeps the per-op cost off the fan-out
        # hot path's <2% tracing-overhead budget.
        ctx = tracing.message_context(sequenced)
        if ctx is None:
            for listener in list(self.rooms.get(doc_id, [])):
                listener(sequenced)
            return
        t0 = time.perf_counter()
        for listener in list(self.rooms.get(doc_id, [])):
            listener(sequenced)
        tracing.record_span("broadcaster.fanout", ctx, t0,
                            time.perf_counter(), document=doc_id,
                            seq=sequenced.sequence_number)
