"""Copier: persists *raw* (pre-sequencing) ops for debugging/replay
(reference copier/README.md:1-24)."""

from __future__ import annotations

from dataclasses import asdict

from ..database import Collection
from ..log import QueuedMessage
from .base import IPartitionLambda, LambdaContext


class CopierLambda(IPartitionLambda):
    def __init__(self, context: LambdaContext, raw_deltas: Collection):
        self.context = context
        self.raw_deltas = raw_deltas

    def handler(self, message: QueuedMessage) -> None:
        boxcar = message.value
        if isinstance(boxcar, dict):
            # Rebalance control records (server/sharding.py handoff/
            # adopt) ride the raw topic as plain dicts — sequencer
            # control plane, not client traffic; nothing to archive.
            self.context.checkpoint(message.offset)
            return
        self.raw_deltas.insert_one({
            "documentId": boxcar.document_id,
            "clientId": boxcar.client_id,
            "offset": message.offset,
            "contents": [asdict(m) for m in boxcar.contents],
        })
        self.context.checkpoint(message.offset)
