"""Partition lambdas (reference routerlicious lambdas, SURVEY.md §2.5):
Deli (sequencer), Scriptorium (delta persistence), Scribe (server-side
summaries + ack/nack), Broadcaster (fan-out), Copier (raw-op capture),
Foreman (task distribution)."""

from .base import IPartitionLambda, LambdaContext
from .deli import DeliLambda
from .scriptorium import ScriptoriumLambda
from .scribe import ScribeLambda
from .broadcaster import BroadcasterLambda
from .copier import CopierLambda
from .foreman import ForemanLambda
