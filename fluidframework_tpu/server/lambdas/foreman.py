"""Foreman: background task distribution with worker heartbeats.

Capability parity with reference foreman/README.md:1-10 + lambda.ts:
distributes help requests (snapshot, intelligence) to registered workers,
tracks heartbeats, and reassigns tasks from dead workers.
"""

from __future__ import annotations

import time
from dataclasses import dataclass, field
from typing import Callable, Dict, List, Optional

from ...protocol.messages import MessageType
from ..log import QueuedMessage
from .base import IPartitionLambda, LambdaContext

DEFAULT_HEARTBEAT_TIMEOUT_S = 30.0


@dataclass
class Worker:
    worker_id: str
    dispatch: Callable[[dict], None]
    last_heartbeat: float = field(default_factory=time.time)
    tasks: List[dict] = field(default_factory=list)


class ForemanLambda(IPartitionLambda):
    def __init__(self, context: LambdaContext,
                 heartbeat_timeout_s: float = DEFAULT_HEARTBEAT_TIMEOUT_S):
        self.context = context
        self.workers: Dict[str, Worker] = {}
        self.heartbeat_timeout_s = heartbeat_timeout_s
        self.pending: List[dict] = []
        self._rr = 0

    # -- worker registry ---------------------------------------------------
    def register_worker(self, worker_id: str,
                        dispatch: Callable[[dict], None]) -> None:
        self.workers[worker_id] = Worker(worker_id, dispatch)
        self._drain()

    def heartbeat(self, worker_id: str) -> None:
        if worker_id in self.workers:
            self.workers[worker_id].last_heartbeat = time.time()

    def complete_task(self, worker_id: str, task: dict) -> None:
        worker = self.workers.get(worker_id)
        if worker and task in worker.tasks:
            worker.tasks.remove(task)

    def reap_dead_workers(self, now: Optional[float] = None) -> List[str]:
        """Reassign tasks from workers whose heartbeat expired."""
        now = time.time() if now is None else now
        dead = [wid for wid, w in self.workers.items()
                if now - w.last_heartbeat > self.heartbeat_timeout_s]
        for wid in dead:
            worker = self.workers.pop(wid)
            self.pending.extend(worker.tasks)
        self._drain()
        return dead

    # -- lambda ------------------------------------------------------------
    def handler(self, message: QueuedMessage) -> None:
        doc_id, sequenced = message.value
        if sequenced.type == MessageType.REMOTE_HELP:
            contents = sequenced.contents or {}
            for task_name in contents.get("tasks", []):
                self.pending.append({"documentId": doc_id, "task": task_name})
            self._drain()
        self.context.checkpoint(message.offset)

    def _drain(self) -> None:
        alive = list(self.workers.values())
        while self.pending and alive:
            task = self.pending.pop(0)
            worker = alive[self._rr % len(alive)]
            self._rr += 1
            worker.tasks.append(task)
            worker.dispatch(task)
