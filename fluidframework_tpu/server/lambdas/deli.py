"""Deli: the sequencer lambda.

Capability parity with reference lambdas/src/deli/lambda.ts:82-224 — assign
sequenceNumber + minimumSequenceNumber per document (min over per-client
refSeqs), nack stale refSeqs, drop duplicate clientSeqs, manage client
join/leave, emit NoClient when the document empties, and checkpoint state.

Two execution paths share the semantics:
- this host lambda: per-op, for the interactive local-server path;
- server/ticket_kernel.py: the batched device kernel the partition host
  uses to ticket whole [B, T] op blocks in one jit (the TPU "boxcar").
"""

from __future__ import annotations

import time
from dataclasses import dataclass, field
from typing import Any, Callable, Dict, List, Optional

from ...protocol.messages import (
    Boxcar,
    DocumentMessage,
    ITrace,
    MessageType,
    Nack,
    NackContent,
    NACK_BAD_REF_SEQ,
    SequencedDocumentMessage,
)
from ...telemetry import tracing
from ..log import QueuedMessage
from .base import IPartitionLambda, LambdaContext


@dataclass
class ClientSeqState:
    """Per-client sequencing entry (reference clientSeqManager.ts)."""

    client_id: str
    ref_seq: int
    client_seq: int
    can_evict: bool = True
    last_update: float = field(default_factory=time.time)


@dataclass
class DeliCheckpoint:
    sequence_number: int
    minimum_sequence_number: int
    log_offset: int
    clients: List[dict]


class DocumentDeliState:
    def __init__(self, sequence_number: int = 0,
                 minimum_sequence_number: int = 0, log_offset: int = -1):
        self.sequence_number = sequence_number
        self.minimum_sequence_number = minimum_sequence_number
        self.log_offset = log_offset
        self.clients: Dict[str, ClientSeqState] = {}

    def msn(self) -> int:
        refs = [c.ref_seq for c in self.clients.values()]
        if not refs:
            return self.minimum_sequence_number
        return max(self.minimum_sequence_number, min(refs))


class DeliLambda(IPartitionLambda):
    def __init__(self, context: LambdaContext,
                 emit: Callable[[str, SequencedDocumentMessage], None],
                 nack: Callable[[str, str, Nack], None],
                 checkpoints=None, fresh_log: bool = False,
                 config=None, send_system=None):
        """emit(document_id, sequenced_message); nack(document_id,
        client_id, nack). checkpoints: optional Collection for state dumps —
        restored at construction so a crash-restarted lambda resumes from
        its last checkpoint instead of re-sequencing from zero.

        fresh_log=True when this lambda consumes a brand-new MessageLog
        (multi-node takeover hands over checkpointed deli state, not the
        log): checkpointed offsets index the previous core's log, so replay
        protection must not skip the new log's messages. False (default) is
        the same-log crash-restart, where the checkpointed offset is the
        replay guard."""
        self.context = context
        self.emit = emit
        self.nack = nack
        self.docs: Dict[str, DocumentDeliState] = {}
        self.checkpoints = checkpoints
        # Batched checkpointing (reference deli/checkpointContext.ts with
        # checkpointBatchSize / checkpointTimeIntervalMsec from the nconf
        # config, routerlicious/config/config.json:62-68): the state dump
        # AND the offset commit move together — committing an offset beyond
        # the saved state would shrink the crash-replay window below what
        # the state needs. Default batch size 1 = checkpoint every message.
        self.checkpoint_batch_size = 1
        self.checkpoint_interval_s = 0.0
        if config is not None:
            self.checkpoint_batch_size = int(config.get(
                "deli.checkpointBatchSize", 1))
            self.checkpoint_interval_s = float(config.get(
                "deli.checkpointTimeIntervalMsec", 0)) / 1000.0
        self._uncheckpointed = 0
        self._last_checkpoint_time = time.monotonic()
        self._pending_offset: Optional[int] = None
        # Ghost-client eviction (reference ClientSequenceTimeout,
        # clientSeqManager canEvict): a writer that crashes without a
        # leave op would pin the MSN forever; after clientTimeout of
        # silence the sequencer synthesizes its leave. 0 disables.
        # The leave is SENT INTO THE RAW LOG (send_system) rather than
        # ticketed in place: sequencing inputs must all ride the log, or
        # a crash-replay would re-derive different sequence numbers than
        # the ones already broadcast (wall clock is not replayable).
        self.send_system = send_system
        self._evicting: Dict[str, set] = {}  # doc -> in-flight evictions
        self.client_timeout_s = 300.0
        if config is not None:
            self.client_timeout_s = float(config.get(
                "deli.clientTimeoutMsec", 300_000)) / 1000.0
        if checkpoints is not None:
            for row in checkpoints.find(lambda d: "documentId" in d):
                if row.get("handedOff") or "state" not in row:
                    # Rebalance tombstone (export_doc/drop_doc): this
                    # partition handed the document to another owner —
                    # restoring it here would re-adopt a moved document.
                    continue
                state = self.load_state(row["state"])
                if fresh_log:
                    state.log_offset = -1
                self.docs[row["documentId"]] = state

    # -- lambda ------------------------------------------------------------
    def handler(self, message: QueuedMessage) -> None:
        if isinstance(message.value, (bytes, bytearray)):
            from ..wire import boxcar_from_wire
            message = QueuedMessage(
                topic=message.topic, partition=message.partition,
                offset=message.offset, key=message.key,
                value=boxcar_from_wire(message.value))
        boxcar: Boxcar = message.value
        doc_id = boxcar.document_id
        state = self.docs.setdefault(doc_id, DocumentDeliState())
        if message.offset <= state.log_offset:
            return  # replayed message already processed (deli/lambda.ts:143)
        for raw in boxcar.contents:
            ctx = tracing.message_context(raw)
            if ctx is None:
                self._ticket(doc_id, state, boxcar.client_id, raw)
            else:
                with tracing.span("deli.ticket", parent=ctx,
                                  document=doc_id):
                    self._ticket(doc_id, state, boxcar.client_id, raw)
        self._evict_ghosts(doc_id, state)
        state.log_offset = message.offset
        self._pending_offset = message.offset
        self._uncheckpointed += 1
        now = time.monotonic()
        due = (self._uncheckpointed >= self.checkpoint_batch_size
               or (self.checkpoint_interval_s
                   and now - self._last_checkpoint_time
                   >= self.checkpoint_interval_s))
        if due:
            self.flush_checkpoint()

    def flush_checkpoint(self) -> None:
        """Write all document states + commit the consumer offset."""
        if self._pending_offset is None:
            return
        if self.checkpoints is not None:
            for doc_id, state in self.docs.items():
                self.checkpoints.upsert(
                    lambda d, _id=doc_id: d.get("documentId") == _id,
                    {"documentId": doc_id, "state": self._dump(state)})
        self.context.checkpoint(self._pending_offset)
        self._pending_offset = None
        self._uncheckpointed = 0
        self._last_checkpoint_time = time.monotonic()

    def close(self) -> None:
        # Graceful close flushes; a crash (no close) replays the batch —
        # exactly the reference's at-least-once window.
        self.flush_checkpoint()

    def doc_sequence_numbers(self) -> Dict[str, int]:
        """Per-document head sequence number: the `ticketed` watermark
        feed (telemetry/watermarks.py). Pulled at scrape time by the
        sharding tier, never per op."""
        return {doc_id: state.sequence_number
                for doc_id, state in self.docs.items()}

    def _dump(self, state: DocumentDeliState) -> dict:
        return {
            "sequenceNumber": state.sequence_number,
            "minimumSequenceNumber": state.minimum_sequence_number,
            "logOffset": state.log_offset,
            "clients": [
                {"clientId": c.client_id, "referenceSequenceNumber": c.ref_seq,
                 "clientSequenceNumber": c.client_seq,
                 "canEvict": c.can_evict}
                for c in state.clients.values()],
        }

    # -- live rebalance hooks (server/sharding.py handoff wrapper) ---------
    def export_doc(self, doc_id: str) -> Optional[dict]:
        """Serialize one document's live sequencing state for an epoch
        handoff (the same dump format checkpoints use). None when the
        document is not owned here — the idempotence a replayed handoff
        marker relies on."""
        state = self.docs.get(doc_id)
        if state is None:
            return None
        return self._dump(state)

    def drop_doc(self, doc_id: str, epoch: int = 0) -> None:
        """Release a handed-off document: forget the live state and
        TOMBSTONE its checkpoint row (handedOff=epoch) so a crash-restart
        of this partition does not re-adopt a document that now lives
        elsewhere. Called only after the adopt record is durably on the
        target partition."""
        self.docs.pop(doc_id, None)
        self._evicting.pop(doc_id, None)
        if self.checkpoints is not None:
            self.checkpoints.upsert(
                lambda d, _id=doc_id: d.get("documentId") == _id,
                {"documentId": doc_id, "handedOff": int(epoch)})

    def adopt_doc(self, doc_id: str, dump: dict) -> bool:
        """Install a handed-off document's state. The dump's logOffset
        indexes the SOURCE partition's log, so the replay guard resets
        (fresh_log semantics) — nothing on this partition predates the
        adoption. Idempotent: a duplicate adopt record (replayed marker
        on the source) is ignored once the document is owned."""
        if doc_id in self.docs:
            return False
        state = self.load_state(dump)
        state.log_offset = -1
        self.docs[doc_id] = state
        if self.checkpoints is not None:
            # Persist the adopted state NOW, not at the next flush
            # cadence: the source's row is already a tombstone, and
            # between adopt and the next checkpoint every cross-
            # partition reader (sequence_number introspection, node
            # takeover) would otherwise see no live row at all.
            self.checkpoints.upsert(
                lambda d, _id=doc_id: d.get("documentId") == _id,
                {"documentId": doc_id, "state": self._dump(state)})
        return True

    @staticmethod
    def load_state(dump: dict) -> DocumentDeliState:
        state = DocumentDeliState(dump["sequenceNumber"],
                                  dump["minimumSequenceNumber"],
                                  dump["logOffset"])
        for c in dump.get("clients", []):
            state.clients[c["clientId"]] = ClientSeqState(
                c["clientId"], c["referenceSequenceNumber"],
                c["clientSequenceNumber"], c.get("canEvict", True))
        return state

    # -- ticketing (reference ticket(), deli/lambda.ts:224) ----------------
    def _ticket(self, doc_id: str, state: DocumentDeliState,
                client_id: Optional[str], msg: DocumentMessage) -> None:
        mtype = msg.type
        if mtype == MessageType.CLIENT_JOIN:
            detail = _join_detail(msg)
            joining = detail.get("clientId", client_id)
            # canEvict=True for ordinary clients (reference upsertClient);
            # nonEvictable in the join detail opts service identities out
            # (legitimately silent for long stretches).
            inner = detail.get("detail") if isinstance(detail, dict) \
                else None
            can_evict = not (isinstance(inner, dict)
                             and inner.get("nonEvictable"))
            state.clients[joining] = ClientSeqState(
                joining, ref_seq=state.sequence_number, client_seq=0,
                can_evict=can_evict)
            self._sequence(doc_id, state, None, msg)
            return
        if mtype == MessageType.CLIENT_LEAVE:
            detail = _join_detail(msg)
            leaving = detail if isinstance(detail, str) else \
                detail.get("clientId", client_id)
            self._evicting.get(doc_id, set()).discard(leaving)
            if leaving in state.clients:
                del state.clients[leaving]
                self._sequence(doc_id, state, None, msg)
                if not state.clients:
                    noclient = DocumentMessage(
                        client_sequence_number=0,
                        reference_sequence_number=state.sequence_number,
                        type=MessageType.NO_CLIENT)
                    self._sequence(doc_id, state, None, noclient)
            return
        if client_id is None:
            # Server-generated control/system message.
            self._sequence(doc_id, state, None, msg)
            return
        entry = state.clients.get(client_id)
        if entry is None:
            self.nack(doc_id, client_id, Nack(
                msg, state.sequence_number,
                NackContent(NACK_BAD_REF_SEQ, "client not joined")))
            return
        if msg.client_sequence_number <= entry.client_seq:
            return  # duplicate (idempotent replay) — deli drops silently
        if msg.reference_sequence_number < state.minimum_sequence_number:
            self.nack(doc_id, client_id, Nack(
                msg, state.sequence_number,
                NackContent(NACK_BAD_REF_SEQ,
                            "refSeq below minimum sequence number")))
            return
        entry.client_seq = msg.client_sequence_number
        entry.ref_seq = msg.reference_sequence_number
        entry.last_update = time.time()
        self._sequence(doc_id, state, client_id, msg)

    def _evict_ghosts(self, doc_id: str, state: DocumentDeliState) -> None:
        """Synthesize leaves for writers silent past clientTimeout
        (reference deli client eviction): checked on document activity, so
        a live document cannot stay pinned behind a dead client. The leave
        goes through the raw log (replay-deterministic); without a
        producer it falls back to in-place ticketing (test harnesses)."""
        if not self.client_timeout_s:
            return
        cutoff = time.time() - self.client_timeout_s
        import json as _json
        in_flight = self._evicting.setdefault(doc_id, set())
        for client_id in [cid for cid, c in state.clients.items()
                          if c.last_update < cutoff and c.can_evict
                          and cid not in in_flight]:
            leave = DocumentMessage(
                client_sequence_number=0, reference_sequence_number=-1,
                type=MessageType.CLIENT_LEAVE,
                data=_json.dumps({"clientId": client_id,
                                  "evicted": True}))
            if self.send_system is not None:
                in_flight.add(client_id)
                # System messages enter the raw log with no client edit
                # to inherit a trace from — stamp a head-sampled root so
                # the eviction's journey joins the fleet timeline.
                tracing.stamp_message(leave, tracing.root_context())
                self.send_system(doc_id, leave)
            else:
                self._ticket(doc_id, state, None, leave)

    def _sequence(self, doc_id: str, state: DocumentDeliState,
                  client_id: Optional[str], msg: DocumentMessage) -> None:
        state.sequence_number += 1
        state.minimum_sequence_number = min(state.msn(),
                                            state.sequence_number - 1)
        sequenced = SequencedDocumentMessage.from_document_message(
            msg, client_id, state.sequence_number,
            state.minimum_sequence_number)
        # Wire-level latency trace stamp (reference deli/lambda.ts:154).
        sequenced.traces.append(ITrace.now("deli", "sequence"))
        self.emit(doc_id, sequenced)


def _join_detail(msg: DocumentMessage):
    import json
    if msg.data is not None:
        return json.loads(msg.data)
    return msg.contents or {}
