"""Scribe: the server-side summary writer.

Capability parity with reference lambdas/src/scribe/lambda.ts:40-192 — runs
a ProtocolOpHandler replica over the sequenced stream, and on a client
Summarize op validates + commits the uploaded summary to git storage, then
emits summaryAck (or summaryNack) back through the sequencer. Also persists
its own protocol-state checkpoints so a restart resumes mid-stream.
"""

from __future__ import annotations

import json
from typing import Callable, Dict, Optional

from ...protocol.messages import DocumentMessage, MessageType, \
    SequencedDocumentMessage
from ...protocol.protocol_handler import ProtocolOpHandler, ProtocolState
from ...telemetry import tracing
from ...telemetry import watermarks
from ...telemetry.counters import increment, record_swallow
from ..database import Collection
from ..log import QueuedMessage
from ..storage import GitStore, Historian
from .base import IPartitionLambda, LambdaContext


class ScribeLambda(IPartitionLambda):
    def __init__(self, context: LambdaContext, historian: Historian,
                 tenant_id: str,
                 send_system: Callable[[str, DocumentMessage], None],
                 checkpoints: Optional[Collection] = None,
                 fresh_log: bool = False,
                 on_commit: Optional[Callable[[str, str], None]] = None):
        """send_system(document_id, message) routes summaryAck/Nack back
        through deli for sequencing. fresh_log: see DeliLambda — True when
        consuming a new MessageLog with checkpoints handed over from a
        previous core (takeover), False for same-log crash-restart.
        on_commit(document_id, commit_sha): fired after a validated
        summary advances the ref — cache-tier invalidation rides this."""
        self.context = context
        self.historian = historian
        self.tenant_id = tenant_id
        self.send_system = send_system
        self.on_commit = on_commit
        self.checkpoints = checkpoints
        self.handlers: Dict[str, ProtocolOpHandler] = {}
        self.log_offsets: Dict[str, int] = {}
        if checkpoints is not None:
            # Crash restart resumes each document's protocol replica from
            # its checkpoint (duplicate sequenced ops replay as no-ops).
            for row in checkpoints.find(lambda d: "documentId" in d):
                self.load_checkpoint(row["documentId"], row)
                if fresh_log:
                    self.log_offsets[row["documentId"]] = -1

    def handler(self, message: QueuedMessage) -> None:
        value = message.value
        if hasattr(value, "messages"):
            # SequencedWindow: one record per flush; process per message
            # with the same per-document replay guard, checkpoint each
            # touched document once at the end.
            touched = set()
            for doc_id, sequenced in value.messages():
                if message.offset <= self.log_offsets.get(doc_id, -1):
                    continue
                handler = self.handlers.setdefault(doc_id,
                                                   ProtocolOpHandler())
                handler.process_message(sequenced)
                if sequenced.type == MessageType.SUMMARIZE:
                    self._handle_summarize(doc_id, sequenced)
                touched.add(doc_id)
            for doc_id in touched:
                self.log_offsets[doc_id] = message.offset
                self._checkpoint_doc(doc_id, message.offset)
            self.context.checkpoint(message.offset)
            return
        doc_id, sequenced = value
        if message.offset <= self.log_offsets.get(doc_id, -1):
            return  # replayed message already handled (mirrors deli's guard)
        handler = self.handlers.setdefault(doc_id, ProtocolOpHandler())
        handler.process_message(sequenced)
        if sequenced.type == MessageType.SUMMARIZE:
            self._handle_summarize(doc_id, sequenced)
        self.log_offsets[doc_id] = message.offset
        self.context.checkpoint(message.offset)
        self._checkpoint_doc(doc_id, message.offset)

    def _checkpoint_doc(self, doc_id: str, offset: int) -> None:
        if self.checkpoints is None:
            return
        snap = self.handlers[doc_id].snapshot()
        self.checkpoints.upsert(
            lambda d, _id=doc_id: d.get("documentId") == _id,
            {"documentId": doc_id,
             "sequenceNumber": snap.sequence_number,
             "minimumSequenceNumber": snap.minimum_sequence_number,
             "quorum": snap.quorum_snapshot,
             "logOffset": offset})

    def _handle_summarize(self, doc_id: str,
                          sequenced: SequencedDocumentMessage) -> None:
        # Summaries are rare and load-bearing: root a trace even when the
        # proposing client didn't carry one (root=True head-samples).
        with tracing.span("scribe.summarize",
                          parent=tracing.message_context(sequenced),
                          root=True, hist="scribe.summarize",
                          document=doc_id):
            self._handle_summarize_inner(doc_id, sequenced)

    def _handle_summarize_inner(self, doc_id: str,
                                sequenced: SequencedDocumentMessage
                                ) -> None:
        contents = sequenced.contents
        if isinstance(contents, str):
            contents = json.loads(contents)
        store = self.historian.store(self.tenant_id, doc_id)
        commit_sha = contents.get("handle")
        commit = store.get(commit_sha) if commit_sha else None
        if commit is None:
            nack = DocumentMessage(
                client_sequence_number=0,
                reference_sequence_number=sequenced.sequence_number,
                type=MessageType.SUMMARY_NACK,
                contents={"summaryProposal": {
                    "summarySequenceNumber": sequenced.sequence_number},
                    "errorMessage": f"unknown summary commit {commit_sha!r}"})
            tracing.stamp_message(nack, tracing.current()
                                  or tracing.root_context())
            self.send_system(doc_id, nack)
            return
        # Valid: advance the main ref and ack with the commit handle.
        store.set_ref("main", commit_sha)
        # Commit rate beside the summarize.* extraction counters: an
        # incremental-summary regression shows up as bytes/commit (or
        # blob-cache hit rate) drifting, not as a single number.
        increment("summarize.commits")
        # `summarized` watermark: ops up to the proposal's seq are now
        # covered by a committed summary (replay folds to zero).
        watermarks.advance_doc(watermarks.SUMMARIZED,
                               getattr(self.context, "partition", 0),
                               doc_id, sequenced.sequence_number)
        if self.on_commit is not None:
            try:
                self.on_commit(doc_id, commit_sha)
            except Exception:  # noqa: BLE001 — observers never break scribe
                record_swallow("scribe.commit_observer")
        ack = DocumentMessage(
            client_sequence_number=0,
            reference_sequence_number=sequenced.sequence_number,
            type=MessageType.SUMMARY_ACK,
            contents={"handle": commit_sha, "summaryProposal": {
                "summarySequenceNumber": sequenced.sequence_number}})
        # The ack re-enters the raw log as a system message: carry the
        # summarize span's context (or a fresh root) so the round trip
        # stays one joined timeline instead of going dark at the ack.
        tracing.stamp_message(ack, tracing.current()
                              or tracing.root_context())
        self.send_system(doc_id, ack)

    def load_checkpoint(self, doc_id: str, dump: dict) -> None:
        self.handlers[doc_id] = ProtocolOpHandler.load(ProtocolState(
            sequence_number=dump["sequenceNumber"],
            minimum_sequence_number=dump["minimumSequenceNumber"],
            quorum_snapshot=dump["quorum"]))
        self.log_offsets[doc_id] = dump.get("logOffset", -1)
