"""Lambda SPI (reference services-core/src/lambdas.ts:18-73):
IPartitionLambda.handler(message) processes one queued message;
IContext.checkpoint(offset) commits progress; IContext.error signals
recoverable-vs-fatal (restart => replay from last checkpoint)."""

from __future__ import annotations

from typing import Any, Callable, Optional

from ..log import MessageLog, QueuedMessage


class LambdaContext:
    def __init__(self, log: MessageLog, group: str, topic: str,
                 partition: int,
                 on_error: Optional[Callable[[Exception, bool], None]] = None):
        self.log = log
        self.group = group
        self.topic = topic
        self.partition = partition
        self._on_error = on_error
        # Batched cross-partition acks (server/sharding.py AckBatcher):
        # when the hosting tier installs a batcher, checkpoint() NOTES
        # the offset instead of committing it, and the tier flushes a
        # whole pump round's per-partition offsets in one commit_many.
        # Deferring an ack only WIDENS the crash-replay window (at-least-
        # once preserved); None (the default) keeps the eager commit.
        self.ack_batcher = None

    def checkpoint(self, offset: int) -> None:
        if self.ack_batcher is not None:
            self.ack_batcher.note(self.partition, offset)
            return
        self.log.commit(self.group, self.topic, self.partition, offset)

    def error(self, err: Exception, restart: bool) -> None:
        if self._on_error:
            self._on_error(err, restart)
        elif restart:
            raise err


class IPartitionLambda:
    def handler(self, message: QueuedMessage) -> None:
        raise NotImplementedError

    def flush(self) -> None:
        """Called by the pump after a drain pass. Batching lambdas (the TPU
        sequencer) accumulate per-message work in handler() and execute it
        here as one device batch — the reference's boxcar/batch moment
        (kafka-service/README.md: process batch N while N+1 queues)."""

    def close(self) -> None:
        pass
