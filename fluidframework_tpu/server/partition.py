"""The lambda host: runner + partition manager + checkpointing.

Capability parity with reference lambdas-driver/src/kafka-service/
(runner.ts:13, partitionManager.ts:22, partition.ts:24, checkpointManager
.ts:10): a runner consumes a topic, spawns one Partition pump per log
partition (queue + pause/resume backpressure), dispatches to the lambda,
and commits offsets so a crashed lambda replays idempotently from its last
checkpoint. The document-router's per-document sub-partitioning is folded
into the lambdas themselves here (they key state by document id).
"""

from __future__ import annotations

import threading
from typing import Callable, Dict, List, Optional

from .lambdas.base import IPartitionLambda, LambdaContext
from .log import MessageLog, QueuedMessage


class PartitionPump:
    """One partition's dispatch loop (reference partition.ts): delivers
    queued messages to the lambda in order; on error, signals the manager
    to restart from the last checkpoint."""

    def __init__(self, log: MessageLog, group: str, topic: str,
                 partition: int,
                 lambda_factory: Callable[[LambdaContext], IPartitionLambda],
                 on_error: Optional[Callable[[Exception, bool], None]] = None,
                 auto_commit: bool = True):
        self.log = log
        self.group = group
        self.topic = topic
        self.partition = partition
        self.context = LambdaContext(log, group, topic, partition, on_error)
        self.lambda_factory = lambda_factory
        self.lambda_ = lambda_factory(self.context)
        self.paused = False
        # auto_commit=False when the lambda owns its replay window (the
        # document router consolidates per-document checkpoints; an eager
        # batch commit here would shrink what a crash replays).
        self.auto_commit = auto_commit
        self._cursor = 0  # next offset to dispatch (>= committed offset)
        self._lock = threading.Lock()

    def pump(self, limit: int = 10**9) -> int:
        """Drain available messages (synchronous dispatch)."""
        if self.paused:
            return 0
        processed = 0
        partition = self.log.topic(self.topic).partitions[self.partition]
        while processed < limit:
            start = max(self._cursor,
                        self.log.committed(self.group, self.topic,
                                           self.partition))
            batch = partition.read(start, min(256, limit - processed))
            if not batch:
                break
            for msg in batch:
                try:
                    self.lambda_.handler(msg)
                except Exception as err:  # noqa: BLE001 — lambda crash path
                    self.restart()
                    self.context.error(err, restart=True)
                    return processed
                processed += 1
                self._cursor = msg.offset + 1
            if self.auto_commit:
                # Lambdas checkpoint themselves; ensure forward progress even
                # if a lambda checkpoints lazily.
                self.log.commit(self.group, self.topic, self.partition,
                                batch[-1].offset)
        if processed:
            try:
                self.lambda_.flush()
            except Exception as err:  # noqa: BLE001 — lambda crash path
                self.restart()
                self.context.error(err, restart=True)
        return processed

    def restart(self) -> None:
        """Crash recovery: rebuild the lambda; the next pump replays from
        the last committed offset (idempotent handlers absorb the replay)."""
        self.lambda_.close()
        # With batched acks (server/sharding.py) the lambda's checkpoint
        # STATE may be ahead of the committed offset (the ack is noted,
        # not yet flushed). The rebuilt lambda restores that state with
        # its per-doc replay guards reset (fresh_log), so an unflushed
        # ack would make the replay window overlap the restored state —
        # already-sequenced joins would re-sequence. Flush AFTER close()
        # (close's own checkpoint notes one more ack) so state and
        # offset agree again, exactly like the eager-commit pipeline.
        batcher = getattr(self.context, "ack_batcher", None)
        if batcher is not None:
            batcher.flush()
        self.lambda_ = self.lambda_factory(self.context)
        self._cursor = self.log.committed(self.group, self.topic,
                                          self.partition)

    def pause(self) -> None:
        self.paused = True

    def resume(self) -> None:
        self.paused = False


class PartitionManager:
    """Spawns a pump per partition of a topic (partitionManager.ts:22)."""

    def __init__(self, log: MessageLog, group: str, topic: str,
                 lambda_factory: Callable[[LambdaContext], IPartitionLambda],
                 auto_commit: bool = True, offload: bool = False,
                 partitions: Optional[List[int]] = None):
        self.log = log
        # offload=True marks a pure-persistence stage (scriptorium/scribe/
        # copier): safe to pump on a worker thread because it never calls
        # back into client connections. Interactive stages (deli nacks,
        # broadcaster delivery) re-enter client locks and MUST pump on the
        # submitting thread (same-thread RLock reentrancy).
        self.offload = offload
        self.pumps: Dict[int, PartitionPump] = {}
        topic_obj = log.topic(topic)
        # partitions=None owns the whole topic (the single-host shape);
        # an explicit subset is the cross-host placement config — each
        # worker process pumps only ITS partitions against the shared
        # remote broker (deploy/RUNBOOK.md multi-host recipe).
        owned = range(len(topic_obj.partitions)) if partitions is None \
            else sorted({int(p) for p in partitions})
        for p in owned:
            if not 0 <= p < len(topic_obj.partitions):
                raise ValueError(
                    f"owned partition {p} out of range for topic "
                    f"{topic!r} with {len(topic_obj.partitions)} "
                    "partitions")
            self.pumps[p] = PartitionPump(log, group, topic, p,
                                          lambda_factory,
                                          auto_commit=auto_commit)

    def pump_all(self) -> int:
        return sum(p.pump() for p in self.pumps.values())

    def restart(self) -> None:
        """Crash-restart every partition's lambda (fresh instances rebuilt
        from their checkpoint stores; consumer offsets are preserved)."""
        for pump in self.pumps.values():
            pump.restart()

    def lambdas(self) -> List[IPartitionLambda]:
        return [p.lambda_ for p in self.pumps.values()]


class LambdaRunner:
    """Hosts several PartitionManagers and pumps them round-robin — the
    single-process stand-in for the reference's one-service-per-lambda
    deployment (docker-compose.yml), preserving the pipeline-parallel shape:
    each stage drains independently against its own consumer group."""

    def __init__(self):
        self.managers: List[PartitionManager] = []
        # Epoch-cadence side work that rides the pump loop without being
        # a consumer-group stage (the read-tier artifact push-through):
        # each ticker is a callable returning work done; tickers run at
        # quiescence so they see flush-boundary state, and they rate-
        # limit themselves (a ticker firing every pump would turn the
        # idle poll loop busy).
        self.tickers: List[Callable[[], int]] = []

    def add(self, manager: PartitionManager) -> PartitionManager:
        self.managers.append(manager)
        return manager

    def add_ticker(self, ticker: Callable[[], int]) -> None:
        self.tickers.append(ticker)

    def _tick(self) -> int:
        return sum(t() for t in self.tickers)

    def pump(self) -> int:
        total = 0
        while True:
            n = sum(m.pump_all() for m in self.managers)
            total += n
            if n == 0:
                return total + self._tick()

    def close(self) -> None:
        pass


class OverlappedLambdaRunner(LambdaRunner):
    """Pipeline-stage overlap (reference kafka-service/README.md:58-60:
    "process batch N+1 while batch N's DB writes are in flight"): each
    round pumps the offload-marked persistence stages on worker threads
    concurrently with the interactive stages inline, so the sequencer
    drains batch N+1 while scriptorium/scribe flush batch N. pump() stays
    synchronous (returns at quiescence), keeping the serial runner's
    crash/replay semantics; within a round the stage wall-clock is
    max(inline, slowest-offloaded), not the sum.

    Only managers with offload=True move off-thread: stages that call back
    into client connections (broadcaster delivery, deli nacks) re-enter
    client-side locks held by the submitting thread and would deadlock on
    a worker."""

    def __init__(self):
        super().__init__()
        self._pool = None

    def _ensure_pool(self):
        if self._pool is None:
            from concurrent.futures import ThreadPoolExecutor
            self._pool = ThreadPoolExecutor(
                max_workers=max(len(self.managers), 1),
                thread_name_prefix="lambda-stage")
        return self._pool

    def pump(self) -> int:
        pool = self._ensure_pool()
        total = 0
        while True:
            futures = [pool.submit(m.pump_all)
                       for m in self.managers if m.offload]
            n = sum(m.pump_all() for m in self.managers if not m.offload)
            n += sum(f.result() for f in futures)
            total += n
            if n == 0:
                return total + self._tick()

    def close(self) -> None:
        if self._pool is not None:
            self._pool.shutdown(wait=False)
            self._pool = None
