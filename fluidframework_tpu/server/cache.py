"""Cache policy for the historian tier: bounded LRU + TTL, O(1) per op.

Capability parity with the reference historian's Redis front
(server/historian: RedisCache get/set with an expiry), restated as an
in-process policy module so the tier has no external service dependency.
The design discipline follows the serving literature (ISSUE refs): every
cache operation is constant time — an OrderedDict recency list, lazy TTL
expiry on access, and byte/entry ceilings enforced by popping from the
cold end — so the cache can never become the request path's long pole.

Two usage profiles in `server/historian.py`:
  - object cache: content-addressed (sha-keyed) immutable git objects;
    no TTL needed for correctness, bounded by bytes/entries only.
  - ref cache: mutable ref -> commit pointers; short TTL bounds staleness
    for writers that bypass the tier, explicit invalidation covers
    write-through commits.
"""

from __future__ import annotations

import threading
import time
from collections import OrderedDict
from typing import Any, Dict, Hashable, Optional, Tuple


class LruTtlCache:
    """Thread-safe LRU cache with optional per-entry TTL and byte budget.

    Counters (cumulative): hits, misses, evictions (capacity), expirations
    (TTL), invalidations (explicit), puts. `bytes` tracks the CURRENT
    cached payload size, `bytes_served` the cumulative hit payload.
    """

    def __init__(self, max_entries: int = 4096,
                 max_bytes: int = 64 * 1024 * 1024,
                 ttl_s: Optional[float] = None):
        self.max_entries = max_entries
        self.max_bytes = max_bytes
        self.ttl_s = ttl_s
        # key -> (value, nbytes, expires_at|None); OrderedDict end = hottest.
        self._entries: "OrderedDict[Hashable, Tuple[Any, int, Optional[float]]]" \
            = OrderedDict()
        self._lock = threading.Lock()
        self.hits = 0
        self.misses = 0
        self.evictions = 0
        self.expirations = 0
        self.invalidations = 0
        self.puts = 0
        self.bytes = 0
        self.bytes_served = 0

    def get(self, key: Hashable) -> Optional[Any]:
        """Returns the cached value or None. Expired entries drop here
        (lazy expiry keeps every op O(1) — no sweeper thread)."""
        now = time.monotonic()
        with self._lock:
            entry = self._entries.get(key)
            if entry is None:
                self.misses += 1
                return None
            value, nbytes, expires_at = entry
            if expires_at is not None and now >= expires_at:
                del self._entries[key]
                self.bytes -= nbytes
                self.expirations += 1
                self.misses += 1
                return None
            self._entries.move_to_end(key)
            self.hits += 1
            self.bytes_served += nbytes
            return value

    def contains(self, key: Hashable) -> bool:
        """Non-counting presence probe (no hit/miss accounting, no LRU
        touch): callers deciding whether work CAN be skipped — e.g. the
        historian's shared-subtree prefetch cutoff — must not skew the
        hit-rate stats operators alert on."""
        now = time.monotonic()
        with self._lock:
            entry = self._entries.get(key)
            if entry is None:
                return False
            expires_at = entry[2]
            return expires_at is None or now < expires_at

    def put(self, key: Hashable, value: Any, nbytes: int = 0,
            ttl_s: Optional[float] = -1.0) -> None:
        """ttl_s: -1.0 (default) inherits the cache-level TTL; None pins
        the entry until evicted/invalidated; a float overrides."""
        ttl = self.ttl_s if ttl_s == -1.0 else ttl_s
        expires_at = (time.monotonic() + ttl) if ttl is not None else None
        with self._lock:
            self._put_locked(key, value, nbytes, expires_at)

    def _put_locked(self, key: Hashable, value: Any, nbytes: int,
                    expires_at: Optional[float]) -> None:
        """Entry write + ceiling enforcement; caller holds self._lock
        (shared by put and put_if_newer so the eviction policy cannot
        fork between them)."""
        old = self._entries.pop(key, None)
        if old is not None:
            self.bytes -= old[1]
        self._entries[key] = (value, nbytes, expires_at)
        self.bytes += nbytes
        self.puts += 1
        while (len(self._entries) > self.max_entries
               or (self.bytes > self.max_bytes
                   and len(self._entries) > 1)):
            _, (_, cold_bytes, _) = self._entries.popitem(last=False)
            self.bytes -= cold_bytes
            self.evictions += 1

    def put_if_newer(self, key: Hashable, value: Any, version: int,
                     nbytes: int = 0,
                     ttl_s: Optional[float] = -1.0) -> bool:
        """Conditional put keyed on a monotone per-entry version (the
        catch-up delta-blob profile, server/readpath.py): a publish that
        lost a race to a FRESHER artifact must never regress the cache,
        because a reader adopting the older blob would replay a longer
        residue tail than the one already served. The version rides the
        entry as (version, value); `get` callers receive the tuple and
        unwrap. Returns True when the entry was written."""
        ttl = self.ttl_s if ttl_s == -1.0 else ttl_s
        expires_at = (time.monotonic() + ttl) if ttl is not None else None
        with self._lock:
            old = self._entries.get(key)
            if old is not None:
                held = old[0]
                if isinstance(held, tuple) and len(held) == 2 \
                        and held[0] > version:
                    return False
            self._put_locked(key, (version, value), nbytes, expires_at)
        return True

    def peek_version(self, key: Hashable) -> Optional[int]:
        """Non-counting version probe for put_if_newer entries (no LRU
        touch, no hit/miss accounting): freshness gates — e.g. the
        catch-up refresh-on-read decision — must not skew the hit-rate
        stats operators alert on."""
        now = time.monotonic()
        with self._lock:
            entry = self._entries.get(key)
            if entry is None:
                return None
            held, _nbytes, expires_at = entry
            if expires_at is not None and now >= expires_at:
                return None
            if isinstance(held, tuple) and len(held) == 2:
                return int(held[0])
            return None

    def invalidate(self, key: Hashable) -> bool:
        with self._lock:
            entry = self._entries.pop(key, None)
            if entry is None:
                return False
            self.bytes -= entry[1]
            self.invalidations += 1
            return True

    def clear(self) -> int:
        with self._lock:
            n = len(self._entries)
            self._entries.clear()
            self.bytes = 0
            self.invalidations += n
            return n

    def __len__(self) -> int:
        with self._lock:
            return len(self._entries)

    def stats(self) -> Dict[str, Any]:
        with self._lock:
            total = self.hits + self.misses
            return {
                "entries": len(self._entries),
                "bytes": self.bytes,
                "bytesServed": self.bytes_served,
                "hits": self.hits,
                "misses": self.misses,
                "hitRate": (self.hits / total) if total else 0.0,
                "evictions": self.evictions,
                "expirations": self.expirations,
                "invalidations": self.invalidations,
                "puts": self.puts,
            }
