"""The ordering service (reference Routerlicious, SURVEY.md §2.5):
deli sequencing (host lambda + device ticket kernel), scriptorium persistence,
scribe summaries, broadcaster fan-out, the partition lambda host, in-memory
log ("LocalKafka"), content-addressed storage (gitrest/historian), and the
local server that wires it together for tests and dev."""
