"""Op-tensor bridge: gRPC ingress for packed op batches.

Capability parity with the reference's client↔service wire at partition
scale (SURVEY.md §2.7 / BASELINE north star: "Node↔Python gRPC bridge with
packed op tensors... the gRPC hop must amortize via partition-sized
batches"): an external producer — a JS front door, another host, a replay
rig — ships a whole partition batch of ops as ONE packed int32 tensor
frame; the bridge runs the fused device pipeline (ticket + apply + summary
lengths) and returns the ticketed assignments in one packed reply.

No protoc codegen: methods are registered with identity (bytes) serializers
and a fixed little-endian frame layout, so any language with a gRPC client
and a struct packer can speak it:

  request  := header(int32 x2: n_docs, n_steps) ++ 10 column tensors
              (PackedOps field order, int32 [n_docs, n_steps], C order)
  response := header(int32 x2) ++ seq[int32 B,T] ++ min_seq[int32 B,T]
              ++ nack[int32 B,T] ++ total_len[int32 B]

Sessions are keyed by metadata ("session-id"); each session owns persistent
device state, so successive batches continue the same documents.
"""

from __future__ import annotations

import threading
from concurrent import futures
from typing import Dict, Optional, Tuple

import jax
import numpy as np

SERVICE = "fluidframework.OpBridge"
_HEADER = np.dtype("<i4")


def encode_ops(cols: Dict[str, np.ndarray]) -> bytes:
    """Pack gen_traces-style columns (PackedOps field order) into a frame."""
    from ..mergetree.oppack import PackedOps
    first = cols[PackedOps._fields[0]]
    b, t = first.shape
    parts = [np.asarray([b, t], dtype=_HEADER).tobytes()]
    for field in PackedOps._fields:
        col = np.ascontiguousarray(cols[field], dtype=np.int32)
        assert col.shape == (b, t), f"column {field} shape {col.shape}"
        parts.append(col.tobytes())
    return b"".join(parts)


def decode_ops(frame: bytes):
    from ..mergetree.oppack import PackedOps
    b, t = np.frombuffer(frame, dtype=_HEADER, count=2)
    size = int(b) * int(t) * 4
    offset = 8
    cols = {}
    for field in PackedOps._fields:
        cols[field] = np.frombuffer(
            frame, dtype=np.int32, count=b * t, offset=offset
        ).reshape(b, t)
        offset += size
    return int(b), int(t), cols


def encode_reply(seq: np.ndarray, min_seq: np.ndarray, nack: np.ndarray,
                 total_len: np.ndarray) -> bytes:
    b, t = seq.shape
    return b"".join([
        np.asarray([b, t], dtype=_HEADER).tobytes(),
        np.ascontiguousarray(seq, np.int32).tobytes(),
        np.ascontiguousarray(min_seq, np.int32).tobytes(),
        np.ascontiguousarray(nack, np.int32).tobytes(),
        np.ascontiguousarray(total_len, np.int32).tobytes(),
    ])


def decode_reply(frame: bytes):
    b, t = np.frombuffer(frame, dtype=_HEADER, count=2)
    b, t = int(b), int(t)
    n = b * t
    seq = np.frombuffer(frame, np.int32, n, 8).reshape(b, t)
    min_seq = np.frombuffer(frame, np.int32, n, 8 + 4 * n).reshape(b, t)
    nack = np.frombuffer(frame, np.int32, n, 8 + 8 * n).reshape(b, t)
    total = np.frombuffer(frame, np.int32, b, 8 + 12 * n)
    return {"seq": seq, "minSeq": min_seq, "nack": nack, "totalLen": total}


class _Session:
    def __init__(self, n_docs: int, capacity: int):
        from ..mergetree.state import make_state
        from . import ticket_kernel as tk
        self.tstate = tk.make_ticket_state(8, batch=n_docs)
        self.mstate = make_state(capacity, 1, batch=n_docs)
        self.lock = threading.Lock()


class OpBridgeServer:
    def __init__(self, capacity: int = 256, port: int = 0,
                 max_workers: int = 4):
        import grpc
        from .pipeline import full_step
        # donate both threaded states: _submit_batch overwrites
        # session.tstate/mstate with the step result, so the previous
        # buffers are dead the moment the call returns — donation halves
        # the bridge's peak device footprint per session.
        self._step = jax.jit(full_step, donate_argnums=(0, 1))
        self.capacity = capacity
        self.sessions: Dict[Tuple[str, int], _Session] = {}
        self._lock = threading.Lock()
        service = self

        class Handler(grpc.GenericRpcHandler):
            def service(self, handler_call_details):
                if handler_call_details.method == f"/{SERVICE}/SubmitBatch":
                    return grpc.unary_unary_rpc_method_handler(
                        service._submit_batch)
                if handler_call_details.method == f"/{SERVICE}/Ping":
                    return grpc.unary_unary_rpc_method_handler(
                        lambda req, ctx: b"pong")
                return None

        self._server = grpc.server(
            futures.ThreadPoolExecutor(max_workers=max_workers))
        self._server.add_generic_rpc_handlers((Handler(),))
        self.port = self._server.add_insecure_port(f"127.0.0.1:{port}")

    def start(self) -> "OpBridgeServer":
        self._server.start()
        return self

    def stop(self) -> None:
        self._server.stop(grace=1)

    @property
    def address(self) -> str:
        return f"127.0.0.1:{self.port}"

    # -- the one hot RPC ----------------------------------------------------
    def _submit_batch(self, request: bytes, context) -> bytes:
        import jax.numpy as jnp
        from ..mergetree.oppack import PackedOps
        from . import ticket_kernel as tk
        session_id = dict(context.invocation_metadata()).get(
            "session-id", "default")
        b, t, cols = decode_ops(request)
        key = (session_id, b)
        with self._lock:
            session = self.sessions.get(key)
            if session is None:
                session = _Session(b, self.capacity)
                self.sessions[key] = session
        ops = PackedOps(**{f: jnp.asarray(cols[f])
                           for f in PackedOps._fields})
        raw = tk.RawOps(client=ops.client, client_seq=ops.seq,
                        ref_seq=ops.ref_seq)
        with session.lock:
            try:
                session.tstate, session.mstate, ticketed, total_len = \
                    self._step(session.tstate, session.mstate, raw, ops)
            except Exception:
                # The step donates tstate/mstate: a runtime execution
                # failure has already consumed those buffers, so the
                # session can never step again — evict it (the next
                # SubmitBatch for this key rebuilds fresh state) instead
                # of poisoning every future RPC with deleted-array errors.
                with self._lock:
                    self.sessions.pop(key, None)
                raise
            seq = np.asarray(ticketed.seq)
            min_seq = np.asarray(ticketed.min_seq)
            nack = np.asarray(ticketed.nacked).astype(np.int32)
            total = np.asarray(total_len)
        return encode_reply(seq, min_seq, nack, total)


class OpBridgeClient:
    def __init__(self, address: str, session_id: str = "default"):
        import grpc
        self._channel = grpc.insecure_channel(address)
        self.session_id = session_id
        self._submit = self._channel.unary_unary(
            f"/{SERVICE}/SubmitBatch",
            request_serializer=lambda b: b,
            response_deserializer=lambda b: b)
        self._ping = self._channel.unary_unary(
            f"/{SERVICE}/Ping",
            request_serializer=lambda b: b,
            response_deserializer=lambda b: b)

    def ping(self) -> bool:
        return self._ping(b"") == b"pong"

    def submit_batch(self, cols: Dict[str, np.ndarray]) -> dict:
        reply = self._submit(encode_ops(cols),
                             metadata=(("session-id", self.session_id),))
        return decode_reply(reply)

    def close(self) -> None:
        self._channel.close()
