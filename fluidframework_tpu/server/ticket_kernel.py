"""Deli ticketing as a batched device kernel.

The reference sequencer (server/routerlicious/packages/lambdas/src/deli/
lambda.ts:142-224 ticket()) assigns each raw op a sequenceNumber and a
minimumSequenceNumber (min over per-client refSeqs held in a heap,
clientSeqManager.ts), nacks bad refSeqs, and drops duplicate clientSeqs.

Here a whole partition tickets in one jit: ops are packed [B, T] (documents
x time, NOOP-padded), per-document sequencing state is a fixed-size client
table (the heap becomes a masked min over a [B, K] table), and lax.scan
walks the time axis while vmap covers documents — the same shape discipline
as the merge-tree kernel, so deli + apply fuse into one device pipeline.
"""

from __future__ import annotations

import functools
from typing import NamedTuple, Tuple

import jax
import jax.numpy as jnp

INT32_MAX = 2**31 - 1


class MsgKind:
    """Wire message classes the sequencer distinguishes (deli/lambda.ts:179
    branches on MessageType): OP covers every client-authored message
    (op/summarize/propose — they all ticket identically), JOIN/LEAVE mutate
    the client table, SYSTEM is a server-generated message (NoClient,
    summaryAck) that sequences unconditionally with no client entry."""

    NOOP = 0
    OP = 1
    JOIN = 2
    LEAVE = 3
    SYSTEM = 4


class TicketState(NamedTuple):
    """Per-document sequencing state (leading batch axis when batched).

    client_ids   [K] connected client ordinals (-1 = free slot)
    client_ref   [K] each client's latest referenceSequenceNumber
    client_cseq  [K] each client's last clientSequenceNumber (dup/gap guard)
    next_seq     []  next sequenceNumber to assign
    min_seq      []  current minimumSequenceNumber
    overflow     []  bool: a JOIN arrived with no free client slot (the host
                     must re-shard that document at a larger K; semantics
                     stay correct-by-flag, like the merge kernel's overflow)
    """

    client_ids: jnp.ndarray
    client_ref: jnp.ndarray
    client_cseq: jnp.ndarray
    next_seq: jnp.ndarray
    min_seq: jnp.ndarray
    overflow: jnp.ndarray


class RawOps(NamedTuple):
    """Unsequenced client ops, [B, T] (or [T] unbatched), NOOP = client -1.

    kind (optional [B, T] MsgKind column): when None, every op with
    client >= 0 is an OP and unknown clients auto-join on first op (the
    bench/bridge shape). With a kind column, JOIN/LEAVE/SYSTEM messages
    sequence on device too — the full deli state machine in one scan."""

    client: jnp.ndarray
    client_seq: jnp.ndarray
    ref_seq: jnp.ndarray
    kind: jnp.ndarray | None = None


class Ticketed(NamedTuple):
    """Per-op ticketing results, same shape as the input RawOps."""

    seq: jnp.ndarray      # assigned sequence number (0 for nacked/noop)
    min_seq: jnp.ndarray  # msn stamped on the op
    nacked: jnp.ndarray   # bool: refSeq below window or client not joined
    # (duplicate clientSeqs are dropped silently — seq stays 0, nacked stays
    # False — matching the host deli's idempotent-replay behavior)
    not_joined: jnp.ndarray  # bool: nack was for an un-joined client
    empty_after: jnp.ndarray  # bool: client table empty after this message
    # (drives the host's NoClient emission with exact deli timing)


def make_ticket_state(clients_capacity: int, batch: int | None = None
                      ) -> TicketState:
    def shape(*dims):
        return dims if batch is None else (batch, *dims)
    return TicketState(
        client_ids=jnp.full(shape(clients_capacity), -1, jnp.int32),
        client_ref=jnp.full(shape(clients_capacity), INT32_MAX, jnp.int32),
        client_cseq=jnp.zeros(shape(clients_capacity), jnp.int32),
        next_seq=jnp.ones(shape(), jnp.int32),
        min_seq=jnp.zeros(shape(), jnp.int32),
        overflow=jnp.zeros(shape(), jnp.bool_),
    )


def _ticket_one(s: TicketState, kind, client, client_seq, ref_seq,
                require_join: bool) -> Tuple[TicketState, Tuple]:
    """Ticket one message for one document (deli/lambda.ts:179-224): the
    whole deli branch structure — join/leave table updates, dup drop, stale
    nack, seq/MSN assignment — as masked updates on the client table."""
    k = s.client_ids.shape[-1]
    has_client = client >= 0
    is_op = (kind == MsgKind.OP) & has_client
    is_join = (kind == MsgKind.JOIN) & has_client
    is_leave = (kind == MsgKind.LEAVE) & has_client
    is_system = kind == MsgKind.SYSTEM

    # Leave first: evict the client from the MSN calculation (deli.py
    # CLIENT_LEAVE; clientSeqManager canEvict). An unknown leaver is dropped.
    gone = is_leave & (s.client_ids == client)
    ids0 = jnp.where(gone, -1, s.client_ids)
    ref0 = jnp.where(gone, INT32_MAX, s.client_ref)
    leave_ok = is_leave & jnp.any(gone)

    slot_mask = ids0 == client
    known = has_client & jnp.any(slot_mask)
    free = ids0 == -1
    have_free = jnp.any(free)
    slot = jnp.where(known, jnp.argmax(slot_mask), jnp.argmax(free))

    # OP admission. Without an explicit-join wire (kind=None), unknown
    # clients auto-join on first op; with it, they nack ("client not
    # joined", deli.py).
    auto_join = is_op & ~known & have_free & (not require_join)
    active = (is_op & known) | auto_join
    prev_cseq = jnp.where(known, s.client_cseq[slot], 0)
    # Duplicate clientSeq: silently dropped, NOT nacked — matching the host
    # deli (deli.py), so an at-least-once log replay is benign on both
    # paths. The dup check wins over the stale-refSeq nack (deli.py checks
    # duplicate first): a redelivered already-sequenced op whose refSeq has
    # since fallen below the MSN must stay a silent drop, not a nack.
    dup = is_op & known & (client_seq <= prev_cseq)
    # refSeq must sit inside the collab window (deli nacks stale refs).
    stale = is_op & (ref_seq < s.min_seq) & ~dup
    not_joined = is_op & ~active
    nacked = stale | not_joined
    op_ticket = is_op & ~dup & ~nacked

    # JOIN: place (or refresh) the client entry with refSeq = the sequence
    # number just before the join op's own (deli.py CLIENT_JOIN). A full
    # table still sequences the join but flags overflow.
    join_ok = is_join & (known | have_free)
    join_full = is_join & ~known & ~have_free

    onehot = jnp.arange(k) == slot
    upd_op = op_ticket & onehot
    upd_join = join_ok & onehot
    client_ids = jnp.where(upd_op | upd_join, client, ids0)
    client_ref = jnp.where(upd_op, ref_seq,
                           jnp.where(upd_join, s.next_seq - 1, ref0))
    client_cseq = jnp.where(upd_op, client_seq,
                            jnp.where(upd_join, 0, s.client_cseq))

    ticket = op_ticket | join_ok | join_full | leave_ok | is_system
    seq = jnp.where(ticket, s.next_seq, 0)
    # MSN: min over active clients' refSeqs (clientSeqManager heap min);
    # monotone non-decreasing, clamped below the just-assigned seq so a
    # future-dated refSeq cannot poison the window (host deli applies the
    # same min(msn, seq-1) clamp in _sequence).
    active_refs = jnp.where(client_ids >= 0, client_ref, INT32_MAX)
    heap_min = jnp.min(active_refs)
    msn = jnp.where(heap_min == INT32_MAX, s.min_seq,
                    jnp.maximum(s.min_seq, heap_min))
    msn = jnp.minimum(msn, s.next_seq - 1)
    s2 = TicketState(
        client_ids=client_ids,
        client_ref=client_ref,
        client_cseq=client_cseq,
        next_seq=jnp.where(ticket, s.next_seq + 1, s.next_seq),
        min_seq=jnp.where(ticket, msn, s.min_seq),
        overflow=s.overflow | join_full,
    )
    empty_after = ~jnp.any(client_ids >= 0)
    return s2, (seq, s2.min_seq, nacked, not_joined, empty_after)


def _leave_one(s: TicketState, client) -> TicketState:
    """Evict a client from the MSN calculation (deli canEvict / leave)."""
    gone = s.client_ids == client
    return s._replace(
        client_ids=jnp.where(gone, -1, s.client_ids),
        client_ref=jnp.where(gone, INT32_MAX, s.client_ref),
    )


def _scan_tickets(state: TicketState, ops: RawOps, batched: bool,
                  require_join: bool = False) -> Tuple[TicketState, Ticketed]:
    steps = ops.client.shape[-1]
    # No kind column: every op row (client >= 0) is an OP (bench/bridge).
    kind = ops.kind if ops.kind is not None else jnp.where(
        ops.client >= 0, MsgKind.OP, MsgKind.NOOP).astype(jnp.int32)

    def body(s, t):
        if batched:
            s2, out = jax.vmap(
                lambda sd, kd, c, cs, r: _ticket_one(
                    sd, kd[t], c[t], cs[t], r[t], require_join)
            )(s, kind, ops.client, ops.client_seq, ops.ref_seq)
        else:
            s2, out = _ticket_one(s, kind[t], ops.client[t],
                                  ops.client_seq[t], ops.ref_seq[t],
                                  require_join)
        return s2, out

    state, outs = jax.lax.scan(
        body, state, jnp.arange(steps, dtype=jnp.int32))
    # scan stacks on axis 0 (time); move time last to match [B, T] layout.
    if batched:
        outs = tuple(jnp.moveaxis(x, 0, -1) for x in outs)
    return state, Ticketed(*outs)


@jax.jit
def ticket_ops(state: TicketState, ops: RawOps
               ) -> Tuple[TicketState, Ticketed]:
    """Ticket a [T] stream for one document."""
    return _scan_tickets(state, ops, batched=False)


@functools.partial(jax.jit, donate_argnums=(0,))
def ticket_ops_batched(state: TicketState, ops: RawOps
                       ) -> Tuple[TicketState, Ticketed]:
    """Ticket [B, T] streams for B documents in one jit."""
    return _scan_tickets(state, ops, batched=True)


@functools.partial(jax.jit, donate_argnums=(0,))
def sequence_batched_strict(state: TicketState, ops: RawOps
                            ) -> Tuple[TicketState, Ticketed]:
    """The serving-path sequencer: [B, T] message streams WITH a MsgKind
    column — joins/leaves/system messages sequence on device and un-joined
    clients nack, exactly the host DeliLambda contract."""
    return _scan_tickets(state, ops, batched=True, require_join=True)


@jax.jit
def evict_clients_batched(state: TicketState, clients: jnp.ndarray
                          ) -> TicketState:
    """Evict one client per document ([B] array, -1 = none)."""
    return jax.vmap(_leave_one)(state, clients)
