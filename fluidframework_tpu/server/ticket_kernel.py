"""Deli ticketing as a batched device kernel.

The reference sequencer (server/routerlicious/packages/lambdas/src/deli/
lambda.ts:142-224 ticket()) assigns each raw op a sequenceNumber and a
minimumSequenceNumber (min over per-client refSeqs held in a heap,
clientSeqManager.ts), nacks bad refSeqs, and drops duplicate clientSeqs.

Here a whole partition tickets in one jit: ops are packed [B, T] (documents
x time, NOOP-padded), per-document sequencing state is a fixed-size client
table (the heap becomes a masked min over a [B, K] table), and lax.scan
walks the time axis while vmap covers documents — the same shape discipline
as the merge-tree kernel, so deli + apply fuse into one device pipeline.
"""

from __future__ import annotations

import functools
from typing import NamedTuple, Tuple

import jax
import jax.numpy as jnp

INT32_MAX = 2**31 - 1


class TicketState(NamedTuple):
    """Per-document sequencing state (leading batch axis when batched).

    client_ids   [K] connected client ordinals (-1 = free slot)
    client_ref   [K] each client's latest referenceSequenceNumber
    client_cseq  [K] each client's last clientSequenceNumber (dup/gap guard)
    next_seq     []  next sequenceNumber to assign
    min_seq      []  current minimumSequenceNumber
    """

    client_ids: jnp.ndarray
    client_ref: jnp.ndarray
    client_cseq: jnp.ndarray
    next_seq: jnp.ndarray
    min_seq: jnp.ndarray


class RawOps(NamedTuple):
    """Unsequenced client ops, [B, T] (or [T] unbatched), NOOP = client -1."""

    client: jnp.ndarray
    client_seq: jnp.ndarray
    ref_seq: jnp.ndarray


class Ticketed(NamedTuple):
    """Per-op ticketing results, same shape as the input RawOps."""

    seq: jnp.ndarray      # assigned sequence number (0 for nacked/noop)
    min_seq: jnp.ndarray  # msn stamped on the op
    nacked: jnp.ndarray   # bool: refSeq below window or client not joined
    # (duplicate clientSeqs are dropped silently — seq stays 0, nacked stays
    # False — matching the host deli's idempotent-replay behavior)


def make_ticket_state(clients_capacity: int, batch: int | None = None
                      ) -> TicketState:
    def shape(*dims):
        return dims if batch is None else (batch, *dims)
    return TicketState(
        client_ids=jnp.full(shape(clients_capacity), -1, jnp.int32),
        client_ref=jnp.full(shape(clients_capacity), INT32_MAX, jnp.int32),
        client_cseq=jnp.zeros(shape(clients_capacity), jnp.int32),
        next_seq=jnp.ones(shape(), jnp.int32),
        min_seq=jnp.zeros(shape(), jnp.int32),
    )


def _ticket_one(s: TicketState, client, client_seq, ref_seq
                ) -> Tuple[TicketState, Tuple]:
    """Ticket one op for one document (deli/lambda.ts:224 ticket())."""
    is_op = client >= 0
    k = s.client_ids.shape[-1]
    slot_mask = s.client_ids == client
    known = is_op & jnp.any(slot_mask)
    slot = jnp.argmax(slot_mask)
    # Unknown client joins the table at the first free slot (the reference
    # creates the heap entry on first op / join).
    free = s.client_ids == -1
    join_slot = jnp.argmax(free)
    can_join = is_op & ~known & jnp.any(free)
    slot = jnp.where(known, slot, join_slot)
    active = known | can_join

    prev_cseq = jnp.where(known, s.client_cseq[slot], 0)
    # Duplicate clientSeq: silently dropped, NOT nacked — matching the host
    # deli (deli.py), so an at-least-once log replay is benign on both paths.
    dup = known & (client_seq <= prev_cseq)
    # refSeq must sit inside the collab window (deli nacks stale refs).
    stale = is_op & (ref_seq < s.min_seq)
    nacked = is_op & (stale | ~active)
    ticket = is_op & ~dup & ~nacked

    seq = jnp.where(ticket, s.next_seq, 0)
    onehot = jnp.arange(k) == slot
    upd = ticket & onehot
    client_ids = jnp.where(upd, client, s.client_ids)
    client_ref = jnp.where(upd, ref_seq, s.client_ref)
    client_cseq = jnp.where(upd, client_seq, s.client_cseq)
    # MSN: min over active clients' refSeqs (clientSeqManager heap min);
    # monotone non-decreasing, clamped below the just-assigned seq so a
    # future-dated refSeq cannot poison the window (host deli applies the
    # same min(msn, seq-1) clamp in _sequence).
    active_refs = jnp.where(client_ids >= 0, client_ref, INT32_MAX)
    heap_min = jnp.min(active_refs)
    msn = jnp.where(heap_min == INT32_MAX, s.min_seq,
                    jnp.maximum(s.min_seq, heap_min))
    msn = jnp.minimum(msn, s.next_seq - 1)
    s2 = TicketState(
        client_ids=client_ids,
        client_ref=client_ref,
        client_cseq=client_cseq,
        next_seq=jnp.where(ticket, s.next_seq + 1, s.next_seq),
        min_seq=jnp.where(ticket, msn, s.min_seq),
    )
    return s2, (seq, s2.min_seq, nacked)


def _leave_one(s: TicketState, client) -> TicketState:
    """Evict a client from the MSN calculation (deli canEvict / leave)."""
    gone = s.client_ids == client
    return s._replace(
        client_ids=jnp.where(gone, -1, s.client_ids),
        client_ref=jnp.where(gone, INT32_MAX, s.client_ref),
    )


def _scan_tickets(state: TicketState, ops: RawOps, batched: bool
                  ) -> Tuple[TicketState, Ticketed]:
    steps = ops.client.shape[-1]

    def body(s, t):
        if batched:
            s2, out = jax.vmap(
                lambda sd, c, cs, r: _ticket_one(sd, c[t], cs[t], r[t])
            )(s, ops.client, ops.client_seq, ops.ref_seq)
        else:
            s2, out = _ticket_one(s, ops.client[t], ops.client_seq[t],
                                  ops.ref_seq[t])
        return s2, out

    state, (seq, msn, nacked) = jax.lax.scan(
        body, state, jnp.arange(steps, dtype=jnp.int32))
    # scan stacks on axis 0 (time); move time last to match [B, T] layout.
    if batched:
        seq, msn, nacked = (jnp.moveaxis(x, 0, -1) for x in (seq, msn, nacked))
    return state, Ticketed(seq=seq, min_seq=msn, nacked=nacked)


@jax.jit
def ticket_ops(state: TicketState, ops: RawOps
               ) -> Tuple[TicketState, Ticketed]:
    """Ticket a [T] stream for one document."""
    return _scan_tickets(state, ops, batched=False)


@functools.partial(jax.jit, donate_argnums=(0,))
def ticket_ops_batched(state: TicketState, ops: RawOps
                       ) -> Tuple[TicketState, Ticketed]:
    """Ticket [B, T] streams for B documents in one jit."""
    return _scan_tickets(state, ops, batched=True)


@jax.jit
def evict_clients_batched(state: TicketState, clients: jnp.ndarray
                          ) -> TicketState:
    """Evict one client per document ([B] array, -1 = none)."""
    return jax.vmap(_leave_one)(state, clients)
