"""Document router: per-document sub-partitioning of one log partition.

Capability parity with reference lambdas-driver/src/document-router/
(`documentLambda.ts`, `documentPartition.ts`, `documentContext.ts`,
`contextManager.ts`; design in kafka-service/README.md:52-56): a partition
carries many documents' messages interleaved; the router fans each message
out to a per-document lambda with its own *virtual* checkpoint context, and
consolidates those per-document checkpoints into the one real partition
offset — committed only up to the point every document has durably
processed, so a crash replays exactly the uncheckpointed suffix for every
document (idempotent handlers absorb the overlap).

TPU mapping (SURVEY.md §2.6.2): the per-document lanes here are the host-
side routing shape; inside the fused pipeline the same documents form the
batch axis of the ticket/apply kernels.
"""

from __future__ import annotations

from typing import Callable, Dict, Optional

from .lambdas.base import IPartitionLambda, LambdaContext
from .log import QueuedMessage


class DocumentContext:
    """The checkpoint surface handed to one document's lambda (reference
    documentContext.ts): tracks the highest offset routed to the document
    (`tail`) and the highest offset its lambda has declared durable
    (`checkpointed`)."""

    def __init__(self, manager: "DocumentContextManager"):
        self._manager = manager
        self.tail = -1          # last offset routed to this document
        self.checkpointed = -1  # last offset the doc lambda checkpointed

    @property
    def pending(self) -> bool:
        return self.checkpointed < self.tail

    def checkpoint(self, offset: int) -> None:
        if offset > self.checkpointed:
            self.checkpointed = min(offset, self.tail)
            self._manager.update()

    def error(self, err: Exception, restart: bool) -> None:
        self._manager.error(err, restart)


class DocumentContextManager:
    """Consolidates per-document checkpoints into the real partition offset
    (reference contextManager.ts): the partition may commit up to
    min(checkpointed over documents still pending, else the global head)."""

    def __init__(self, context: LambdaContext):
        self.context = context
        self.contexts: Dict[str, DocumentContext] = {}
        self.head = -1       # last offset routed to any document
        self._committed = -1

    def create_context(self, doc_id: str) -> DocumentContext:
        ctx = DocumentContext(self)
        self.contexts[doc_id] = ctx
        return ctx

    def track(self, doc_id: str, offset: int) -> DocumentContext:
        ctx = self.contexts.get(doc_id)
        if ctx is None:
            ctx = self.create_context(doc_id)
        ctx.tail = offset
        self.head = max(self.head, offset)
        return ctx

    def safe_offset(self) -> int:
        pending = [c.checkpointed for c in self.contexts.values() if c.pending]
        if not pending:
            return self.head
        return min(pending)

    def update(self) -> None:
        safe = self.safe_offset()
        if safe > self._committed:
            self._committed = safe
            self.context.checkpoint(safe)

    def error(self, err: Exception, restart: bool) -> None:
        self.context.error(err, restart)


class DocumentRouterLambda(IPartitionLambda):
    """The routing lambda itself (reference documentLambda.ts). Document
    identity comes from the message key (the log already partitions by it).

    A per-document lambda crash marks that document corrupt and stops
    routing to it (reference documentPartition.ts: "Close" the partition on
    error) while other documents keep flowing; the error still surfaces
    through the real context so the host can decide to restart the stage.
    """

    def __init__(self, context: LambdaContext,
                 document_lambda_factory: Callable[
                     [str, DocumentContext], IPartitionLambda]):
        self.context = context
        self.manager = DocumentContextManager(context)
        self.factory = document_lambda_factory
        self.documents: Dict[str, IPartitionLambda] = {}
        self.corrupt: Dict[str, Exception] = {}

    def handler(self, message: QueuedMessage) -> None:
        doc_id = message.key
        if doc_id in self.corrupt:
            # Skip but keep the checkpoint frontier moving: a dead document
            # must not pin the partition offset forever.
            ctx = self.manager.track(doc_id, message.offset)
            ctx.checkpoint(message.offset)
            return
        ctx = self.manager.track(doc_id, message.offset)
        doc_lambda = self.documents.get(doc_id)
        if doc_lambda is None:
            doc_lambda = self.factory(doc_id, ctx)
            self.documents[doc_id] = doc_lambda
        try:
            doc_lambda.handler(message)
        except Exception as err:  # noqa: BLE001 — per-doc crash isolation
            self.corrupt[doc_id] = err
            ctx.checkpoint(message.offset)
            self.manager.error(err, restart=False)

    def close(self) -> None:
        for doc_lambda in self.documents.values():
            doc_lambda.close()
        self.documents.clear()

    # -- introspection ------------------------------------------------------
    def document_ids(self) -> list:
        return list(self.documents)

    def reap_idle(self, keep: Optional[set] = None) -> int:
        """Drop fully-checkpointed document lambdas (reference
        documentPartition inactivity timeout): safe because their state
        reloads from checkpoints on the next message."""
        keep = keep or set()
        reaped = 0
        for doc_id in list(self.documents):
            ctx = self.manager.contexts.get(doc_id)
            if doc_id not in keep and ctx is not None and not ctx.pending:
                self.documents.pop(doc_id).close()
                del self.manager.contexts[doc_id]
                reaped += 1
        return reaped
