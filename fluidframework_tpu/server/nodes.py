"""Multi-node ordering: document->node reservations with takeover.

Capability parity with reference memory-orderer's multi-node mode
(`memory-orderer/src/{reservationManager.ts,nodeManager.ts,localNode.ts,
proxyOrderer.ts}`, SURVEY.md §2.6.4): each document is owned by exactly one
orderer node via a leased reservation persisted in the shared database;
clients may connect through any node — non-owners forward to the owner
(proxy orderer); when the owner dies or its lease expires another node
takes the reservation over and resumes sequencing from the deli/scribe
checkpoints in the shared database, so sequence numbers continue without
gaps or duplicates.

TPU deployment shape: nodes are hosts of a pod slice; the shared
DatabaseManager/Historian stand in for the durable Mongo/git services; the
per-document core is the same lambda pipeline the single-node path runs
(one `LocalServer` per owned document, mirroring the reference's
LocalOrderer-per-document), so a takeover is "construct pipeline from
checkpoint" — the state handed over is the checkpoint, never the log.
"""

from __future__ import annotations

import itertools
import threading
import time
from typing import Dict, List, Optional

from ..core.events import TypedEventEmitter
from .database import Collection, DatabaseManager
from .lambdas.scriptorium import query_deltas
from .local_server import Connection, LocalServer
from .storage import Historian


class NodeManager:
    """Node liveness registry (reference nodeManager.ts): nodes heartbeat
    into the shared db; a node is alive if its last heartbeat is fresh."""

    def __init__(self, nodes: Collection, heartbeat_timeout_s: float = 30.0):
        self.nodes = nodes
        self.heartbeat_timeout_s = heartbeat_timeout_s

    def register(self, node_id: str, now: Optional[float] = None) -> None:
        self.heartbeat(node_id, now)

    def heartbeat(self, node_id: str, now: Optional[float] = None) -> None:
        ts = time.time() if now is None else now
        self.nodes.upsert(lambda d: d.get("nodeId") == node_id,
                          {"nodeId": node_id, "lastHeartbeat": ts,
                           "alive": True})

    def mark_dead(self, node_id: str) -> None:
        row = self.nodes.find_one(lambda d: d.get("nodeId") == node_id)
        if row:
            row["alive"] = False
            self.nodes.upsert(lambda d: d.get("nodeId") == node_id, row)

    def is_alive(self, node_id: str, now: Optional[float] = None) -> bool:
        row = self.nodes.find_one(lambda d: d.get("nodeId") == node_id)
        if row is None or not row.get("alive"):
            return False
        ts = time.time() if now is None else now
        return ts - row["lastHeartbeat"] <= self.heartbeat_timeout_s


class ReservationManager:
    """Leased document->node ownership (reference reservationManager.ts).
    `get_or_reserve` returns the current owner, taking the reservation
    over when it is expired or its owner is no longer alive."""

    def __init__(self, reservations: Collection, node_manager: NodeManager,
                 lease_s: float = 60.0):
        self.reservations = reservations
        self.node_manager = node_manager
        self.lease_s = lease_s
        # Reservation decisions must be atomic per process (the reference
        # leans on Mongo's atomic update; the in-memory db needs a lock).
        self._lock = threading.Lock()

    def get_or_reserve(self, key: str, node_id: str,
                       now: Optional[float] = None) -> str:
        ts = time.time() if now is None else now
        with self._lock:
            row = self.reservations.find_one(lambda d: d.get("key") == key)
            if row is not None:
                owner = row["nodeId"]
                if (row["expires"] > ts
                        and self.node_manager.is_alive(owner, ts)):
                    return owner
            # Expired / dead owner / unreserved: take it.
            self.reservations.upsert(
                lambda d: d.get("key") == key,
                {"key": key, "nodeId": node_id,
                 "expires": ts + self.lease_s})
            return node_id

    def owner(self, key: str) -> Optional[str]:
        row = self.reservations.find_one(lambda d: d.get("key") == key)
        return row["nodeId"] if row else None

    def extend(self, key: str, node_id: str,
               now: Optional[float] = None) -> bool:
        """Renew the lease; False if the reservation moved elsewhere."""
        ts = time.time() if now is None else now
        with self._lock:
            row = self.reservations.find_one(lambda d: d.get("key") == key)
            if row is None or row["nodeId"] != node_id:
                return False
            self.reservations.upsert(
                lambda d: d.get("key") == key,
                {"key": key, "nodeId": node_id,
                 "expires": ts + self.lease_s})
            return True

    def release(self, key: str, node_id: str) -> None:
        with self._lock:
            row = self.reservations.find_one(lambda d: d.get("key") == key)
            if row is not None and row["nodeId"] == node_id:
                self.reservations.upsert(
                    lambda d: d.get("key") == key,
                    {"key": key, "nodeId": node_id, "expires": 0.0})


class ProxyConnection(TypedEventEmitter):
    """A client connection held through a non-owning node (reference
    proxyOrderer.ts): submit/disconnect forward to the owner's connection;
    op/nack/disconnect events relay back."""

    def __init__(self, remote: Connection, via_node: str):
        super().__init__()
        self.remote = remote
        self.via_node = via_node
        self.client_id = remote.client_id
        remote.on("op", lambda msg: self.emit("op", msg))
        remote.on("nack", lambda nack: self.emit("nack", nack))
        remote.on("signal", lambda sig: self.emit("signal", sig))
        remote.on("disconnect", lambda: self.emit("disconnect"))

    @property
    def connected(self) -> bool:
        return self.remote.connected

    def submit(self, messages) -> None:
        self.remote.submit(messages)

    def submit_signal(self, content) -> None:
        self.remote.submit_signal(content)

    def disconnect(self) -> None:
        self.remote.disconnect()


class OrdererNode:
    """One orderer host. Owns a set of documents (per-document lambda
    cores) and proxies the rest (reference localNode.ts)."""

    def __init__(self, cluster: "Cluster", node_id: str):
        self.cluster = cluster
        self.node_id = node_id
        self.cores: Dict[str, LocalServer] = {}
        self.proxies: List[ProxyConnection] = []
        self.running = True
        self._lock = threading.RLock()
        cluster.node_manager.register(node_id)

    # -- ownership ---------------------------------------------------------
    def _own_core(self, document_id: str) -> LocalServer:
        """Create (or reuse) this node's pipeline for a document it owns.
        Construction restores deli/scribe checkpoints from the shared db —
        the takeover path."""
        with self._lock:
            core = self.cores.get(document_id)
            if core is not None:
                return core
            had_checkpoint = self.cluster.deli_checkpoint(document_id)
            core = self.cluster.server_cls(
                tenant_id=self.cluster.tenant_id, db=self.cluster.db,
                historian=self.cluster.historian)
            # Fencing gate: every pump (i.e. every batch of sequencing work)
            # first renews this node's lease on the document. If the
            # reservation has moved — another node took over while this one
            # was idle/partitioned — the pump aborts BEFORE sequencing
            # anything and the core self-fences, so two cores can never
            # write forked histories for one document (split-brain guard).
            core.pump_gate = (
                lambda doc_id=document_id: self._renew_or_fence(doc_id))
            self.cores[document_id] = core
            if had_checkpoint:
                self._evict_stale_clients(core, document_id, had_checkpoint)
            return core

    def _renew_or_fence(self, document_id: str) -> bool:
        """Renew liveness + lease for one owned document; on failure drop
        the core and disconnect its clients (they reconnect through a
        surviving node, which owns the reservation now)."""
        if not self.running:
            return False
        self.cluster.node_manager.heartbeat(self.node_id)
        if self.cluster.reservations.extend(document_id, self.node_id):
            return True
        self._fence(document_id)
        return False

    def _fence(self, document_id: str) -> None:
        with self._lock:
            core = self.cores.pop(document_id, None)
        if core is None:
            return
        for conns in list(core._connections.values()):
            for conn in list(conns):
                conn.connected = False
                conn.emit("disconnect")

    def _evict_stale_clients(self, core: LocalServer, document_id: str,
                             checkpoint: dict) -> None:
        """The previous owner's clients can never speak again (their
        connections died with it). Sequence server-generated leaves for
        them — the reference deli's client-eviction path — so the MSN is
        not pinned at a dead client's refSeq forever."""
        import json as _json
        from ..protocol.messages import DocumentMessage, MessageType
        for entry in checkpoint.get("clients", []):
            core._send_system(document_id, DocumentMessage(
                client_sequence_number=0,
                reference_sequence_number=-1,
                type=MessageType.CLIENT_LEAVE,
                data=_json.dumps({"clientId": entry["clientId"]})))
        core.pump()

    # -- client surface ----------------------------------------------------
    def connect(self, document_id: str, details: Optional[dict] = None):
        """Connect a client to a document through this node: a direct
        connection when this node owns it, a ProxyConnection otherwise."""
        if not self.running:
            raise ConnectionError(f"node {self.node_id} is stopped")
        self.heartbeat()
        owner = self.cluster.reservations.get_or_reserve(
            document_id, self.node_id)
        if owner == self.node_id:
            return self._own_core(document_id).connect(document_id, details)
        peer = self.cluster.node(owner)
        remote = peer._own_core(document_id).connect(document_id, details)
        proxy = ProxyConnection(remote, via_node=self.node_id)
        with self._lock:
            self.proxies.append(proxy)
        return proxy

    def get_deltas(self, document_id: str, from_seq: int = 0,
                   to_seq: Optional[int] = None) -> List[dict]:
        return query_deltas(self.cluster.deltas, document_id, from_seq,
                            to_seq)

    def heartbeat(self) -> None:
        self.cluster.node_manager.heartbeat(self.node_id)
        for doc_id in list(self.cores):
            if not self.cluster.reservations.extend(doc_id, self.node_id):
                self._fence(doc_id)

    def stop(self) -> None:
        """Simulate node death: drop client connections, stop heartbeating.
        Checkpoints stay in the shared db for the next owner."""
        with self._lock:
            self.running = False
            for doc_id, core in self.cores.items():
                for conn in [c for conns in core._connections.values()
                             for c in conns]:
                    conn.connected = False
                    conn.emit("disconnect")
            self.cores.clear()
            # Clients that entered through this node as a proxy lose their
            # path too: sever at the owner's end so 'disconnect' fires and
            # ProxyConnection.connected goes False.
            proxies, self.proxies = self.proxies, []
        for proxy in proxies:
            if proxy.remote.connected:
                proxy.remote.disconnect()
        self.cluster.node_manager.mark_dead(self.node_id)


class Cluster:
    """A set of orderer nodes over shared durable services (the multi-node
    deployment in one process; reference docker-compose scale-out)."""

    def __init__(self, tenant_id: str = "cluster",
                 heartbeat_timeout_s: float = 30.0, lease_s: float = 60.0,
                 server_cls=LocalServer):
        """server_cls: the per-document pipeline class — LocalServer
        (scalar deli) or TpuLocalServer (device-batched sequencer); both
        restore from the shared checkpoint collections on takeover."""
        self.tenant_id = tenant_id
        self.server_cls = server_cls
        self.db = DatabaseManager()
        self.historian = Historian()
        self.node_manager = NodeManager(self.db.collection("nodes"),
                                        heartbeat_timeout_s)
        self.reservations = ReservationManager(
            self.db.collection("reservations"), self.node_manager, lease_s)
        self._nodes: Dict[str, OrdererNode] = {}
        self._counter = itertools.count(1)

    @property
    def deltas(self) -> Collection:
        from .lambdas.scriptorium import delta_key
        return self.db.collection("deltas", unique_key=delta_key)

    def deli_checkpoint(self, document_id: str) -> Optional[dict]:
        """Checkpointed sequencing state for a doc, normalized to the
        scalar shape ({"clients": [{"clientId": ...}], ...}) — reads the
        scalar deli's per-doc row or the TPU sequencer's consolidated dump
        (server/tpu_sequencer.py _checkpoint)."""
        ckpts = self.db.collection("deliCheckpoints")
        # "state" in d: skip handed-off tombstones (live rebalancing
        # leaves one on the document's old partition; server/sharding.py).
        row = ckpts.find_one(
            lambda d: d.get("documentId") == document_id and "state" in d)
        if row:
            return row["state"]
        tpu = ckpts.find_one(lambda d: d.get("kind") == "tpu-sequencer")
        if not tpu:
            return None
        dump = tpu["state"]
        doc = dump.get("docs", {}).get(document_id)
        if doc is None:
            return None
        lane = doc["lane"]
        tstate = dump["tstate"]
        by_ordinal = {int(v): k for k, v in doc["interner"].items()}
        clients = [{"clientId": by_ordinal[int(o)]}
                   for o in tstate["client_ids"][lane]
                   if int(o) >= 0 and int(o) in by_ordinal]
        return {"sequenceNumber": int(tstate["next_seq"][lane]) - 1,
                "clients": clients}

    def create_node(self, node_id: Optional[str] = None) -> OrdererNode:
        nid = node_id or f"node-{next(self._counter)}"
        node = OrdererNode(self, nid)
        self._nodes[nid] = node
        return node

    def node(self, node_id: str) -> OrdererNode:
        return self._nodes[node_id]

    def live_nodes(self) -> List[OrdererNode]:
        return [n for n in self._nodes.values() if n.running]
