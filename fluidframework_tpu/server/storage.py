"""Content-addressed summary storage + caching proxy.

Capability parity with reference server/gitrest (git-style trees/blobs/
commits/refs over REST, README:1-9) and server/historian (Redis-backed
caching proxy in front of it). The git object model is kept — blobs are
content-addressed by sha, trees reference child shas, commits chain — so
incremental summaries (SummaryHandle pointing into the previous summary)
dedupe structurally, exactly like the reference's summary write path
(scribe -> historian -> gitrest).
"""

from __future__ import annotations

import json
import threading
import time
from dataclasses import dataclass, field
from typing import Dict, List, Optional, Tuple

from ..protocol.summary import (
    SummaryBlob,
    SummaryHandle,
    SummaryObject,
    SummaryTree,
    blob_sha,
)


@dataclass
class GitBlob:
    sha: str
    content: bytes


@dataclass
class GitTree:
    sha: str
    entries: Dict[str, Tuple[str, str]]  # name -> (kind: blob|tree, sha)


@dataclass
class GitCommit:
    sha: str
    tree_sha: str
    parents: List[str]
    message: str
    timestamp: float


class GitStore:
    """One tenant/document scope's object store (gitrest equivalent)."""

    def __init__(self):
        self._objects: Dict[str, object] = {}
        self._refs: Dict[str, str] = {}  # ref name -> commit sha
        self._lock = threading.Lock()

    # -- objects -----------------------------------------------------------
    def put_blob(self, content: bytes) -> str:
        sha = blob_sha(content)
        with self._lock:
            self._objects.setdefault(sha, GitBlob(sha, content))
        return sha

    def put_tree(self, entries: Dict[str, Tuple[str, str]]) -> str:
        canonical = json.dumps(sorted(entries.items())).encode()
        sha = blob_sha(b"tree\x00" + canonical)
        with self._lock:
            self._objects.setdefault(sha, GitTree(sha, dict(entries)))
        return sha

    def put_commit(self, tree_sha: str, parents: List[str],
                   message: str) -> str:
        ts = time.time()
        sha = blob_sha(
            f"commit\x00{tree_sha}\x00{parents}\x00{message}\x00{ts}".encode())
        with self._lock:
            self._objects[sha] = GitCommit(sha, tree_sha, list(parents),
                                           message, ts)
        return sha

    def get(self, sha: str):
        return self._objects.get(sha)

    # -- refs --------------------------------------------------------------
    def set_ref(self, name: str, commit_sha: str) -> None:
        with self._lock:
            self._refs[name] = commit_sha

    def get_ref(self, name: str) -> Optional[str]:
        return self._refs.get(name)

    # -- summary upload/download ------------------------------------------
    def write_summary(self, tree: SummaryTree, ref: str = "main",
                      message: str = "summary",
                      base_commit: Optional[str] = None,
                      advance_ref: bool = False) -> str:
        """Upload a summary tree (resolving handles against the ref's
        current commit). Returns the new commit sha.

        The ref only advances when advance_ref=True (the initial attach
        summary, or scribe acking a client summary): a client upload is a
        *proposal* — it must not become the load target until the sequenced
        summarize op is validated and acked (reference: scribe writes the
        ref, clients only upload; scribe/lambda.ts:162-192)."""
        parent = base_commit if base_commit is not None else self.get_ref(ref)
        base_tree = None
        if parent:
            commit = self.get(parent)
            base_tree = commit.tree_sha if commit else None
        tree_sha = self._write_tree(tree, base_tree)
        commit_sha = self.put_commit(tree_sha, [parent] if parent else [],
                                     message)
        if advance_ref:
            self.set_ref(ref, commit_sha)
        return commit_sha

    def _write_tree(self, node: SummaryObject, base_tree: Optional[str]) -> str:
        if isinstance(node, SummaryBlob):
            content = node.content
            if isinstance(content, str):
                content = content.encode()
            return self.put_blob(content)
        if isinstance(node, SummaryHandle):
            sha = self._resolve_handle(node.handle, base_tree)
            if sha is None:
                raise KeyError(f"unresolvable summary handle {node.handle!r}")
            return sha
        if isinstance(node, SummaryTree):
            entries: Dict[str, Tuple[str, str]] = {}
            for name, child in node.entries.items():
                # Incremental: a handle child resolves against the same-name
                # path of the base tree.
                sha = self._write_tree(child, self._child_sha(base_tree, name))
                kind = "blob" if isinstance(child, SummaryBlob) else "tree"
                if isinstance(child, SummaryHandle):
                    kind = "blob" if child.handle_type == "blob" else "tree"
                entries[name] = (kind, sha)
            return self.put_tree(entries)
        raise TypeError(f"cannot store {type(node)!r}")

    def _child_sha(self, tree_sha: Optional[str], name: str) -> Optional[str]:
        if tree_sha is None:
            return None
        tree = self.get(tree_sha)
        if not isinstance(tree, GitTree) or name not in tree.entries:
            return None
        return tree.entries[name][1]

    def _resolve_handle(self, path: str, base_tree: Optional[str]
                        ) -> Optional[str]:
        sha = base_tree
        for part in path.strip("/").split("/"):
            if not part or sha is None:
                break
            sha = self._child_sha(sha, part)
        return sha

    def read_summary(self, commit_sha: Optional[str] = None,
                     ref: str = "main",
                     lazy: bool = False) -> Optional[SummaryTree]:
        """lazy=True: blob entries resolve their content on first access
        (LazySummaryBlob) and `self.blob_fetches` counts resolutions —
        the loader's header-first / body-on-demand snapshot load."""
        sha = commit_sha or self.get_ref(ref)
        if sha is None:
            return None
        commit = self.get(sha)
        if not isinstance(commit, GitCommit):
            return None  # unknown/garbage version
        return self._read_tree(commit.tree_sha, lazy)

    blob_fetches = 0  # lazy-blob resolutions (per-store instance counter)

    def _fetch_blob(self, sha: str):
        self.blob_fetches += 1
        blob = self.get(sha)
        try:
            return blob.content.decode()
        except UnicodeDecodeError:
            return blob.content

    def _read_tree(self, tree_sha: str, lazy: bool = False) -> SummaryTree:
        from ..protocol.summary import LazySummaryBlob
        tree = self.get(tree_sha)
        out = SummaryTree()
        for name, (kind, sha) in tree.entries.items():
            if kind == "blob":
                if lazy:
                    out.entries[name] = LazySummaryBlob(
                        lambda s=sha: self._fetch_blob(s))
                else:
                    blob = self.get(sha)
                    try:
                        out.entries[name] = SummaryBlob(
                            blob.content.decode())
                    except UnicodeDecodeError:
                        out.entries[name] = SummaryBlob(blob.content)
            else:
                out.entries[name] = self._read_tree(sha, lazy)
        return out

    def list_commits(self, ref: str = "main", limit: int = 50) -> List[GitCommit]:
        out = []
        sha = self.get_ref(ref)
        while sha and len(out) < limit:
            commit = self.get(sha)
            if commit is None:
                break
            out.append(commit)
            sha = commit.parents[0] if commit.parents else None
        return out


class Historian:
    """Caching proxy over per-document GitStores (reference historian:
    the storage endpoint drivers actually talk to)."""

    def __init__(self):
        self._stores: Dict[Tuple[str, str], GitStore] = {}
        self._cache: Dict[str, object] = {}
        self._lock = threading.Lock()
        self.cache_hits = 0
        self.cache_misses = 0

    def store(self, tenant_id: str, document_id: str) -> GitStore:
        key = (tenant_id, document_id)
        with self._lock:
            if key not in self._stores:
                self._stores[key] = GitStore()
            return self._stores[key]

    def get_cached(self, sha: str, tenant_id: str, document_id: str):
        """Object lookup through the cache. Safe to share across documents:
        objects are content-addressed, so a sha uniquely names its bytes;
        only refs (mutable) must never be cached."""
        if sha in self._cache:
            self.cache_hits += 1
            return self._cache[sha]
        self.cache_misses += 1
        obj = self.store(tenant_id, document_id).get(sha)
        if obj is not None:
            with self._lock:
                self._cache[sha] = obj
        return obj

    blob_fetches = 0  # lazy-blob resolutions through this historian

    def read_summary(self, tenant_id: str, document_id: str,
                     commit_sha: Optional[str] = None,
                     ref: str = "main",
                     lazy: bool = False) -> Optional[SummaryTree]:
        """The drivers' summary download path: identical semantics to
        GitStore.read_summary but every object fetch rides the cache, so a
        summary shared by N loading clients hits storage once. lazy=True
        defers blob content to first access (LazySummaryBlob)."""
        store = self.store(tenant_id, document_id)
        sha = commit_sha or store.get_ref(ref)
        if sha is None:
            return None
        commit = self.get_cached(sha, tenant_id, document_id)
        if not isinstance(commit, GitCommit):
            return None
        return self._read_tree_cached(commit.tree_sha, tenant_id,
                                      document_id, lazy)

    def _fetch_blob_cached(self, sha: str, tenant_id: str,
                           document_id: str):
        self.blob_fetches += 1
        blob = self.get_cached(sha, tenant_id, document_id)
        try:
            return blob.content.decode()
        except UnicodeDecodeError:
            return blob.content

    def _read_tree_cached(self, tree_sha: str, tenant_id: str,
                          document_id: str,
                          lazy: bool = False) -> SummaryTree:
        from ..protocol.summary import LazySummaryBlob
        tree = self.get_cached(tree_sha, tenant_id, document_id)
        out = SummaryTree()
        for name, (kind, sha) in tree.entries.items():
            if kind == "blob":
                if lazy:
                    out.entries[name] = LazySummaryBlob(
                        lambda s=sha: self._fetch_blob_cached(
                            s, tenant_id, document_id))
                else:
                    blob = self.get_cached(sha, tenant_id, document_id)
                    try:
                        out.entries[name] = SummaryBlob(
                            blob.content.decode())
                    except UnicodeDecodeError:
                        out.entries[name] = SummaryBlob(blob.content)
            else:
                out.entries[name] = self._read_tree_cached(
                    sha, tenant_id, document_id, lazy)
        return out
