"""Service entrypoints: the deployment wiring (docker-compose analog).

The reference runs every lambda as its own service against the Kafka
broker (server/docker-compose.yml:2-55; `kafka-service/index.js <name>
<lambda>` per service). The equivalent here:

    python -m fluidframework_tpu.server.main broker  --config deploy/config.json
    python -m fluidframework_tpu.server.main worker  --config deploy/config.json
    python -m fluidframework_tpu.server.main worker  --stages scriptorium,scribe ...
    python -m fluidframework_tpu.server.main historian --config deploy/config.json

- `broker` hosts the ordered log (pure-Python or the native C++ engine)
  over gRPC (server/log_service.py) — the Kafka role.
- `worker` runs any subset of lambda stages over RemoteMessageLog against
  the broker, with durable sqlite checkpoints/deltas and a file-backed git
  store (server/durable.py) — the per-lambda service role. `--stages
  tpu-deli` swaps the scalar sequencer for the device-batched
  TpuSequencerLambda (server/tpu_sequencer.py).
- `historian` runs the standalone summary-cache tier
  (server/historian.py) over the shared git directory (or proxying an
  alfred URL via `historian.upstream`); scribe workers notify it on
  summary commits when `historian.url` points at it.

Deli nacks publish to the `nacks` topic (the front door consumes it and
routes to the offending client's socket); sequenced deltas flow through
the `deltas` topic exactly as in-process. Crash/restart semantics are the
lambda host's: offsets commit with checkpoints, replay is idempotent.

See deploy/RUNBOOK.md for topology, scaling, and failure handling.
"""

from __future__ import annotations

import argparse
import json
import signal
import sys
import time
from typing import List, Optional

RAW_TOPIC = "rawdeltas"
DELTAS_TOPIC = "deltas"
NACKS_TOPIC = "nacks"

DEFAULT_CONFIG = {
    # monitorPort > 0 serves /health + /metrics.prom on the broker
    # process — the durable engine's group-commit counters
    # (fluid_durable_fsyncs_total, fluid_durable_batch_bytes, the
    # durable.group_commit latency histogram) live HERE, not in the
    # workers, so the observatory must scrape the broker to see them.
    "broker": {"host": "127.0.0.1", "port": 7080, "native": False,
               "partitions": 1, "monitorPort": 0},
    "storage": {"db": "var/fluid.sqlite", "git": "var/git"},
    # monitorPort > 0 serves /health + /metrics.prom + /trace for the
    # fleet observatory to scrape; `name` tags every exported span with
    # this process identity (default worker:<stages>); traceSample > 0
    # head-samples 1-in-N op traces in this worker.
    # `partitions`: null pumps every broker partition (single-host
    # shape); a list like [0,1,2,3] makes this worker pump ONLY those
    # raw-topic partitions — the cross-host placement config (two
    # workers owning [0..7] and [8..15] against one remote broker ARE
    # the 16-partition ingest tier; deploy/RUNBOOK.md multi-host
    # recipe). Applies to the sequencing stage (deli/tpu-deli).
    "worker": {"stages": ["deli", "scriptorium", "scribe", "copier"],
               "poll_ms": 10, "tenant": "local", "monitorPort": 0,
               "name": None, "traceSample": 0, "partitions": None},
    # The fleet observatory (server/observatory.py): scrapes each
    # worker's monitor endpoints on intervalS, merges /fleet/health,
    # /fleet/metrics.prom, /fleet/lag, and joins drained trace rings by
    # traceId into /fleet/trace. `workers` lists monitor base URLs.
    "observatory": {"host": "127.0.0.1", "port": 7090, "workers": [],
                    "intervalS": 2.0},
    "deli": {"checkpointBatchSize": 8, "checkpointTimeIntervalMsec": 500},
    # The summary-cache tier (server/historian.py). `historian` service:
    # host/port to serve on; upstream (alfred URL) switches store mode ->
    # proxy mode; monitorPort exposes /health+/metrics with the cache
    # counters. Workers: a non-empty `url` makes scribe notify the tier
    # on every summary commit (write-through invalidation).
    "historian": {"host": "127.0.0.1", "port": 7081, "upstream": None,
                  "url": None, "refTtlS": 2.0,
                  "maxBytes": 256 * 1024 * 1024, "monitorPort": 0},
    # Read-tier catch-up artifact push-through (server/readpath.py
    # ArtifactPushThrough, docs/read_path.md): a `tpu-deli` worker with a
    # configured historian url pushes refreshed artifacts to the tier's
    # /historian/catchup route on this cadence — default ON; connecting
    # clients then get summary + artifact in one round trip without the
    # worker in the path.
    "catchup": {"push": True, "intervalS": 0.25},
}


def load_config(path: Optional[str]) -> dict:
    cfg = json.loads(json.dumps(DEFAULT_CONFIG))  # deep copy
    if path:
        with open(path) as f:
            loaded = json.load(f)
        for key, value in loaded.items():
            if isinstance(value, dict):
                cfg.setdefault(key, {}).update(value)
            else:
                cfg[key] = value
    return cfg


class _ConfigView:
    """Dotted-key accessor over the config dict (the nconf role —
    services-core/src/lambdas.ts:56 passes each lambda its config slice)."""

    def __init__(self, cfg: dict):
        self.cfg = cfg

    def get(self, dotted: str, default=None):
        node = self.cfg
        for part in dotted.split("."):
            if not isinstance(node, dict) or part not in node:
                return default
            node = node[part]
        return node


def run_broker(cfg: dict) -> None:
    from .log import make_message_log
    from .log_service import LogServiceServer

    bcfg = cfg["broker"]
    log_dir = cfg.get("storage", {}).get("log")
    if log_dir:
        # Durable broker: partitions + offsets persist to disk, a restart
        # resumes with full history (server/durable.py DurableMessageLog).
        from .durable import DurableMessageLog
        log = DurableMessageLog(log_dir,
                                default_partitions=bcfg.get("partitions", 1))
    else:
        log = make_message_log(default_partitions=bcfg.get("partitions", 1),
                               native=bcfg.get("native", False))
    log.topic(RAW_TOPIC)
    log.topic(DELTAS_TOPIC)
    log.topic(NACKS_TOPIC)
    server = LogServiceServer(log, port=bcfg.get("port", 7080))
    server.start()
    print(f"broker: serving ordered log on {server.address}", flush=True)
    monitor = None
    if bcfg.get("monitorPort"):
        from .monitor import ServiceMonitor
        monitor = ServiceMonitor(host=bcfg.get("host", "127.0.0.1"),
                                 port=bcfg["monitorPort"])
        monitor.watch_durable("broker", log)
        monitor.start()
        print(f"broker: monitor on {monitor.url}", flush=True)
    _wait_for_signal()
    if monitor is not None:
        monitor.stop()
    server.stop()


def run_historian(cfg: dict) -> None:
    from .historian import HistorianService

    hcfg = cfg.get("historian", {})
    store = None
    upstream = hcfg.get("upstream")
    if not upstream:
        from .durable import FileHistorian
        store = FileHistorian(cfg["storage"]["git"])
    service = HistorianService(
        upstream_url=upstream, store=store,
        host=hcfg.get("host", "127.0.0.1"), port=hcfg.get("port", 7081),
        max_bytes=hcfg.get("maxBytes", 256 * 1024 * 1024),
        ref_ttl_s=hcfg.get("refTtlS", 2.0))
    service.start()
    print(f"historian: serving cache tier on {service.url} "
          f"({'proxy' if upstream else 'store'} mode)", flush=True)
    monitor = None
    if hcfg.get("monitorPort"):
        from .monitor import ServiceMonitor
        monitor = ServiceMonitor(host=hcfg.get("host", "127.0.0.1"),
                                 port=hcfg["monitorPort"])
        monitor.watch_historian("historian", service)
        monitor.start()
        print(f"historian: monitor on {monitor.url}", flush=True)
    _wait_for_signal()
    if monitor is not None:
        monitor.stop()
    service.stop()


def build_worker(cfg: dict, stages: List[str]):
    """Wire the requested lambda stages over the remote log + durable
    services. Returns (runner, close_fn)."""
    from .durable import FileHistorian, SqliteDatabaseManager
    from .lambdas import (
        BroadcasterLambda,
        CopierLambda,
        DeliLambda,
        ScribeLambda,
        ScriptoriumLambda,
    )
    from .lambdas.scriptorium import delta_key
    from .log_service import RemoteMessageLog
    from .partition import LambdaRunner, PartitionManager
    from .routing import doc_shard
    from ..protocol.messages import Boxcar

    bcfg = cfg["broker"]
    address = f"{bcfg.get('host', '127.0.0.1')}:{bcfg.get('port', 7080)}"
    log = RemoteMessageLog(address,
                           default_partitions=bcfg.get("partitions", 1))
    db = SqliteDatabaseManager(cfg["storage"]["db"])
    historian = FileHistorian(cfg["storage"]["git"])
    tenant = cfg["worker"].get("tenant", "local")
    deltas = db.collection("deltas", unique_key=delta_key)
    raw_deltas = db.collection("rawdeltas")
    deli_ckpt = db.collection("deliCheckpoints")
    scribe_ckpt = db.collection("scribeCheckpoints")
    view = _ConfigView(cfg)

    # Explicit-partition produce through the shared md5 router
    # (server/routing.py): a worker's system messages (deli ghost
    # evictions, scribe acks) and sequenced emits must land on the SAME
    # partition the front door routes the document to — the broker's
    # own key hash is never consulted on a sharded topology.
    n_parts = int(bcfg.get("partitions", 1))

    def emit_sequenced(doc_id, sequenced):
        log.send_to(DELTAS_TOPIC, doc_shard(doc_id, n_parts), doc_id,
                    (doc_id, sequenced))

    def emit_nack(doc_id, client_id, nack):
        log.send_to(NACKS_TOPIC, doc_shard(doc_id, n_parts), doc_id,
                    (doc_id, client_id, nack))

    def send_system(doc_id, message):
        log.send_to(RAW_TOPIC, doc_shard(doc_id, n_parts), doc_id, Boxcar(
            tenant_id=tenant, document_id=doc_id, client_id=None,
            contents=[message]))

    # Sequencer checkpoints are PARTITION-SCOPED (server/sharding.py
    # PartitionCheckpoints): with partitions > 1, N lambdas over one
    # raw collection would clobber each other's tpu-sequencer row, and
    # every scalar deli restart would adopt every OTHER partition's
    # documents.
    from .sharding import PartitionCheckpoints

    # Cross-host placement: a worker owning a partition subset pumps
    # only ITS slice of the raw topic against the shared remote broker.
    owned_partitions = cfg["worker"].get("partitions")
    if owned_partitions is not None:
        owned_partitions = [int(p) for p in owned_partitions]

    runner = LambdaRunner()
    for stage in stages:
        if stage == "deli":
            runner.add(PartitionManager(
                log, "deli", RAW_TOPIC,
                lambda ctx: DeliLambda(
                    ctx, emit=emit_sequenced,
                    nack=emit_nack,
                    checkpoints=PartitionCheckpoints(deli_ckpt,
                                                     ctx.partition),
                    fresh_log=False, config=view,
                    send_system=send_system),
                auto_commit=False, partitions=owned_partitions))
        elif stage == "tpu-deli":
            from .tpu_sequencer import TpuSequencerLambda

            def make_tpu_deli(ctx):
                lam = TpuSequencerLambda(
                    ctx, emit=emit_sequenced, nack=emit_nack,
                    checkpoints=PartitionCheckpoints(deli_ckpt,
                                                     ctx.partition),
                    deltas=deltas,
                    config=view, send_system=send_system)
                # Batched emit: ONE deltas-topic produce per fast flush
                # window (downstream lambdas fan it out), matching the
                # reference's per-message produce amortized per window.
                # Produced to the SAME partition index the window's source
                # documents hash to (raw and deltas topics share the
                # partition count), so per-doc ordering and consumer
                # affinity hold with multi-partition brokers.
                lam.emit_window = lambda w, p=ctx.partition: log.send_to(
                    DELTAS_TOPIC, p, "__window__", w)
                return lam

            deli_mgr = runner.add(PartitionManager(
                log, "deli", RAW_TOPIC, make_tpu_deli, auto_commit=False,
                partitions=owned_partitions))

            # Catch-up artifact push-through (default-on): refreshed
            # artifacts land in the historian tier's catch-up cache so
            # clients connecting through the historian adopt `summary +
            # delta` in one round trip (docs/read_path.md). The supplier
            # reads LIVE lambdas from the manager's pumps — a crashed/
            # restarted partition's replacement lambda is picked up, the
            # dead one dropped. A worker without a historian url (or
            # with catchup.push=false) runs exactly as before.
            historian_url = cfg.get("historian", {}).get("url")
            if historian_url and view.get("catchup.push", True):
                from .historian import notify_catchup_refresh
                from .readpath import ArtifactPushThrough

                push = ArtifactPushThrough(
                    sequencers=lambda m=deli_mgr: [
                        p.lambda_ for p in m.pumps.values()],
                    scribe_checkpoints=scribe_ckpt,
                    historian=historian,
                    tenant_id=tenant,
                    publish=lambda t, d, a, _url=historian_url:
                        notify_catchup_refresh(_url, t, d, a),
                    interval_s=float(view.get("catchup.intervalS", 0.25)))
                runner.add_ticker(push.pump)
        elif stage == "scriptorium":
            runner.add(PartitionManager(
                log, "scriptorium", DELTAS_TOPIC,
                lambda ctx: ScriptoriumLambda(ctx, deltas)))
        elif stage == "scribe":
            # A configured historian tier hears about every commit the
            # scribe acks (write-through invalidation + warm prefetch);
            # without one (or with it down) the notify is a no-op.
            historian_url = cfg.get("historian", {}).get("url")
            on_commit = None
            if historian_url:
                from .historian import notify_summary_commit

                def on_commit(doc_id, sha, _url=historian_url,
                              _tenant=tenant):
                    notify_summary_commit(_url, _tenant, doc_id, sha)

            runner.add(PartitionManager(
                log, "scribe", DELTAS_TOPIC,
                lambda ctx, _oc=on_commit: ScribeLambda(
                    ctx, historian, tenant, send_system=send_system,
                    checkpoints=scribe_ckpt, fresh_log=False,
                    on_commit=_oc)))
        elif stage == "copier":
            runner.add(PartitionManager(
                log, "copier", RAW_TOPIC,
                lambda ctx: CopierLambda(ctx, raw_deltas)))
        elif stage == "broadcaster":
            # Standalone broadcaster keeps room state empty — real
            # deployments host it inside the front door (alfred) where the
            # websockets live; this stage exists for topology parity.
            runner.add(PartitionManager(
                log, "broadcaster", DELTAS_TOPIC,
                lambda ctx: BroadcasterLambda(ctx, rooms={})))
        else:
            raise SystemExit(f"unknown stage {stage!r}")

    def close():
        for manager in runner.managers:
            for pump in manager.pumps.values():
                pump.lambda_.close()
        db.close()

    return runner, close


def run_worker(cfg: dict, stages: List[str]) -> None:
    from ..telemetry import tracing

    wcfg = cfg.get("worker", {})
    # Fleet identity BEFORE any span records: every span this process
    # exports carries the name the observatory joins timelines by.
    tracing.set_process_name(wcfg.get("name")
                             or f"worker:{'+'.join(stages)}")
    sample = int(wcfg.get("traceSample", 0) or 0)
    if sample:
        tracing.configure(sample=sample)
    runner, close = build_worker(cfg, stages)
    poll_s = cfg["worker"].get("poll_ms", 10) / 1000.0
    print(f"worker: stages={stages} broker="
          f"{cfg['broker'].get('host')}:{cfg['broker'].get('port')}",
          flush=True)
    monitor = None
    if wcfg.get("monitorPort"):
        from ..telemetry import watermarks
        from .monitor import ServiceMonitor

        # Worker-side scrape surface for the observatory. SLO
        # enforcement stays fleet-level (observatory) — a worker whose
        # stages never observe the policy stage must not 503.
        monitor = ServiceMonitor(host=cfg["broker"].get("host",
                                                        "127.0.0.1"),
                                 port=int(wcfg["monitorPort"]),
                                 enforce_slo=False)

        def watermark_probe() -> dict:
            # Pull-model `ticketed` refresh from the live sequencer
            # lambdas (crash-restarted replacements included); the
            # raw_end mark needs broker-side end offsets and is the
            # single-process/broker monitor's job (known limit:
            # docs/observability.md v3).
            for manager in runner.managers:
                for p, pump in manager.pumps.items():
                    seqs = getattr(pump.lambda_, "doc_sequence_numbers",
                                   None)
                    if seqs is None:
                        continue
                    for doc, seq in seqs().items():
                        watermarks.advance_doc(watermarks.TICKETED, p,
                                               doc, seq)
            return {"stages": stages}

        monitor.add_probe("worker", watermark_probe)
        monitor.start()
        print(f"worker: monitor on {monitor.url}", flush=True)
    stop = {"flag": False}

    def on_signal(*_):
        stop["flag"] = True

    signal.signal(signal.SIGTERM, on_signal)
    signal.signal(signal.SIGINT, on_signal)
    while not stop["flag"]:
        try:
            n = runner.pump()
        except Exception as err:  # noqa: BLE001 — transport outage
            # Broker unreachable (restarting, network blip): keep polling —
            # the gRPC channel reconnects and the durable log serves our
            # committed offsets when the broker is back. Lambda-level
            # crashes are already handled inside the pump (restart +
            # replay); only transport errors surface here.
            print(f"worker: broker unavailable ({type(err).__name__}); "
                  "retrying", flush=True)
            time.sleep(min(poll_s * 20, 1.0))
            continue
        if n == 0:
            time.sleep(poll_s)
    if monitor is not None:
        monitor.stop()
    close()
    print("worker: stopped", flush=True)


def run_observatory(cfg: dict) -> None:
    from .observatory import FleetObservatory

    ocfg = cfg.get("observatory", {})
    targets = ocfg.get("workers") or []
    obs = FleetObservatory(workers=targets,
                           host=ocfg.get("host", "127.0.0.1"),
                           port=int(ocfg.get("port", 7090)),
                           interval_s=float(ocfg.get("intervalS", 2.0)))
    obs.start()
    print(f"observatory: aggregating {len(targets)} workers on "
          f"{obs.url}", flush=True)
    _wait_for_signal()
    obs.stop()


def main(argv=None) -> None:
    parser = argparse.ArgumentParser(
        prog="fluidframework_tpu.server.main",
        description="Run one service of the ordering pipeline")
    parser.add_argument("service", choices=["broker", "worker",
                                            "historian", "observatory"])
    parser.add_argument("--config", default=None,
                        help="path to deploy config JSON")
    parser.add_argument("--stages", default=None,
                        help="comma-separated lambda stages for `worker`")
    args = parser.parse_args(argv)
    cfg = load_config(args.config)
    if args.service == "broker":
        run_broker(cfg)
    elif args.service == "historian":
        run_historian(cfg)
    elif args.service == "observatory":
        run_observatory(cfg)
    else:
        stages = (args.stages.split(",") if args.stages
                  else cfg["worker"]["stages"])
        run_worker(cfg, stages)


def _wait_for_signal() -> None:
    done = {"flag": False}

    def on_signal(*_):
        done["flag"] = True

    signal.signal(signal.SIGTERM, on_signal)
    signal.signal(signal.SIGINT, on_signal)
    while not done["flag"]:
        time.sleep(0.2)


if __name__ == "__main__":
    main(sys.argv[1:])
