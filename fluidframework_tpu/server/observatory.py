"""Fleet observatory: the cross-process observability aggregator
(docs/observability.md v3).

One process per fleet scrapes every worker's monitor surface
(`/health`, `/metrics.prom`, `/trace`) on an interval and serves the
merged view:

  /fleet/health        per-worker reachability + health, merged
                       watermark lag, multi-window burn-rate verdict
  /fleet/metrics.prom  every worker's exposition re-labelled with
                       instance="<worker>" (HELP/TYPE deduplicated)
  /fleet/lag           per-worker watermark snapshots + per-edge fleet
                       totals (the Kafka-style consumer-lag board)
  /fleet/trace         drained spans from every worker joined into ONE
                       perfetto-ready timeline — spans carry their
                       source process identity (pid + args.proc stamped
                       at export by telemetry/tracing.chrome_trace), so
                       a sampled op's alfred-ingest -> deli-ticket ->
                       broadcaster-fanout -> reader-adoption journey
                       reads as one trace across processes
                       (?trace_id=<id> filters to one op)
  /fleet/workers       the scrape target list + last scrape status

Scraping /trace DRAINS each worker's flight recorder (the monitor's
existing drain contract), so the observatory is the fleet's span sink:
spans accumulate here in a bounded ring, joined by args.trace_id.

Burn-rate policy: the engine (telemetry/slo.py) evaluates fleet-level
objectives fed once per scrape — `worker_health` (every worker scrape
ok) and `fleet_lag` (total broadcast-edge lag under the configured
ceiling). A breach surfaces in /fleet/health with per-objective
attribution and flips it to 503.
"""

from __future__ import annotations

import json
import re
import threading
import time
import urllib.request
from collections import deque
from typing import Callable, Dict, List, Optional

from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

from ..telemetry.counters import record_swallow
from ..telemetry.slo import BurnRateEngine, Objective

# Sample line of the exposition format: name, optional labels, rest
# (value + optional exemplar). Label bodies never contain a literal
# '}' in this codebase's metric surface (stage/symbol names are
# escaped, not free-form), which keeps the split unambiguous.
_SAMPLE_RE = re.compile(
    r"^([A-Za-z_:][A-Za-z0-9_:]*)(\{[^}]*\})?\s(.*)$")


def _default_fetch(url: str, timeout_s: float) -> bytes:
    with urllib.request.urlopen(url, timeout=timeout_s) as resp:
        return resp.read()


class FleetObservatory:
    """Scrape-merge-serve loop over a list of worker monitor URLs.

    `workers` entries are monitor base URLs ("http://127.0.0.1:7101")
    or {"name": ..., "url": ...} dicts; bare URLs get worker<i> names.
    `fetch` is injectable for tests (url, timeout_s) -> bytes.
    """

    def __init__(self, workers: List, host: str = "127.0.0.1",
                 port: int = 0, interval_s: float = 2.0,
                 scrape_timeout_s: float = 2.0,
                 trace_capacity: int = 20000,
                 lag_ceiling: float = 10000.0,
                 burn: Optional[BurnRateEngine] = None,
                 fetch: Optional[Callable[[str, float], bytes]] = None):
        self.targets: List[Dict[str, str]] = []
        for i, w in enumerate(workers):
            if isinstance(w, dict):
                self.targets.append({"name": w.get("name", f"worker{i}"),
                                     "url": w["url"].rstrip("/")})
            else:
                self.targets.append({"name": f"worker{i}",
                                     "url": str(w).rstrip("/")})
        self.interval_s = float(interval_s)
        self.scrape_timeout_s = float(scrape_timeout_s)
        self.lag_ceiling = float(lag_ceiling)
        self.fetch = fetch or _default_fetch
        self.burn = burn or BurnRateEngine(
            [Objective("worker_health", 0.99,
                       "every worker scrape returns a healthy /health"),
             Objective("fleet_lag", 0.95,
                       "total broadcast-edge lag stays under the "
                       "configured ceiling")],
            fast_window_s=max(4 * self.interval_s, 10.0),
            slow_window_s=max(30 * self.interval_s, 60.0))
        # Guards everything the scrape thread writes and the HTTP
        # request threads read.
        self._lock = threading.Lock()
        self._state: Dict[str, dict] = {}   # worker name -> last scrape
        self._spans: deque = deque(maxlen=int(trace_capacity))
        self._scrapes = 0
        self._thread: Optional[threading.Thread] = None
        self._http_thread: Optional[threading.Thread] = None
        self._stop = threading.Event()
        self.host = host
        service = self

        class Handler(BaseHTTPRequestHandler):
            protocol_version = "HTTP/1.1"

            def log_message(self, fmt, *args):  # quiet
                pass

            def do_GET(self):
                service._route(self)

        self._httpd = ThreadingHTTPServer((host, port), Handler)
        self.port = self._httpd.server_address[1]

    # -- scraping ------------------------------------------------------
    def _scrape_worker(self, target: Dict[str, str]) -> dict:
        url = target["url"]
        out = {"name": target["name"], "url": url, "ok": False,
               "error": None, "health": None,
               "scrapedAt": time.time()}
        try:
            health = json.loads(self.fetch(
                f"{url}/health", self.scrape_timeout_s))
            out["health"] = health
            trace = json.loads(self.fetch(
                f"{url}/trace", self.scrape_timeout_s))
            events = trace.get("traceEvents", [])
            out["spans"] = len(events)
            with self._lock:
                self._spans.extend(events)
            out["ok"] = bool(health.get("ok", False))
        except Exception as exc:  # noqa: BLE001 — down worker = finding
            out["error"] = repr(exc)
        return out

    def scrape_once(self) -> dict:
        """One scrape round over every target; feeds the burn engine
        and returns the merged worker states."""
        results = [self._scrape_worker(t) for t in self.targets]
        with self._lock:
            for res in results:
                self._state[res["name"]] = res
            self._scrapes += 1
        ok = sum(1 for r in results if r["ok"])
        self.burn.record("worker_health", good=ok,
                         bad=len(results) - ok)
        lag = self._fleet_lag_locked()
        total_broadcast = lag.get("fleet", {}).get("broadcast", 0.0)
        self.burn.record("fleet_lag",
                         good=1 if total_broadcast <= self.lag_ceiling
                         else 0,
                         bad=0 if total_broadcast <= self.lag_ceiling
                         else 1)
        return {name: {"ok": r["ok"], "error": r["error"]}
                for name, r in ((res["name"], res) for res in results)}

    def _run(self) -> None:
        # fluidlint: disable=SHARED_STATE_NO_LOCK — threading.Event is
        # internally locked; start/stop flag it from the main thread.
        while not self._stop.is_set():
            try:
                self.scrape_once()
            except Exception:  # noqa: BLE001 — the loop must survive
                record_swallow("observatory.scrape_loop")
            self._stop.wait(self.interval_s)

    # -- merged views --------------------------------------------------
    def _fleet_lag_locked(self) -> dict:
        """Per-worker watermark snapshots + per-edge fleet totals."""
        with self._lock:
            states = dict(self._state)
        workers = {}
        fleet: Dict[str, float] = {}
        for name, res in states.items():
            wm = ((res.get("health") or {}).get("watermarks")
                  if res.get("ok") else None)
            workers[name] = wm
            if not wm:
                continue
            for edge, detail in (wm.get("lags") or {}).items():
                fleet[edge] = fleet.get(edge, 0.0) + float(
                    detail.get("total", 0.0))
        return {"workers": workers, "fleet": fleet}

    def fleet_health(self) -> dict:
        with self._lock:
            states = {name: {"ok": res["ok"], "error": res["error"],
                             "url": res["url"],
                             "scrapedAt": res["scrapedAt"]}
                      for name, res in self._state.items()}
            scrapes = self._scrapes
        burn = self.burn.evaluate()
        lag = self._fleet_lag_locked()
        workers_ok = bool(states) and all(s["ok"]
                                          for s in states.values())
        return {"ok": workers_ok and burn["ok"],
                "workers": states,
                "scrapes": scrapes,
                "lag": lag["fleet"],
                "burnRate": burn}

    def fleet_prom(self) -> str:
        """Merge every worker's exposition, injecting
        instance="<worker>" into each sample. HELP/TYPE metadata is
        emitted once per metric family (first worker wins); the
        OpenMetrics EOF terminator is re-appended once.

        Fetched from each worker at REQUEST time, not in the scrape
        loop: rendering the full histogram exposition is the most
        expensive part of a worker's monitor surface, and between
        requests nobody reads it — the periodic scrape carries only
        health + trace drains. A worker whose fetch fails contributes
        nothing to this merge (same as a down worker mid-scrape)."""
        proms: Dict[str, str] = {}
        for target in self.targets:
            try:
                proms[target["name"]] = self.fetch(
                    f"{target['url']}/metrics.prom",
                    self.scrape_timeout_s).decode("utf-8", "replace")
            except Exception:  # noqa: BLE001 — down worker = absent
                record_swallow("observatory.fleet_prom")
                continue
        lines: List[str] = []
        seen_meta = set()
        for name in sorted(proms):
            for line in proms[name].splitlines():
                if not line:
                    continue
                if line.startswith("#"):
                    if line == "# EOF":
                        continue
                    parts = line.split(None, 3)
                    key = tuple(parts[:3])  # ('#', 'TYPE'|'HELP', name)
                    if key in seen_meta:
                        continue
                    seen_meta.add(key)
                    lines.append(line)
                    continue
                m = _SAMPLE_RE.match(line)
                if m is None:
                    continue
                metric, labels, rest = m.groups()
                inst = f'instance="{name}"'
                if labels:
                    labels = "{" + inst + "," + labels[1:]
                else:
                    labels = "{" + inst + "}"
                lines.append(f"{metric}{labels} {rest}")
        lines.append("# EOF")
        return "\n".join(lines) + "\n"

    def fleet_trace(self, trace_id: Optional[str] = None) -> dict:
        """The joined timeline: every span drained from every worker,
        ordered by timestamp; each already carries its source process
        (pid + args.proc). ?trace_id= narrows to one op's journey."""
        with self._lock:
            events = list(self._spans)
        if trace_id:
            events = [e for e in events
                      if (e.get("args") or {}).get("trace_id")
                      == trace_id]
        events.sort(key=lambda e: (e.get("ts", 0), e.get("pid", 0)))
        traces: Dict[str, set] = {}
        for e in events:
            args = e.get("args") or {}
            tid = args.get("trace_id")
            if tid:
                traces.setdefault(tid, set()).add(args.get("proc"))
        return {"traceEvents": events, "displayTimeUnit": "ms",
                "joined": {
                    "traces": len(traces),
                    "crossProcess": sum(1 for procs in traces.values()
                                        if len(procs) > 1)}}

    def workers_view(self) -> dict:
        with self._lock:
            return {"targets": list(self.targets),
                    "scrapes": self._scrapes,
                    "spansHeld": len(self._spans)}

    # -- lifecycle -----------------------------------------------------
    @property
    def url(self) -> str:
        return f"http://{self.host}:{self.port}"

    def start(self) -> "FleetObservatory":
        # fluidlint: disable=SHARED_STATE_NO_LOCK — threading.Event
        self._stop.clear()
        self._thread = threading.Thread(target=self._run,
                                        name="observatory-scrape",
                                        daemon=True)
        self._thread.start()
        self._http_thread = threading.Thread(
            target=self._httpd.serve_forever, name="observatory-http",
            daemon=True)
        self._http_thread.start()
        return self

    def stop(self) -> None:
        """Safe on a never-started observatory (pull-model users call
        scrape_once() directly and only ever need the socket closed)."""
        # fluidlint: disable=SHARED_STATE_NO_LOCK — threading.Event
        self._stop.set()
        if self._http_thread is not None:
            self._httpd.shutdown()
        self._httpd.server_close()
        if self._thread is not None:
            self._thread.join(timeout=5)
        if self._http_thread is not None:
            self._http_thread.join(timeout=5)

    # -- HTTP ----------------------------------------------------------
    def _route(self, handler) -> None:
        path, _, query = handler.path.partition("?")
        content_type = "application/json"
        if path == "/fleet/health":
            payload = self.fleet_health()
            status = 200 if payload["ok"] else 503
            body = json.dumps(payload).encode()
        elif path == "/fleet/metrics.prom":
            body = self.fleet_prom().encode()
            status = 200
            content_type = ("application/openmetrics-text; "
                            "version=1.0.0; charset=utf-8")
        elif path == "/fleet/lag":
            body = json.dumps(self._fleet_lag_locked()).encode()
            status = 200
        elif path == "/fleet/trace":
            trace_id = None
            for part in query.split("&"):
                if part.startswith("trace_id="):
                    trace_id = part.split("=", 1)[1]
            body = json.dumps(self.fleet_trace(trace_id)).encode()
            status = 200
        elif path == "/fleet/workers":
            body = json.dumps(self.workers_view()).encode()
            status = 200
        else:
            body = json.dumps({"error": f"no route {path}"}).encode()
            status = 404
        handler.send_response(status)
        handler.send_header("Content-Type", content_type)
        handler.send_header("Content-Length", str(len(body)))
        handler.end_headers()
        handler.wfile.write(body)
