"""Occupancy-driven admission control: the overload front door.

The serving ring sustains a measured per-process capacity (BENCH r06),
but nothing in the ingest path protected that figure under overload:
alfred and `LocalServer` accepted every op, partition queues grew
without bound, and the p99<=2xp50 serving SLO (server/monitor.py
SloPolicy) was merely *reported* as breached. This module closes the
loop: a credit-based controller consumes the live occupancy signals the
pipeline already publishes —

  * raw-topic partition backlog (messages appended but not yet pumped
    through the sequencer; `LocalServer.raw_backlog`),
  * the sequencer's occupancy hints (in-flight window ring depth and
    staged-op backlog; `TpuSequencerLambda.occupancy_hints`),
  * the rolling serving-flush latency histogram
    (telemetry/counters.py), normalized against the declared SLO budget

— and moves ingest through explicit states:

  ACCEPT    everything admitted; only the hard queue bound applies.
  THROTTLE  per-tenant fair-share credits; over-credit submissions nack
            429 with a server-computed `retry_after` the driver already
            honors (loader/drivers/resilience.py ThrottlingError,
            loader/container.py throttle recovery).
  SHED      non-essential traffic (signals/presence, no-ops) rejected
            outright; essential ops ride tighter credits. Shedding the
            cheap-to-regenerate traffic first is what keeps the SLO
            holding *for admitted ops* instead of breached for all.
  DEGRADE   survival mode: ingest refused (503 + retry_after), archival
            pumps paused via the registered degrade hooks, queues
            bounded — the process never OOMs and never wedges.

De-escalation is hysteretic and time-based: one level per
`recover_after_s` of calm, so a controller in DEGRADE returns to ACCEPT
within ~3x `recover_after_s` of load dropping (the overload-smoke
grades this at 5 s).

The controller is deliberately deterministic and clock-injectable: the
fault-injection harness (testing/faultinject.py SkewedClock) and the
admission unit tests drive it with scripted signals and virtual time.

Sharded ingest (server/sharding.py) adds a PARTITION channel: the tier
registers one occupancy source per partition (`add_partition_source`)
and submits carry the document's home partition. Those feeds never join
the global queue depth — the aggregate core source already counts every
partition's backlog, and double-counting would re-introduce the PR 6
phantom-drain inflation N-fold. Instead they drive a per-partition soft
bound (default 2x the fair share of the queue limit): a submit to a HOT
partition throttles (429 + retry_after) even while the global ladder
sits in ACCEPT, so one storming partition cannot queue unboundedly nor
starve its siblings' admission (docs/ingest_sharding.md).

Config keys (nconf slice, all optional):
  admission.enabled      (default true)
  admission.queueLimit   hard backlog bound in queued units — broker
                         records (one submit batch = one record) plus
                         sequencer staged ops (default 4096)
  admission.throttleAt / admission.shedAt / admission.degradeAt
                         pressure thresholds (defaults 0.5 / 0.8 / 0.95)
  admission.recoverAfterS  calm seconds per de-escalation step (0.5)
  admission.sloStage     latency histogram feeding the pressure term
                         (default serving.flush)
  admission.partitionLimit  per-partition soft record bound (default
                         2x queueLimit / registered partitions)

See docs/overload.md for the full state machine and credit accounting.
"""

from __future__ import annotations

import threading
import time
from typing import Callable, Dict, List, NamedTuple, Optional

from ..telemetry.counters import (bounded, gauge, increment,
                                  latency_window, nearest_rank, observe,
                                  record_swallow)

# -- states (ordered ladder) -------------------------------------------------
ACCEPT = "accept"
THROTTLE = "throttle"
SHED = "shed"
DEGRADE = "degrade"

STATE_LEVEL = {ACCEPT: 0, THROTTLE: 1, SHED: 2, DEGRADE: 3}
LEVEL_STATE = {v: k for k, v in STATE_LEVEL.items()}

# -- op classes (shed ordering) ----------------------------------------------
# Essential traffic (sequenced content ops, joins/leaves) sheds LAST;
# transient fan-out (signals/presence) and no-ops shed FIRST — they are
# cheap for the client to regenerate and carry no document state.
CLASS_OP = "op"
CLASS_JOIN = "join"
CLASS_SIGNAL = "signal"
CLASS_NOOP = "noop"

_NON_ESSENTIAL = frozenset((CLASS_SIGNAL, CLASS_NOOP))

# Credit headroom per state: the fraction of the measured drain rate
# handed out as per-tenant credits. THROTTLE keeps near-capacity flowing
# (the point is pacing, not starving); SHED leaves slack for the queue
# to actually drain.
_HEADROOM = {THROTTLE: 0.95, SHED: 0.7}

_BURST_S = 0.25          # per-tenant credit burst window (seconds of share)
_ACTIVE_TTL_S = 2.0      # tenant counts toward fair-share split this long
# Idle buckets past this are DELETED (not just dropped from the active
# split): a churning tenant population must not grow the dict — and the
# /health status block serialized from it — without bound; a returning
# tenant simply re-buckets at zero credits.
_TENANT_EVICT_S = 10 * _ACTIVE_TTL_S
_MIN_RETRY_S = 0.05
_MAX_RETRY_S = 2.0


class Decision(NamedTuple):
    admitted: bool
    state: str
    retry_after_s: float
    reason: str


_ADMITTED = Decision(True, ACCEPT, 0.0, "ok")


class _TenantBucket:
    __slots__ = ("tokens", "last_seen")

    def __init__(self, now: float):
        self.tokens = 0.0
        self.last_seen = now


class AdmissionController:
    """One controller fronts one process's ingest (a LocalServer core,
    or shared across every tenant core of an alfred — fair-share credits
    are keyed by tenant either way)."""

    def __init__(self, queue_limit: int = 4096,
                 throttle_at: float = 0.5, shed_at: float = 0.8,
                 degrade_at: float = 0.95,
                 recover_after_s: float = 0.5,
                 interval_s: float = 0.02,
                 slo_stage: str = "serving.flush",
                 slo_ratio: float = 2.0,
                 slo_min_samples: int = 64,
                 clock: Callable[[], float] = time.monotonic,
                 partition_limit: Optional[int] = None,
                 config=None):
        self._partition_limit_cfg = partition_limit
        if config is not None:
            queue_limit = int(config.get("admission.queueLimit",
                                         queue_limit))
            throttle_at = float(config.get("admission.throttleAt",
                                           throttle_at))
            shed_at = float(config.get("admission.shedAt", shed_at))
            degrade_at = float(config.get("admission.degradeAt",
                                          degrade_at))
            recover_after_s = float(config.get("admission.recoverAfterS",
                                               recover_after_s))
            slo_stage = config.get("admission.sloStage", slo_stage)
            self._partition_limit_cfg = config.get(
                "admission.partitionLimit", self._partition_limit_cfg)
        self.queue_limit = int(queue_limit)
        self.throttle_at = float(throttle_at)
        self.shed_at = float(shed_at)
        self.degrade_at = float(degrade_at)
        self.recover_after_s = float(recover_after_s)
        self.interval_s = float(interval_s)
        self.slo_stage = slo_stage
        self.slo_ratio = float(slo_ratio)
        self.slo_min_samples = int(slo_min_samples)
        self.clock = clock

        self._lock = threading.RLock()
        self._state = ACCEPT
        self._forced: Optional[str] = None
        self._sources: Dict[str, dict] = {}
        # Per-partition fairness channel (sharded ingest): occupancy
        # feeds keyed by partition index, and the cached per-partition
        # depths (polled on the observe cadence, bumped optimistically
        # between polls exactly like the global cache).
        self._partition_sources: Dict[int, dict] = {}
        self._partition_depth: Dict[int, int] = {}
        self._tenants: Dict[str, _TenantBucket] = {}
        self._degrade_enter: List[Callable[[], None]] = []
        self._degrade_exit: List[Callable[[], None]] = []

        now = self.clock()
        self._last_observe = now - self.interval_s  # first admit observes
        self._calm_since: Optional[float] = None
        self._queue_depth = 0          # cached raw backlog + staged ops
        self._depth_at_poll = 0        # depth as of the last source poll
        self._staged_ops = 0
        self._ring_frac = 0.0
        self._lat_ratio = 0.0
        self._pressure = 0.0
        self.peak_queue_depth = 0
        self._admitted_since = 0       # records admitted since last observe
        self._rejects_since = 0        # credit rejects since last observe
        self._drain_rate: Optional[float] = None  # EWMA records/s drained
        self._drain_acc = 0.0          # queue-limited drained-op window
        self._drain_acc_dt = 0.0

    # -- wiring -------------------------------------------------------------
    def add_source(self, name: str,
                   queue_depth: Optional[Callable[[], int]] = None,
                   hints: Optional[Callable[[], dict]] = None) -> None:
        """Register an occupancy feed: `queue_depth` returns this
        source's un-pumped ingest backlog in broker records; `hints`
        returns the
        sequencer's occupancy-hint dict (ring_occupancy / ring_depth /
        staged_ops). Sources are polled on the observe cadence."""
        with self._lock:
            self._sources[name] = {"queue_depth": queue_depth,
                                   "hints": hints}

    def remove_source(self, name: str) -> None:
        with self._lock:
            self._sources.pop(name, None)

    def add_partition_source(self, partition: int,
                             queue_depth: Optional[Callable[[], int]] = None,
                             hints: Optional[Callable[[], dict]] = None,
                             scope: Optional[str] = None) -> None:
        """Register one ingest partition's occupancy feed for the
        FAIRNESS channel (module docstring): `queue_depth` returns the
        partition's raw-record backlog, `hints` the owning sequencer's
        occupancy dict (staged ops count toward the partition's depth).
        Deliberately NOT summed into the global queue depth — the
        aggregate source already counts it (double-count audit,
        docs/ingest_sharding.md).

        `scope` namespaces the channel on a SHARED controller: alfred
        runs one controller across every tenant core, and each core's
        tier registers its partitions under its tenant id — without the
        scope, core B's feeds would silently replace core A's. A
        scope-less registration (single-core deployments, direct
        controller tests) matches any tenant."""
        with self._lock:
            key = (scope, int(partition))
            self._partition_sources[key] = {
                "queue_depth": queue_depth, "hints": hints}
            self._partition_depth.setdefault(key, 0)

    def _partition_key(self, tenant: str,
                       partition: int) -> Optional[tuple]:
        """The registered feed a (tenant, partition) admit maps to:
        tenant-scoped first, then the scope-less fallback."""
        if (tenant, partition) in self._partition_sources:
            return (tenant, partition)
        if (None, partition) in self._partition_sources:
            return (None, partition)
        return None

    def partition_limit(self, scope: Optional[str] = None) -> int:
        """The per-partition soft record bound: configured, or 2x the
        fair share of the hard queue limit over the scope's partition
        count — enough headroom for benign skew, far below the point
        one partition could exhaust the global budget."""
        if self._partition_limit_cfg is not None:
            return int(self._partition_limit_cfg)
        n = sum(1 for (s, _p) in self._partition_sources if s == scope)
        if n == 0:
            # No feeds under this scope (introspection with the default
            # scope on a tenant-scoped controller): fall back to the
            # distinct partition indices across every scope.
            n = len({p for (_s, p) in self._partition_sources})
        n = max(1, n)
        return max(1, min(self.queue_limit,
                          (2 * self.queue_limit) // n))

    def add_degrade_hooks(self, enter: Callable[[], None],
                          exit: Callable[[], None]) -> None:
        """Callbacks fired on the DEGRADE boundary (pause/resume the
        archival partition pumps; LocalServer registers these)."""
        with self._lock:
            self._degrade_enter.append(enter)
            self._degrade_exit.append(exit)

    def force_state(self, state: Optional[str]) -> None:
        """Pin the ladder (tests / operator override); None releases.
        Degrade hooks fire on the boundary exactly as for organic
        transitions."""
        with self._lock:
            previous = self._state
            self._forced = state
            if state is not None:
                self._transition(previous, state)
                self._state = state
                self._calm_since = None

    # -- signal collection --------------------------------------------------
    def _poll_sources(self) -> None:
        depth = 0
        staged = 0
        ring_frac = 0.0
        for name, src in list(self._sources.items()):
            try:
                if src["queue_depth"] is not None:
                    depth += int(src["queue_depth"]())
                if src["hints"] is not None:
                    h = src["hints"]() or {}
                    staged += int(h.get("staged_ops", 0))
                    ring_depth = max(1, int(h.get("ring_depth", 1)))
                    # Occupancy is WINDOW-counted (a K-window fused
                    # burst reports K, not 1 — see TpuSequencerLambda.
                    # occupancy_hints), so the raw ratio can exceed 1
                    # whenever a burst is in flight. Clamp at "full":
                    # that keeps the latency term live during long scan
                    # steps (an uncapped ratio is not more full than
                    # full) while the 0.45 damping below still
                    # guarantees bursting-by-design never reaches the
                    # 0.5 THROTTLE threshold on its own.
                    ring_frac = max(
                        ring_frac,
                        min(1.0, float(h.get("ring_occupancy", 0))
                            / ring_depth))
            except Exception:  # noqa: BLE001 — a probe must not block ingest
                record_swallow("admission.source")
        self._staged_ops = staged
        self._queue_depth = depth + staged
        self._ring_frac = ring_frac
        if self._queue_depth > self.peak_queue_depth:
            self.peak_queue_depth = self._queue_depth
        # Fairness channel: refresh each partition's cached depth (raw
        # records + the owning sequencer's staged ops). Kept OUT of the
        # global depth above — see add_partition_source.
        for key, src in list(self._partition_sources.items()):
            try:
                d = int(src["queue_depth"]()) \
                    if src["queue_depth"] is not None else 0
                if src["hints"] is not None:
                    d += int((src["hints"]() or {}).get("staged_ops", 0))
                self._partition_depth[key] = d
            except Exception:  # noqa: BLE001 — a probe must not block ingest
                record_swallow("admission.partition_source")

    def _latency_pressure(self) -> float:
        window = latency_window(self.slo_stage)
        if len(window) < self.slo_min_samples:
            self._lat_ratio = 0.0
            return 0.0
        ordered = sorted(window)
        p50 = nearest_rank(ordered, 0.50)
        p99 = nearest_rank(ordered, 0.99)
        self._lat_ratio = (p99 / p50) if p50 > 0 else 0.0
        # Normalized so the SLO-budget edge (p99 == ratio*p50) lands
        # exactly on the THROTTLE threshold and 2x budget on DEGRADE:
        # latency spread starts pacing ingest the moment the declared
        # budget is at risk, not after it is long gone.
        return (self._lat_ratio / (2.0 * self.slo_ratio)) \
            if self._lat_ratio else 0.0

    def observe(self, force: bool = False) -> None:
        """Refresh signals + run the state ladder. Rate-limited to
        `interval_s` (the admit hot path calls this on every decision)."""
        with self._lock:
            now = self.clock()
            dt = now - self._last_observe
            if not force and dt < self.interval_s:
                return
            # Depth at the LAST poll, not the live cache: admits bump
            # the cache optimistically between polls, and reading the
            # bumped value here would count those arrivals twice (once
            # in prev_depth, once in _admitted_since), inflating the
            # capacity estimate by the admission rate — credits then
            # overshoot and the queue equilibrates half-full instead of
            # near-empty, taxing every admitted op's latency.
            prev_depth = self._depth_at_poll
            self._poll_sources()
            self._depth_at_poll = self._queue_depth
            # Drain-rate (capacity) estimate: what left the queue, but
            # only over QUEUE-LIMITED intervals — backlog present at
            # BOTH ends, so the server was verifiably saturated the
            # whole time. An idle or credit-starved server drains
            # exactly its arrival rate, which says nothing about
            # capacity — feeding those samples in is the death spiral
            # where a quiet DEGRADE decays the estimate to zero and the
            # de-escalated ladder then hands out near-zero credits.
            # Samples accumulate to a full-interval window before the
            # EWMA sees them: drains are bursty (a pump cycle lands
            # whole batches between polls, and the queue-full path
            # forces micro-dt re-polls), and an instantaneous burst/dt
            # reading can be wrong by orders of magnitude.
            drained = prev_depth + self._admitted_since - self._queue_depth
            self._admitted_since = 0
            if dt > 0 and drained >= 0 and prev_depth > 0 \
                    and self._queue_depth > 0:
                self._drain_acc += drained
                self._drain_acc_dt += dt
                if self._drain_acc_dt >= 2 * self.interval_s:
                    rate = self._drain_acc / self._drain_acc_dt
                    self._drain_rate = rate if self._drain_rate is None \
                        else 0.5 * self._drain_rate + 0.5 * rate
                    self._drain_acc = 0.0
                    self._drain_acc_dt = 0.0
            elif (self._rejects_since > 0 and self._queue_depth == 0
                    and self._drain_rate is not None
                    and STATE_LEVEL[self._state] >= 1):
                # Upward probe: credit rejects while the queue sits EMPTY
                # mean the estimate — not the server — is the limit (the
                # stall has passed, or the estimate bootstrapped low).
                # Grow it until either the rejects stop or the queue
                # starts building, at which point real queue-limited
                # samples take over and re-anchor it at true capacity.
                self._drain_rate *= 1.05
            self._rejects_since = 0
            lat_frac = self._latency_pressure()
            queue_frac = self._queue_depth / max(1, self.queue_limit)
            # A full in-flight ring is a UTILIZATION signal, not overload
            # — pipelined serving runs the ring at depth by design and
            # the ring itself is bounded (dispatch blocks at depth). It
            # contributes damped pressure (never enough to throttle on
            # its own) that stacks with real queue growth. Likewise the
            # latency-spread term only counts when ingest is actually
            # queueing: tail spread over an empty queue is compile /
            # GC noise, and pacing admitted traffic cannot fix it.
            if queue_frac <= 0.05 and self._ring_frac < 1.0:
                lat_frac = 0.0
            self._pressure = max(queue_frac, 0.45 * self._ring_frac,
                                 lat_frac)
            self._last_observe = now
            self._step_ladder(now)
            self._refill_credits(dt, now)
            gauge("admission.pressure", round(self._pressure, 4))
            gauge("admission.level", STATE_LEVEL[self._state])
            gauge("admission.queue_depth", self._queue_depth)
            gauge("admission.peak_queue_depth", self.peak_queue_depth)
            for (scope, p) in sorted(
                    self._partition_sources,
                    key=lambda k: (k[0] or "", k[1])):
                label = f"p{p}" if scope is None else f"{scope}.p{p}"
                gauge(bounded("admission.partition_depth", label),
                      self._partition_depth.get((scope, p), 0))

    # -- the ladder ---------------------------------------------------------
    def _target_level(self) -> int:
        p = self._pressure
        if p >= self.degrade_at:
            return 3
        if p >= self.shed_at:
            return 2
        if p >= self.throttle_at:
            return 1
        return 0

    def _entry_threshold(self, level: int) -> float:
        return (self.throttle_at, self.shed_at,
                self.degrade_at)[level - 1]

    def _step_ladder(self, now: float) -> None:
        if self._forced is not None:
            return
        level = STATE_LEVEL[self._state]
        target = self._target_level()
        if target > level:
            # Escalate immediately — overload does not wait politely.
            self._transition(self._state, LEVEL_STATE[target])
            self._state = LEVEL_STATE[target]
            self._calm_since = None
            return
        if level == 0:
            self._calm_since = None
            return
        # De-escalate one level per recover_after_s of sustained calm
        # (pressure clearly below the current level's entry edge) —
        # hysteresis so a queue hovering at the threshold cannot flap.
        # THROTTLE additionally requires the calm window to be free of
        # credit rejects before opening back to ACCEPT: under sustained
        # overload the credits keep the queue empty (pressure ~0), and
        # pressure-only calm would flap ACCEPT->burst->THROTTLE forever,
        # sawtoothing the queue and the admitted ops' latency with it.
        # (Credit rejects clear _calm_since in admit; SHED/DEGRADE
        # de-escalation stays pressure-only — dropping into the next
        # credit state is always safe.)
        if self._pressure < self._entry_threshold(level) * 0.7:
            if self._calm_since is None:
                self._calm_since = now
            elif now - self._calm_since >= self.recover_after_s:
                self._transition(self._state, LEVEL_STATE[level - 1])
                self._state = LEVEL_STATE[level - 1]
                self._calm_since = now
        else:
            self._calm_since = None

    def _transition(self, old: str, new: str) -> None:
        if old == new:
            return
        increment(f"admission.transitions.{old}_to_{new}")
        was_degraded = STATE_LEVEL[old] == 3
        is_degraded = STATE_LEVEL[new] == 3
        hooks = self._degrade_enter if (is_degraded and not was_degraded) \
            else self._degrade_exit if (was_degraded and not is_degraded) \
            else ()
        for hook in hooks:
            try:
                hook()
            except Exception:  # noqa: BLE001 — a pump hook must not kill admit
                record_swallow("admission.degrade_hook")

    # -- credits ------------------------------------------------------------
    def _credit_scale(self) -> float:
        """Credit rate = drain capacity x state headroom x REMAINING
        QUEUE HEADROOM. The last factor is the drain control law: with a
        standing queue, pacing at 95% of capacity would clear it at only
        5% per interval, holding admitted-op latency elevated long after
        the burst that built it; scaling the share down with queue depth
        makes the backlog clear at near-full drain rate and the system
        settle where the queue is ~empty and credits ~= capacity."""
        headroom = _HEADROOM.get(self._state, 0.0)
        return headroom * max(
            0.0, 1.0 - self._queue_depth / max(1, self.queue_limit))

    def _refill_credits(self, dt: float, now: float) -> None:
        for tenant, b in list(self._tenants.items()):
            if now - b.last_seen > _TENANT_EVICT_S:
                del self._tenants[tenant]
        if STATE_LEVEL[self._state] < 1 or dt <= 0:
            return
        active = [t for t, b in self._tenants.items()
                  if now - b.last_seen <= _ACTIVE_TTL_S]
        if not active or self._drain_rate is None:
            return
        share = self._drain_rate * self._credit_scale() / len(active)
        cap = max(1.0, share * _BURST_S)
        for tenant in active:
            bucket = self._tenants[tenant]
            bucket.tokens = min(cap, bucket.tokens + share * dt)

    def _share_rate(self, now: float) -> float:
        active = max(1, sum(1 for b in self._tenants.values()
                            if now - b.last_seen <= _ACTIVE_TTL_S))
        return (self._drain_rate or 0.0) * self._credit_scale() / active

    def _retry_after(self, need: float, now: float) -> float:
        share = self._share_rate(now)
        if share <= 0:
            return max(_MIN_RETRY_S, self.recover_after_s)
        return min(_MAX_RETRY_S, max(_MIN_RETRY_S, need / share))

    # -- the decision -------------------------------------------------------
    def admit(self, tenant: str = "local", kind: str = CLASS_OP,
              count: int = 1, records: Optional[int] = None,
              partition: Optional[int] = None,
              trace_id: Optional[str] = None) -> Decision:
        """One admission decision for `count` ops of class `kind` from
        `tenant`, arriving as `records` broker records (a multi-op
        submit batch rides ONE boxcar record — the unit `raw_backlog`
        polls and the pumps drain; defaults to `count`). Queue depth,
        the hard bound, credits, and the drain estimator all account in
        records so the cached depth stays calibrated against the polled
        backlog; the admission.* counters keep op units for
        observability. `partition` (sharded ingest) additionally applies
        the per-partition fairness bound. Thread-safe; O(1) beyond the
        rate-limited observe."""
        recs = count if records is None else records
        self.observe()
        with self._lock:
            now = self.clock()
            bucket = self._tenants.get(tenant)
            if bucket is None:
                bucket = self._tenants[tenant] = _TenantBucket(now)
            bucket.last_seen = now
            state = self._state
            # Hard bound FIRST, in every state: the raw queue must never
            # outgrow its limit, whatever the ladder believes — this is
            # the never-OOM invariant the overload bench grades.
            if kind != CLASS_SIGNAL \
                    and self._queue_depth + recs > self.queue_limit:
                # The cached depth inflates optimistically between
                # observes (every admit bumps it, only a poll decrements)
                # — re-poll before rejecting so a burst admitted inside
                # one observe interval can't trip the bound spuriously.
                self.observe(force=True)
                state = self._state
            if kind != CLASS_SIGNAL \
                    and self._queue_depth + recs > self.queue_limit:
                increment("admission.rejected.queue_full", count)
                retry = self._retry_after(recs, now)
                self._note_reject(retry, trace_id)
                return Decision(False, state if state != ACCEPT else SHED,
                                retry, "queue full")
            # Per-partition fairness bound (sharded ingest): a HOT
            # partition's documents throttle — 429 + retry_after, the
            # ladder's THROTTLE contract — while the GLOBAL state stays
            # wherever pressure puts it, so siblings keep their
            # admission untouched. Records-unit accounting, same
            # optimistic-bump/re-poll discipline as the hard bound.
            pkey = self._partition_key(tenant, partition) \
                if partition is not None else None
            if kind != CLASS_SIGNAL and pkey is not None:
                limit = self.partition_limit(pkey[0])
                if self._partition_depth.get(pkey, 0) + recs > limit:
                    self.observe(force=True)
                if self._partition_depth.get(pkey, 0) + recs > limit:
                    increment("admission.rejected.partition_hot", count)
                    # Bounded family (PR 12 cardinality guard): per-
                    # partition labels are few, but the guard is the
                    # contract for any dynamic-label family.
                    increment(bounded("admission.partition_hot",
                                      f"p{partition}"), count)
                    retry = self._retry_after(recs, now)
                    self._note_reject(retry, trace_id)
                    return Decision(False, THROTTLE, retry,
                                    f"partition {partition} hot")
            if state == ACCEPT:
                return self._admitted(kind, count, recs, pkey)
            if state == DEGRADE:
                if kind == CLASS_SIGNAL:
                    increment("admission.shed_signals", count)
                    return Decision(False, state, 0.0, "degraded")
                increment("admission.rejected.degrade", count)
                retry = max(self.recover_after_s * 2, _MIN_RETRY_S)
                self._note_reject(retry, trace_id)
                return Decision(False, state, retry, "degraded")
            if kind in _NON_ESSENTIAL and state == SHED:
                if kind == CLASS_SIGNAL:
                    increment("admission.shed_signals", count)
                else:
                    increment("admission.rejected.shed", count)
                # Signals are transient fire-and-forget: no retry loop.
                return Decision(False, state, 0.0, "shedding non-essential")
            # THROTTLE (all classes) / SHED (essential): fair-share
            # credits. With no drain estimate yet, fall back to queue
            # headroom at the state's allowance.
            if self._drain_rate is None:
                allowance = self.queue_limit * (0.75 if state == THROTTLE
                                                else 0.5)
                if self._queue_depth + recs <= allowance:
                    return self._admitted(kind, count, recs, pkey)
                increment(f"admission.rejected.{state}", count)
                self._credit_reject(recs)
                retry = self._retry_after(recs, now)
                self._note_reject(retry, trace_id)
                return Decision(False, state, retry, "no headroom")
            if bucket.tokens >= recs:
                bucket.tokens -= recs
                return self._admitted(kind, count, recs, pkey)
            increment(f"admission.rejected.{state}", count)
            self._credit_reject(recs)
            retry = self._retry_after(recs - bucket.tokens, now)
            self._note_reject(retry, trace_id)
            return Decision(False, state, retry, "over credit share")

    def retract(self, count: int = 1, records: Optional[int] = None,
                partition: Optional[int] = None,
                tenant: str = "local") -> None:
        """Undo an `admit` whose batch never reached the queue (a LATER
        gate — e.g. the per-document token bucket — nacked it). Without
        this the phantom records read as drained at the next observe,
        inflating the capacity estimate exactly when both limiters are
        active. A retract after an intervening poll can push
        `_admitted_since` negative; the drain window's `drained >= 0`
        guard discards that sample rather than crediting it."""
        recs = count if records is None else records
        with self._lock:
            self._queue_depth = max(0, self._queue_depth - recs)
            self._admitted_since -= recs
            pkey = self._partition_key(tenant, partition) \
                if partition is not None else None
            if pkey is not None:
                self._partition_depth[pkey] = max(
                    0, self._partition_depth.get(pkey, 0) - recs)
            increment("admission.retracted", count)

    def _credit_reject(self, count: int) -> None:
        """Bookkeeping for a fair-share (credit/headroom) rejection:
        feeds the upward capacity probe, and in THROTTLE resets the calm
        clock — offered load still exceeds the admitted share, so the
        door to ACCEPT must stay shut (see _step_ladder)."""
        self._rejects_since += count
        if self._state == THROTTLE:
            self._calm_since = None

    def _admitted(self, kind: str, count: int,
                  records: Optional[int] = None,
                  pkey: Optional[tuple] = None) -> Decision:
        increment("admission.admitted", count)
        if kind != CLASS_SIGNAL:
            # Signals never enter the sequencer queue. Depth is bumped
            # in RECORDS — the unit the source polls replace it with.
            recs = count if records is None else records
            self._admitted_since += recs
            self._queue_depth += recs
            if pkey is not None:
                # Optimistic per-partition bump, replaced by the next
                # poll (same discipline as the global cache).
                self._partition_depth[pkey] = \
                    self._partition_depth.get(pkey, 0) + recs
            if self._queue_depth > self.peak_queue_depth:
                self.peak_queue_depth = self._queue_depth
        return _ADMITTED if self._state == ACCEPT else Decision(
            True, self._state, 0.0, "ok")

    def _note_reject(self, retry_after_s: float,
                     trace_id: Optional[str]) -> None:
        # Histogram (with trace exemplars for /metrics.prom): how long
        # the server is asking rejected traffic to stay away.
        observe("admission.retry_wait_ms", retry_after_s * 1000.0,
                trace_id)

    # -- introspection ------------------------------------------------------
    @property
    def state(self) -> str:
        with self._lock:
            return self._state

    def queue_depth(self) -> int:
        with self._lock:
            return self._queue_depth

    def status(self) -> dict:
        """The /health `admission` block (server/monitor.py
        watch_admission)."""
        with self._lock:
            now = self.clock()
            return {
                "state": self._state,
                "level": STATE_LEVEL[self._state],
                "forced": self._forced,
                "pressure": round(self._pressure, 4),
                "queueDepth": self._queue_depth,
                "stagedOps": self._staged_ops,
                "queueLimit": self.queue_limit,
                "peakQueueDepth": self.peak_queue_depth,
                "ringOccupancyFrac": round(self._ring_frac, 4),
                "latencyRatio": round(self._lat_ratio, 3),
                "drainRateOpsS": round(self._drain_rate, 1)
                if self._drain_rate is not None else None,
                "thresholds": {"throttle": self.throttle_at,
                               "shed": self.shed_at,
                               "degrade": self.degrade_at},
                "recoverAfterS": self.recover_after_s,
                "tenants": {
                    t: {"credits": round(b.tokens, 2),
                        "idleS": round(now - b.last_seen, 3)}
                    for t, b in self._tenants.items()},
                "partitions": {
                    (str(p) if scope is None else f"{scope}:{p}"): {
                        "depth": self._partition_depth.get((scope, p), 0),
                        "limit": self.partition_limit(scope)}
                    for (scope, p) in sorted(
                        self._partition_sources,
                        key=lambda k: (k[0] or "", k[1]))
                } if self._partition_sources else None,
            }


def admission_from_config(config=None) -> Optional[AdmissionController]:
    """The standard construction gate: honors `admission.enabled`
    (default on) and passes the config through for the knob overrides."""
    if config is not None and not _truthy(
            config.get("admission.enabled", True)):
        return None
    return AdmissionController(config=config)


def _truthy(value) -> bool:
    if isinstance(value, str):
        return value.lower() not in ("0", "false", "no", "off", "")
    return bool(value)
