"""Gateway: an example host service serving documents over HTTP.

Capability parity with reference server/gateway (3,410 LoC: a web host
that loads Fluid containers server-side and serves loader pages wired to
the ordering service): this gateway loads real containers through any
driver factory, renders document state (generic DDS dump, or the data
object's own view via ViewAdapter when it provides one), and serves it as
JSON — the loader-page analog for a DOM-less host. Documents stay resident
(live against the service) between requests, so successive GETs observe
remote edits.
"""

from __future__ import annotations

import json
import re
import threading
import urllib.parse
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Dict, Optional

from ..loader.container import Container, Loader
from ..telemetry.counters import record_swallow


def _dump_channel(channel) -> dict:
    """Generic DDS state dump for rendering (feature-probed)."""
    out: dict = {"type": getattr(channel, "TYPE", "unknown")}
    if hasattr(channel, "get_text"):
        try:
            out["text"] = channel.get_text()
            return out
        except TypeError:
            pass
    if hasattr(channel, "get_items"):
        out["items"] = channel.get_items()
        return out
    if hasattr(channel, "keys"):
        try:
            out["entries"] = {k: channel.get(k) for k in channel.keys()}
            return out
        except Exception:  # noqa: BLE001 — duck-typed channel probe
            # Not actually map-shaped (keys() lied): fall through to the
            # value probe. Counted — a climbing rate means a DDS type is
            # rendering wrong in every gateway dump, not an odd one-off.
            record_swallow("gateway.channel_probe")
    if hasattr(channel, "value"):
        out["value"] = channel.value
    return out


class GatewayService:
    def __init__(self, loader: Loader, host: str = "127.0.0.1",
                 port: int = 0):
        self.loader = loader
        self.containers: Dict[str, Container] = {}
        self._adapters: Dict[tuple, object] = {}  # (doc, path) -> ViewAdapter
        self._lock = threading.Lock()
        service = self

        class Handler(BaseHTTPRequestHandler):
            protocol_version = "HTTP/1.1"

            def log_message(self, fmt, *args):  # quiet
                pass

            def do_GET(self):
                service._route(self)

        self._httpd = ThreadingHTTPServer((host, port), Handler)
        self._httpd.daemon_threads = True
        self.host, self.port = self._httpd.server_address
        self._thread: Optional[threading.Thread] = None

    # -- lifecycle ---------------------------------------------------------
    def start(self) -> "GatewayService":
        self._thread = threading.Thread(target=self._httpd.serve_forever,
                                        name="gateway", daemon=True)
        self._thread.start()
        return self

    def stop(self) -> None:
        self._httpd.shutdown()
        self._httpd.server_close()
        if self._thread:
            self._thread.join(timeout=5)
        with self._lock:
            for container in self.containers.values():
                container.close()
            self.containers.clear()

    @property
    def url(self) -> str:
        return f"http://{self.host}:{self.port}"

    # -- document residency -------------------------------------------------
    def _container(self, doc_id: str) -> Container:
        with self._lock:
            if doc_id not in self.containers:
                self.containers[doc_id] = self.loader.resolve(doc_id)
            return self.containers[doc_id]

    # -- routes -------------------------------------------------------------
    _DOC = re.compile(r"^/doc/(?P<doc>[^/]+)$")
    _OBJ = re.compile(r"^/doc/(?P<doc>[^/]+)/view(?P<path>/.*)?$")

    def _route(self, handler) -> None:
        path = urllib.parse.unquote(handler.path.partition("?")[0])
        try:
            if path == "/health":
                with self._lock:
                    resident = len(self.containers)
                return _send(handler, 200, {"ok": True,
                                            "resident": resident})
            m = self._DOC.match(path)
            if m:
                return self._serve_document(handler, m.group("doc"))
            m = self._OBJ.match(path)
            if m:
                return self._serve_view(handler, m.group("doc"),
                                        m.group("path") or "/")
            _send(handler, 404, {"error": f"no route {path}"})
        except FileNotFoundError:
            _send(handler, 404, {"error": f"unknown document {path}"})
        except Exception as exc:  # noqa: BLE001 — route bug -> 500
            _send(handler, 500, {"error": repr(exc)})

    def _serve_document(self, handler, doc_id: str) -> None:
        container = self._container(doc_id)
        with container.op_lock:
            stores = {
                store_id: {cid: _dump_channel(ch)
                           for cid, ch in store.channels.items()}
                for store_id, store in container.runtime.datastores.items()}
            _send(handler, 200, {
                "documentId": doc_id,
                "sequenceNumber": container.protocol.sequence_number,
                "dataStores": stores,
            })

    def _serve_view(self, handler, doc_id: str, path: str) -> None:
        """Render through the code-loaded data object's own view surface.
        One adapter per (doc, path) — adapters subscribe to channel events
        for their lifetime, so per-request adapters would leak listeners on
        the resident container."""
        from ..framework.views import ViewAdapter
        container = self._container(doc_id)
        with self._lock:
            adapter = self._adapters.get((doc_id, path))
            if adapter is None:
                adapter = ViewAdapter(container.request(path))
                self._adapters[(doc_id, path)] = adapter
        frames = []
        adapter.mount(frames.append)
        adapter.unmount()
        _send(handler, 200, {"documentId": doc_id, "view": frames[-1]})


def _send(handler, status: int, payload: dict) -> None:
    body = json.dumps(payload).encode()
    handler.send_response(status)
    handler.send_header("Content-Type", "application/json")
    handler.send_header("Content-Length", str(len(body)))
    handler.end_headers()
    handler.wfile.write(body)
