"""Tenant management + JWT auth (the "Riddler" role).

Capability parity with reference server/routerlicious Riddler
(`routerlicious-base/src/riddler/tenantManager.ts`, `api.ts`) and the token
helpers in services-utils (`generateToken`, jsrsasign HS256 JWTs): tenants
are registered with a per-tenant shared secret; clients present a signed
JWT whose claims scope them to (tenantId, documentId, scopes); the front
door (alfred) validates the token against the tenant key before admitting
the connection.

Implemented with stdlib hmac/hashlib (no external jose dependency) — the
wire format is a standard RFC 7519 HS256 JWT so any off-the-shelf client
library can mint compatible tokens.
"""

from __future__ import annotations

import base64
import hashlib
import hmac
import json
import secrets
import threading
import time
from dataclasses import dataclass, field
from typing import Dict, List, Optional


class AuthError(Exception):
    """Token/tenant validation failure (maps to HTTP 401/403)."""


def _b64url(data: bytes) -> str:
    return base64.urlsafe_b64encode(data).rstrip(b"=").decode("ascii")


def _b64url_decode(data: str) -> bytes:
    pad = "=" * (-len(data) % 4)
    return base64.urlsafe_b64decode(data + pad)


def sign_token(key: str, claims: dict) -> str:
    """Mint an HS256 JWT over `claims` with the tenant secret `key`."""
    header = {"alg": "HS256", "typ": "JWT"}
    signing_input = (
        _b64url(json.dumps(header, separators=(",", ":")).encode())
        + "."
        + _b64url(json.dumps(claims, separators=(",", ":")).encode())
    )
    sig = hmac.new(key.encode(), signing_input.encode(), hashlib.sha256).digest()
    return signing_input + "." + _b64url(sig)


def verify_token(key: str, token: str) -> dict:
    """Verify signature + expiry; returns the claims dict or raises AuthError."""
    try:
        signing_input, _, sig_part = token.rpartition(".")
        header_part, _, claims_part = signing_input.partition(".")
        header = json.loads(_b64url_decode(header_part))
        claims = json.loads(_b64url_decode(claims_part))
        sig = _b64url_decode(sig_part)
    except Exception as exc:  # malformed structure/base64/json
        raise AuthError(f"malformed token: {exc}") from exc
    if header.get("alg") != "HS256":
        raise AuthError(f"unsupported alg {header.get('alg')!r}")
    expected = hmac.new(key.encode(), signing_input.encode(),
                        hashlib.sha256).digest()
    if not hmac.compare_digest(sig, expected):
        raise AuthError("bad signature")
    exp = claims.get("exp")
    if exp is not None and time.time() > float(exp):
        raise AuthError("token expired")
    return claims


def generate_token(key: str, tenant_id: str, document_id: str,
                   scopes: Optional[List[str]] = None,
                   user: Optional[dict] = None,
                   lifetime_s: float = 3600.0) -> str:
    """The reference `generateToken` shape (services-utils): standard claims
    {tenantId, documentId, scopes, user, iat, exp, ver}."""
    now = time.time()
    claims = {
        "tenantId": tenant_id,
        "documentId": document_id,
        "scopes": scopes if scopes is not None
        else ["doc:read", "doc:write", "summary:write"],
        "user": user or {"id": "anonymous"},
        "iat": int(now),
        "exp": int(now + lifetime_s),
        "ver": "1.0",
    }
    return sign_token(key, claims)


@dataclass
class Tenant:
    id: str
    key: str
    storage_url: str = ""
    orderer_url: str = ""
    metadata: dict = field(default_factory=dict)


class TenantManager:
    """Tenant CRUD + token validation (Riddler). Thread-safe."""

    def __init__(self):
        self._tenants: Dict[str, Tenant] = {}
        self._lock = threading.Lock()

    def create_tenant(self, tenant_id: str,
                      key: Optional[str] = None) -> Tenant:
        with self._lock:
            if tenant_id in self._tenants:
                raise ValueError(f"tenant {tenant_id!r} exists")
            tenant = Tenant(id=tenant_id, key=key or secrets.token_hex(16))
            self._tenants[tenant_id] = tenant
            return tenant

    def get_tenant(self, tenant_id: str) -> Tenant:
        tenant = self._tenants.get(tenant_id)
        if tenant is None:
            raise AuthError(f"unknown tenant {tenant_id!r}")
        return tenant

    def get_key(self, tenant_id: str) -> str:
        return self.get_tenant(tenant_id).key

    def list_tenants(self) -> List[str]:
        return sorted(self._tenants)

    def validate_token(self, tenant_id: str, token: str,
                       document_id: Optional[str] = None,
                       scope: Optional[str] = None) -> dict:
        """Full admission check: signature, tenant match, doc match, scope."""
        claims = verify_token(self.get_key(tenant_id), token)
        if claims.get("tenantId") != tenant_id:
            raise AuthError("token tenant mismatch")
        if document_id is not None and claims.get("documentId") not in (
                document_id, "*"):
            raise AuthError("token document mismatch")
        if scope is not None and scope not in claims.get("scopes", []):
            raise AuthError(f"missing scope {scope!r}")
        return claims
