"""Sharded multi-partition ingest tier: per-partition sequencer workers.

The serving ring sustains a measured per-process ingest rate (BENCH
r06+: ~24.5k ops/s on the pipelined CPU shape), but alfred -> deli was
effectively ONE logical partition, so that figure never composed — the
million-ops/s story (ROADMAP; the Pulsar benchmarking bar in PAPERS.md)
needs N partitions, each owned by its own deli/sequencer worker, whose
per-partition service rates ADD.

This module owns everything per-partition that used to live implicitly
in ``LocalServer`` (the decoupling refactor the ROADMAP counts):

  * ``SequencerShardSet`` — the tier. One ``PartitionManager`` over the
    raw-op topic whose factory builds ONE sequencer lambda per
    partition (scalar ``DeliLambda`` or the device-batched
    ``TpuSequencerLambda`` — the host is agnostic), a restart-stable
    md5 document router (server/routing.py — the SAME scheme the
    broadcaster's fan-out shards use, so the two tiers can never
    disagree on a document's home), per-partition checkpoint/offset
    state, per-partition pump/busy accounting (the composition figure
    `bench.py ingest-smoke` grades), and optional per-partition worker
    threads.

  * ``PartitionCheckpoints`` — a partition-scoped view over the shared
    deli checkpoint collection. Without it, N ``TpuSequencerLambda``
    instances would clobber one another's single ``kind ==
    "tpu-sequencer"`` row (the scalar deli's per-document rows collide
    more subtly: every partition's restart would adopt every OTHER
    partition's documents). Rows carry an ``ingestPartition`` field;
    missing means partition 0, so pre-sharding checkpoints restore
    unchanged.

  * ``AckBatcher`` — batched cross-partition acks. Sequencer lambdas
    checkpoint through their ``LambdaContext``; with a batcher
    installed, a pump round's per-partition offset commits coalesce
    into ONE ``MessageLog.commit_many`` (one lock acquisition
    in-process; one gRPC round trip against the remote broker).
    Deferring an ack within a round only WIDENS the crash-replay
    window, so at-least-once semantics are untouched.

Admission interplay (server/admission.py): the tier registers one
occupancy source per partition (raw-record backlog + the sequencer's
occupancy hints), and ``AdmissionController.admit(partition=...)``
enforces a per-partition soft bound on top of the global ladder — one
hot partition throttles ITS documents without starving siblings, and
without the global ladder ever leaving ACCEPT (docs/ingest_sharding.md,
docs/overload.md).

Thread model: by default nothing here spawns threads — the tier pumps
on the caller's thread exactly like the pre-sharding pipeline, which is
what every deterministic in-process test relies on. ``start_workers``
opts into one daemon worker per partition (the deployment shape: one
worker per core); while workers run, a partition is only ever pumped by
its one owner: ``pump_round`` refuses outright, and runner rounds
(``LocalServer.pump`` / auto_pump drive every registered manager,
including this tier's) skip the ingest stage while still pumping the
downstream stages on the caller's thread.
"""

from __future__ import annotations

import threading
import time
from typing import Callable, Dict, List, Optional

from ..telemetry import watermarks
from ..telemetry.counters import increment
from .lambdas.base import IPartitionLambda, LambdaContext
from .partition import PartitionManager
from .routing import PartitionRouter


class AckBatcher:
    """Collects per-partition checkpoint offsets and flushes them as one
    batched cross-partition commit (``MessageLog.commit_many``).

    note() keeps only the max offset per partition (commits are
    monotonic); flush() is idempotent and cheap when empty. The batch
    is swapped out under AckBatcher._lock but committed OUTSIDE it, so
    the lock is never held across broker I/O — no ordering against the
    log's own locks exists at all."""

    def __init__(self, log, group: str, topic: str):
        self.log = log
        self.group = group
        self.topic = topic
        self._pending: Dict[int, int] = {}
        self._lock = threading.Lock()

    def note(self, partition: int, offset: int) -> None:
        with self._lock:
            held = self._pending.get(partition)
            if held is None or offset > held:
                self._pending[partition] = offset

    def flush(self) -> int:
        """Commit every noted offset in one batch; returns the number of
        partitions acked."""
        with self._lock:
            if not self._pending:
                return 0
            pending, self._pending = self._pending, {}
        # Commit OUTSIDE the lock: on the durable engine commit_many is
        # an fsync'd offsets-file rewrite, and holding _lock across it
        # would stall every other partition worker's note(). Safe
        # because commit_many is never-regress per partition on every
        # engine, so a racing higher-offset flush cannot be regressed
        # by this batch landing late.
        self.log.commit_many(self.group, self.topic, pending)
        increment("ingest.ack_batches")
        increment("ingest.acked_partitions", len(pending))
        return len(pending)

    def pending_count(self) -> int:
        with self._lock:
            return len(self._pending)


class PartitionCheckpoints:
    """Partition-scoped view over a shared checkpoint Collection: every
    row this view writes carries ``ingestPartition``, and every read
    filters on it (missing == partition 0, so checkpoints written before
    sharding restore into partition 0 unchanged). Presents exactly the
    find/find_one/upsert surface the sequencer lambdas use."""

    def __init__(self, inner, partition: int):
        self.inner = inner
        self.partition = int(partition)

    def _scope(self, predicate: Callable[[dict], bool]):
        p = self.partition
        return lambda d: (int(d.get("ingestPartition", 0)) == p
                          and predicate(d))

    def find(self, predicate: Callable[[dict], bool]) -> List[dict]:
        return self.inner.find(self._scope(predicate))

    def find_one(self, predicate: Callable[[dict], bool]) -> Optional[dict]:
        return self.inner.find_one(self._scope(predicate))

    def upsert(self, match: Callable[[dict], bool], doc: dict) -> None:
        doc = dict(doc)
        doc["ingestPartition"] = self.partition
        self.inner.upsert(self._scope(match), doc)

    def __len__(self) -> int:
        return len(self.find(lambda d: True))


class _PartitionStats:
    """Per-partition pump accounting (mutated only under the tier's
    stats lock): broker records drained, pump calls that made progress,
    and the busy wall-clock the partition's worker spent inside its
    pump — the denominator of the per-partition service rate the
    ingest-smoke composition figure sums."""

    __slots__ = ("records", "pump_calls", "busy_s", "restarts")

    def __init__(self):
        self.records = 0
        self.pump_calls = 0
        self.busy_s = 0.0
        self.restarts = 0

    def as_dict(self) -> dict:
        return {"records": self.records, "pumpCalls": self.pump_calls,
                "busyS": round(self.busy_s, 6), "restarts": self.restarts}


class _ShardedPartitionManager(PartitionManager):
    """PartitionManager whose pump round ends with a batched ack flush:
    every driver that pumps through the manager surface (LambdaRunner
    rounds, direct pump_all) keeps the committed offsets current at
    round granularity without N per-partition broker commits.

    ``workers_owned`` tells the manager the tier's per-partition worker
    threads currently own the pumps: runner rounds (``LocalServer.pump``
    drives every registered manager, this one included) SKIP the ingest
    stage instead of becoming a second concurrent driver of the same
    non-thread-safe pumps, while downstream stages keep pumping on the
    caller's thread."""

    def __init__(self, *args, acks: Optional[AckBatcher] = None,
                 workers_owned: Optional[Callable[[], bool]] = None,
                 **kwargs):
        super().__init__(*args, **kwargs)
        self.acks = acks
        self.workers_owned = workers_owned

    def pump_all(self) -> int:
        if self.workers_owned is not None and self.workers_owned():
            return 0
        n = super().pump_all()
        if self.acks is not None:
            self.acks.flush()
        return n

    def restart(self) -> None:
        # Flush first: the rebuilt lambdas' pumps reset their cursor to
        # the committed offset, and a pending (noted, unflushed) ack
        # would needlessly widen the replay window.
        if self.acks is not None:
            self.acks.flush()
        super().restart()


class SequencerShardSet:
    """The horizontally-sharded ingest tier (module docstring).

    ``lambda_factory(ctx, checkpoints)`` builds one sequencer lambda for
    a partition; ``checkpoints`` is that partition's scoped view (or
    None when the tier has no checkpoint store)."""

    def __init__(self, log, topic: str, group: str,
                 lambda_factory: Callable[..., IPartitionLambda],
                 checkpoints=None, auto_commit: bool = True,
                 batch_acks: Optional[bool] = None):
        self.log = log
        self.topic = topic
        self.group = group
        self.checkpoints = checkpoints
        topic_obj = log.topic(topic)
        self.partitions = len(topic_obj.partitions)
        self.router = PartitionRouter(self.partitions)
        # Batched acks engage for self-checkpointing lambdas on a truly
        # sharded topic; the single-partition pipeline keeps today's
        # eager per-checkpoint commit timing bit-for-bit.
        if batch_acks is None:
            batch_acks = (not auto_commit) and self.partitions > 1
        self.acks = AckBatcher(log, group, topic) if batch_acks else None

        def build(ctx: LambdaContext) -> IPartitionLambda:
            scoped = None if checkpoints is None else \
                PartitionCheckpoints(checkpoints, ctx.partition)
            lam = lambda_factory(ctx, scoped)
            if self.acks is not None:
                ctx.ack_batcher = self.acks
            return lam

        self.manager = _ShardedPartitionManager(
            log, group, topic, build, auto_commit=auto_commit,
            acks=self.acks,
            workers_owned=lambda: self.workers_running)
        # Guards the per-partition stats against concurrent workers; the
        # worker-lifecycle flags below are only written under it too.
        self._stats_lock = threading.Lock()
        self.stats: Dict[int, _PartitionStats] = {
            p: _PartitionStats() for p in self.manager.pumps}
        self._workers: List[threading.Thread] = []
        self._workers_run = False

    # -- partition access ---------------------------------------------------
    def live(self, partition: int) -> IPartitionLambda:
        """The LIVE lambda owning a partition (post-crash-restart this
        is the rebuilt instance — never cache it across restarts)."""
        return self.manager.pumps[partition].lambda_

    def partition_for(self, document_id: str) -> int:
        return self.router.partition_for(document_id)

    def sequencer_for(self, document_id: str) -> IPartitionLambda:
        """The live sequencer lambda owning a document's home partition."""
        return self.live(self.partition_for(document_id))

    def sequencers(self) -> List[IPartitionLambda]:
        return [self.live(p) for p in sorted(self.manager.pumps)]

    # -- pumping ------------------------------------------------------------
    def pump_partition(self, partition: int, limit: int = 10 ** 9) -> int:
        """Drain one partition (busy-time accounted). Does NOT flush
        batched acks — round drivers flush once per round; workers flush
        after each call (their rounds are per-partition)."""
        pump = self.manager.pumps[partition]
        t0 = time.perf_counter()
        n = pump.pump(limit=limit)
        dt = time.perf_counter() - t0
        if n:
            with self._stats_lock:
                st = self.stats[partition]
                st.records += n
                st.pump_calls += 1
                st.busy_s += dt
        return n

    def pump_round(self, limit_per_partition: int = 10 ** 9) -> int:
        """One round-robin pass over every partition + one batched ack
        flush — the single-threaded drive loop (benches, tests). Refuses
        to run while workers own the partitions."""
        with self._stats_lock:
            workers_running = self._workers_run
        if workers_running:
            raise RuntimeError(
                "pump_round while partition workers are running: a "
                "partition must only ever be pumped by its one owner")
        total = 0
        for p in sorted(self.manager.pumps):
            total += self.pump_partition(p, limit_per_partition)
        self.flush_acks()
        return total

    def flush_acks(self) -> int:
        return self.acks.flush() if self.acks is not None else 0

    # -- per-partition worker threads ----------------------------------------
    def start_workers(self, idle_sleep_s: float = 0.0005) -> None:
        """One daemon worker per partition — the deployment shape (one
        worker per core). The hosting server must stop driving the deli
        stage itself (auto_pump off / round pumps refused) while workers
        run; downstream stages (scriptorium/scribe/broadcaster) still
        pump wherever they always did."""
        with self._stats_lock:
            if self._workers_run:
                return
            self._workers_run = True
        self._workers = [
            threading.Thread(target=self._worker, args=(p, idle_sleep_s),
                             name=f"ingest-partition-{p}", daemon=True)
            for p in sorted(self.manager.pumps)]
        for t in self._workers:
            t.start()

    def stop_workers(self, timeout: float = 5.0) -> None:
        with self._stats_lock:
            self._workers_run = False
        stuck = []
        for t in self._workers:
            t.join(timeout=timeout)
            if t.is_alive():
                stuck.append(t.name)
        if stuck:
            # A worker wedged inside its pump (device compile, stalled
            # lambda) still OWNS its partition: silently returning would
            # let the caller's pump_round become a second concurrent
            # driver of the same non-thread-safe sequencer. Re-flag and
            # refuse.
            with self._stats_lock:
                self._workers_run = True
            raise RuntimeError(
                f"partition workers still alive after {timeout}s: "
                f"{stuck} — the partitions stay worker-owned; retry "
                "stop_workers with a longer timeout")
        self._workers = []
        self.flush_acks()

    @property
    def workers_running(self) -> bool:
        with self._stats_lock:
            return self._workers_run

    def _worker(self, partition: int, idle_sleep_s: float) -> None:
        while True:
            with self._stats_lock:
                if not self._workers_run:
                    return
            n = self.pump_partition(partition)
            if n:
                self.flush_acks()
            else:
                time.sleep(idle_sleep_s)

    # -- occupancy / introspection -------------------------------------------
    def raw_backlog_partition(self, partition: int) -> int:
        """One partition's un-pumped broker-record backlog (end offset
        minus the group's committed offset) — the unit admission's queue
        accounting polls (one submit batch == one boxcar record; see the
        PR 6 phantom-drain fix)."""
        part = self.log.topic(self.topic).partitions[partition]
        return max(0, part.end_offset
                   - self.log.committed(self.group, self.topic, partition))

    def raw_backlog_by_partition(self) -> Dict[int, int]:
        return {p: self.raw_backlog_partition(p)
                for p in sorted(self.manager.pumps)}

    def raw_backlog(self) -> int:
        return sum(self.raw_backlog_by_partition().values())

    def occupancy_partition(self, partition: int) -> dict:
        """Raw backlog + the owning sequencer's occupancy hints for one
        partition (hints absent for lambdas that publish none)."""
        out = {"partition": partition,
               "backlog": self.raw_backlog_partition(partition)}
        lam = self.live(partition)
        hints = getattr(lam, "occupancy_hints", None)
        if hints is not None:
            out["hints"] = hints()
        return out

    def partition_stats(self) -> List[dict]:
        """Per-partition health/metrics block (monitor watch_partitions):
        offsets, lag, staged work, and the pump accounting."""
        topic_obj = self.log.topic(self.topic)
        out = []
        with self._stats_lock:
            pump_stats = {p: st.as_dict() for p, st in self.stats.items()}
        for p in sorted(self.manager.pumps):
            end = topic_obj.partitions[p].end_offset
            committed = self.log.committed(self.group, self.topic, p)
            row = {"partition": p, "endOffset": end,
                   "committedOffset": committed,
                   "lag": max(0, end - committed)}
            lam = self.live(p)
            hints = getattr(lam, "occupancy_hints", None)
            if hints is not None:
                h = hints()
                row["stagedOps"] = int(h.get("staged_ops", 0))
                row["ringOccupancy"] = int(h.get("ring_occupancy", 0))
            row.update(pump_stats.get(p, {}))
            out.append(row)
        return out

    def refresh_watermarks(self, tenant: str = "local") -> None:
        """Stamp the ingest tier's watermarks (telemetry/watermarks.py)
        from live state: raw_end/raw_ingested from the partition
        offsets, ticketed from each sequencer's per-doc head sequence
        numbers. Pull model — called at scrape/probe/soak-tick time, so
        the op path pays nothing; replayed offsets and sequence numbers
        fold to zero inside the monotonic table."""
        topic_obj = self.log.topic(self.topic)
        for p in sorted(self.manager.pumps):
            watermarks.advance(watermarks.RAW_END, p,
                               topic_obj.partitions[p].end_offset,
                               tenant=tenant)
            committed = self.log.committed(self.group, self.topic, p)
            watermarks.advance(watermarks.RAW_INGESTED, p,
                               max(0, committed or 0), tenant=tenant)
            seqs = getattr(self.live(p), "doc_sequence_numbers", None)
            if seqs is not None:
                for doc, seq in seqs().items():
                    watermarks.advance_doc(watermarks.TICKETED, p,
                                           doc, seq, tenant=tenant)

    # -- admission wiring ----------------------------------------------------
    def register_admission(self, controller, tenant_id: str) -> None:
        """Register one occupancy source per partition with the
        admission controller's PARTITION channel (fairness gate). These
        feeds do NOT add into the controller's global queue depth — the
        hosting server's aggregate ``core:<tenant>`` source already
        counts every partition's backlog, and summing both would
        re-introduce exactly the phantom-depth inflation the PR 6
        record accounting removed (regression-tested in
        tests/test_sharded_ingest.py)."""
        for p in sorted(self.manager.pumps):
            lam = self.live(p)
            has_hints = getattr(lam, "occupancy_hints", None) is not None
            controller.add_partition_source(
                p,
                queue_depth=lambda p=p: self.raw_backlog_partition(p),
                # Resolve the lambda at poll time: a crash-restart swaps
                # the instance and a captured handle would go stale.
                hints=(lambda p=p: self.live(p).occupancy_hints())
                if has_hints else None,
                # Tenant-scoped: alfred shares ONE controller across
                # tenant cores, and each core's feeds must coexist.
                scope=tenant_id)

    # -- crash / restart ------------------------------------------------------
    def restart_partition(self, partition: int) -> None:
        """Crash-restart one partition's lambda (rebuilt from its scoped
        checkpoints; the pump replays from the last committed offset)."""
        self.flush_acks()
        self.manager.pumps[partition].restart()
        with self._stats_lock:
            self.stats[partition].restarts += 1

    def restart_all(self) -> None:
        self.manager.restart()
        with self._stats_lock:
            for st in self.stats.values():
                st.restarts += 1
