"""Sharded multi-partition ingest tier: per-partition sequencer workers.

The serving ring sustains a measured per-process ingest rate (BENCH
r06+: ~24.5k ops/s on the pipelined CPU shape), but alfred -> deli was
effectively ONE logical partition, so that figure never composed — the
million-ops/s story (ROADMAP; the Pulsar benchmarking bar in PAPERS.md)
needs N partitions, each owned by its own deli/sequencer worker, whose
per-partition service rates ADD.

This module owns everything per-partition that used to live implicitly
in ``LocalServer`` (the decoupling refactor the ROADMAP counts):

  * ``SequencerShardSet`` — the tier. One ``PartitionManager`` over the
    raw-op topic whose factory builds ONE sequencer lambda per
    partition (scalar ``DeliLambda`` or the device-batched
    ``TpuSequencerLambda`` — the host is agnostic), a restart-stable
    md5 document router (server/routing.py — the SAME scheme the
    broadcaster's fan-out shards use, so the two tiers can never
    disagree on a document's home), per-partition checkpoint/offset
    state, per-partition pump/busy accounting (the composition figure
    `bench.py ingest-smoke` grades), and optional per-partition worker
    threads.

  * ``PartitionCheckpoints`` — a partition-scoped view over the shared
    deli checkpoint collection. Without it, N ``TpuSequencerLambda``
    instances would clobber one another's single ``kind ==
    "tpu-sequencer"`` row (the scalar deli's per-document rows collide
    more subtly: every partition's restart would adopt every OTHER
    partition's documents). Rows carry an ``ingestPartition`` field;
    missing means partition 0, so pre-sharding checkpoints restore
    unchanged.

  * ``AckBatcher`` — batched cross-partition acks. Sequencer lambdas
    checkpoint through their ``LambdaContext``; with a batcher
    installed, a pump round's per-partition offset commits coalesce
    into ONE ``MessageLog.commit_many`` (one lock acquisition
    in-process; one gRPC round trip against the remote broker).
    Deferring an ack within a round only WIDENS the crash-replay
    window, so at-least-once semantics are untouched.

Admission interplay (server/admission.py): the tier registers one
occupancy source per partition (raw-record backlog + the sequencer's
occupancy hints), and ``AdmissionController.admit(partition=...)``
enforces a per-partition soft bound on top of the global ladder — one
hot partition throttles ITS documents without starving siblings, and
without the global ladder ever leaving ACCEPT (docs/ingest_sharding.md,
docs/overload.md).

Thread model: by default nothing here spawns threads — the tier pumps
on the caller's thread exactly like the pre-sharding pipeline, which is
what every deterministic in-process test relies on. ``start_workers``
opts into one daemon worker per partition (the deployment shape: one
worker per core); while workers run, a partition is only ever pumped by
its one owner: ``pump_round`` refuses outright, and runner rounds
(``LocalServer.pump`` / auto_pump drive every registered manager,
including this tier's) skip the ingest stage while still pumping the
downstream stages on the caller's thread.
"""

from __future__ import annotations

import threading
import time
from typing import Callable, Dict, List, Optional

from ..telemetry import watermarks
from ..telemetry.counters import increment, record_swallow
from .lambdas.base import IPartitionLambda, LambdaContext
from .partition import PartitionManager
from .routing import PartitionRouter

REBALANCE_KEY = "__rebalance__"
_ROUTING_ROW_KIND = "routingEpochs"


class AckBatcher:
    """Collects per-partition checkpoint offsets and flushes them as one
    batched cross-partition commit (``MessageLog.commit_many``).

    note() keeps only the max offset per partition (commits are
    monotonic); flush() is idempotent and cheap when empty. The batch
    is swapped out under AckBatcher._lock but committed OUTSIDE it, so
    the lock is never held across broker I/O — no ordering against the
    log's own locks exists at all."""

    def __init__(self, log, group: str, topic: str):
        self.log = log
        self.group = group
        self.topic = topic
        self._pending: Dict[int, int] = {}
        self._lock = threading.Lock()

    def note(self, partition: int, offset: int) -> None:
        with self._lock:
            held = self._pending.get(partition)
            if held is None or offset > held:
                self._pending[partition] = offset

    def flush(self) -> int:
        """Commit every noted offset in one batch; returns the number of
        partitions acked."""
        with self._lock:
            if not self._pending:
                return 0
            pending, self._pending = self._pending, {}
        # Commit OUTSIDE the lock: on the durable engine commit_many is
        # an fsync'd offsets-file rewrite, and holding _lock across it
        # would stall every other partition worker's note(). Safe
        # because commit_many is never-regress per partition on every
        # engine, so a racing higher-offset flush cannot be regressed
        # by this batch landing late.
        self.log.commit_many(self.group, self.topic, pending)
        increment("ingest.ack_batches")
        increment("ingest.acked_partitions", len(pending))
        return len(pending)

    def pending_count(self) -> int:
        with self._lock:
            return len(self._pending)


class PartitionCheckpoints:
    """Partition-scoped view over a shared checkpoint Collection: every
    row this view writes carries ``ingestPartition``, and every read
    filters on it (missing == partition 0, so checkpoints written before
    sharding restore into partition 0 unchanged). Presents exactly the
    find/find_one/upsert surface the sequencer lambdas use."""

    def __init__(self, inner, partition: int):
        self.inner = inner
        self.partition = int(partition)

    def _scope(self, predicate: Callable[[dict], bool]):
        p = self.partition
        return lambda d: (int(d.get("ingestPartition", 0)) == p
                          and predicate(d))

    def find(self, predicate: Callable[[dict], bool]) -> List[dict]:
        return self.inner.find(self._scope(predicate))

    def find_one(self, predicate: Callable[[dict], bool]) -> Optional[dict]:
        return self.inner.find_one(self._scope(predicate))

    def upsert(self, match: Callable[[dict], bool], doc: dict) -> None:
        doc = dict(doc)
        doc["ingestPartition"] = self.partition
        self.inner.upsert(self._scope(match), doc)

    def __len__(self) -> int:
        return len(self.find(lambda d: True))


class _PartitionStats:
    """Per-partition pump accounting (mutated only under the tier's
    stats lock): broker records drained, pump calls that made progress,
    and the busy wall-clock the partition's worker spent inside its
    pump — the denominator of the per-partition service rate the
    ingest-smoke composition figure sums."""

    __slots__ = ("records", "pump_calls", "busy_s", "restarts")

    def __init__(self):
        self.records = 0
        self.pump_calls = 0
        self.busy_s = 0.0
        self.restarts = 0

    def as_dict(self) -> dict:
        return {"records": self.records, "pumpCalls": self.pump_calls,
                "busyS": round(self.busy_s, 6), "restarts": self.restarts}


class _ShardedPartitionManager(PartitionManager):
    """PartitionManager whose pump round ends with a batched ack flush:
    every driver that pumps through the manager surface (LambdaRunner
    rounds, direct pump_all) keeps the committed offsets current at
    round granularity without N per-partition broker commits.

    ``workers_owned`` tells the manager the tier's per-partition worker
    threads currently own the pumps: runner rounds (``LocalServer.pump``
    drives every registered manager, this one included) SKIP the ingest
    stage instead of becoming a second concurrent driver of the same
    non-thread-safe pumps, while downstream stages keep pumping on the
    caller's thread."""

    def __init__(self, *args, acks: Optional[AckBatcher] = None,
                 workers_owned: Optional[Callable[[], bool]] = None,
                 **kwargs):
        super().__init__(*args, **kwargs)
        self.acks = acks
        self.workers_owned = workers_owned

    def pump_all(self) -> int:
        if self.workers_owned is not None and self.workers_owned():
            return 0
        n = super().pump_all()
        if self.acks is not None:
            self.acks.flush()
        return n

    def restart(self) -> None:
        # Flush first: the rebuilt lambdas' pumps reset their cursor to
        # the committed offset, and a pending (noted, unflushed) ack
        # would needlessly widen the replay window.
        if self.acks is not None:
            self.acks.flush()
        super().restart()


class _RebalancingSequencer(IPartitionLambda):
    """Per-partition handoff shim around the real sequencer lambda: it
    intercepts the two rebalance control records riding the raw topic
    and buffers a re-homed document's traffic until its state arrives.

      handoff marker (on the SOURCE partition): export the document's
        live state through the inner lambda's ``export_doc``, durably
        produce the adopt record onto the TARGET partition, then
        ``drop_doc`` (tombstoned checkpoint row). Replay-idempotent:
        once dropped, export returns None and the marker is a no-op.

      adopt record (on the TARGET partition): ``adopt_doc`` installs the
        state (dup adopts ignored), then the shim drains every record it
        buffered for the document IN ARRIVAL ORDER — so the document's
        op stream is processed exactly as: everything before the marker
        by the old owner, everything after the adopt by the new one,
        with nothing lost, duplicated, or reordered in between.

    The awaiting set re-derives on (re)build from the router's override
    table minus the documents the inner lambda restored — a crashed
    target partition comes back buffering for exactly the adoptions its
    checkpoint has not absorbed yet. Buffered records survive the crash
    too: the shim durably notes the FIRST buffered offset per document
    (one upsert per handoff, not per record) and on rebuild re-reads
    [fromOffset, committed) from the log (``MessageLog.read_from`` —
    offset-indexed on the durable engine), because the pump has already
    committed past those records and will never replay them. Everything
    else (occupancy hints, doc_sequence_numbers, ``docs``...) delegates
    to the inner lambda."""

    def __init__(self, inner: IPartitionLambda, tier: "SequencerShardSet",
                 partition: int, checkpoints=None):
        self.inner = inner
        self.tier = tier
        self.partition = int(partition)
        self.checkpoints = checkpoints
        self.buffered: Dict[str, List] = {}
        owned = getattr(inner, "docs", {})
        self.awaiting = {
            doc for doc in tier.router.overrides_targeting(self.partition)
            if doc not in owned}
        self._recover_buffered()

    def _recover_buffered(self) -> None:
        """Re-read pre-crash buffered records from the log: their offsets
        were committed when they were buffered (the pump's cursor must
        advance), so replay will never re-deliver them — the durable
        fromOffset note is what makes buffering crash-safe."""
        if self.checkpoints is None or not self.awaiting:
            return
        committed = self.tier.log.committed(
            self.tier.group, self.tier.topic, self.partition)
        for row in self.checkpoints.find(
                lambda d: d.get("kind") == "rebalanceBuffer"):
            doc = row.get("documentId")
            start = int(row.get("fromOffset", -1))
            if doc not in self.awaiting or start < 0 or committed <= start:
                continue
            for msg in self.tier.log.read_from(
                    self.tier.topic, self.partition, start,
                    committed - start):
                if msg.key == doc and not (
                        isinstance(msg.value, dict)
                        and REBALANCE_KEY in msg.value):
                    self.buffered.setdefault(doc, []).append(msg)
                    increment("ingest.rebalance_buffer_recovered")

    def _note_buffering(self, doc_id: str, offset: int) -> None:
        if self.checkpoints is None:
            return
        self.checkpoints.upsert(
            lambda d, _id=doc_id: (d.get("kind") == "rebalanceBuffer"
                                   and d.get("documentId") == _id),
            {"kind": "rebalanceBuffer", "documentId": doc_id,
             "fromOffset": int(offset)})

    # -- control-plane ------------------------------------------------------
    def expect(self, doc_id: str) -> None:
        """Arm buffering for a document whose adoption is in flight (the
        tier calls this BEFORE installing the routing override, so no
        post-bump record can reach the inner lambda unowned)."""
        self.awaiting.add(doc_id)

    def _mark_offset(self, message) -> None:
        # Control records must advance the inner lambda's checkpoint
        # cursor like any other handled record, or a marker at the head
        # of a quiet partition would replay forever under batched acks.
        if hasattr(self.inner, "_pending_offset"):
            self.inner._pending_offset = message.offset

    def _handoff(self, message, record: dict) -> None:
        doc_id = record["doc"]
        target = int(record["target"])
        epoch = int(record.get("epoch", 0))
        dump = self.inner.export_doc(doc_id)
        if dump is None:
            # Replayed marker after the drop, or a document this
            # partition never sequenced: the adopt record is already
            # durably on the target (or there is no state to move).
            record_swallow("ingest.rebalance_marker_noop")
            return
        # Durably publish the state BEFORE dropping it: a crash between
        # the two replays this marker and re-exports; the target dedups
        # duplicate adopts. The reverse order could lose the document.
        self.tier.log.send_to(
            self.tier.topic, target, doc_id,
            {REBALANCE_KEY: "adopt", "doc": doc_id, "state": dump,
             "epoch": epoch, "source": self.partition})
        self.inner.drop_doc(doc_id, epoch)
        increment("ingest.rebalance_handoffs")

    def _adopt(self, message, record: dict) -> None:
        doc_id = record["doc"]
        if self.inner.adopt_doc(doc_id, record["state"]):
            increment("ingest.rebalance_adoptions")
        else:
            record_swallow("ingest.rebalance_adopt_dup")
        self.awaiting.discard(doc_id)
        self._note_buffering(doc_id, -1)  # retire the recovery note
        for held in self.buffered.pop(doc_id, []):
            self.inner.handler(held)

    # -- IPartitionLambda ---------------------------------------------------
    def handler(self, message) -> None:
        value = message.value
        if isinstance(value, dict) and REBALANCE_KEY in value:
            if value[REBALANCE_KEY] == "handoff":
                self._handoff(message, value)
            else:
                self._adopt(message, value)
            self._mark_offset(message)
            return
        if message.key in self.awaiting:
            # The document's state is still in flight from its old
            # owner: hold the record and replay it after adoption — per-
            # doc order across the handoff is arrival order, bit-for-bit.
            if message.key not in self.buffered:
                # Durable note BEFORE the pump can commit this offset:
                # a crash while awaiting re-reads from here.
                self._note_buffering(message.key, message.offset)
            self.buffered.setdefault(message.key, []).append(message)
            self._mark_offset(message)
            increment("ingest.rebalance_buffered")
            return
        self.inner.handler(message)

    def flush(self) -> None:
        self.inner.flush()

    def close(self) -> None:
        self.inner.close()

    def __getattr__(self, item):
        return getattr(self.inner, item)

    # The shim's OWN state is this fixed set; everything else — reads
    # above, and writes here — belongs to the wrapped sequencer. Without
    # write-through, `tier.live(p).client_timeout_s = ...` (and every
    # other knob callers poke on "the sequencer") would silently land on
    # the shim and never reach the lambda that reads it.
    _OWN_ATTRS = frozenset(
        {"inner", "tier", "partition", "checkpoints", "buffered",
         "awaiting"})

    def __setattr__(self, name, value):
        if name in _RebalancingSequencer._OWN_ATTRS:
            object.__setattr__(self, name, value)
        else:
            setattr(self.inner, name, value)


class SequencerShardSet:
    """The horizontally-sharded ingest tier (module docstring).

    ``lambda_factory(ctx, checkpoints)`` builds one sequencer lambda for
    a partition; ``checkpoints`` is that partition's scoped view (or
    None when the tier has no checkpoint store).

    ``partitions_owned`` (default: all of the topic's partitions) is the
    cross-host placement config: a worker process that owns a subset
    pumps ONLY those partitions against the shared remote broker, so
    scaling out is deploy/RUNBOOK.md config — two hosts owning [0..7]
    and [8..15] ARE the 16-partition tier. Routing (partition_for) still
    spans the full partition count on every host."""

    def __init__(self, log, topic: str, group: str,
                 lambda_factory: Callable[..., IPartitionLambda],
                 checkpoints=None, auto_commit: bool = True,
                 batch_acks: Optional[bool] = None,
                 partitions_owned: Optional[List[int]] = None):
        self.log = log
        self.topic = topic
        self.group = group
        self.checkpoints = checkpoints
        topic_obj = log.topic(topic)
        self.partitions = len(topic_obj.partitions)
        self.router = PartitionRouter(self.partitions)
        self._load_routing()
        # Batched acks engage for self-checkpointing lambdas on a truly
        # sharded topic; the single-partition pipeline keeps today's
        # eager per-checkpoint commit timing bit-for-bit.
        if batch_acks is None:
            batch_acks = (not auto_commit) and self.partitions > 1
        self.acks = AckBatcher(log, group, topic) if batch_acks else None

        def build(ctx: LambdaContext) -> IPartitionLambda:
            scoped = None if checkpoints is None else \
                PartitionCheckpoints(checkpoints, ctx.partition)
            lam = lambda_factory(ctx, scoped)
            if self.acks is not None:
                ctx.ack_batcher = self.acks
            return _RebalancingSequencer(lam, self, ctx.partition,
                                         checkpoints=scoped)

        self.manager = _ShardedPartitionManager(
            log, group, topic, build, auto_commit=auto_commit,
            acks=self.acks,
            workers_owned=lambda: self.workers_running,
            partitions=partitions_owned)
        # Guards the per-partition stats against concurrent workers; the
        # worker-lifecycle flags below are only written under it too.
        self._stats_lock = threading.Lock()
        self.stats: Dict[int, _PartitionStats] = {
            p: _PartitionStats() for p in self.manager.pumps}
        self._workers: List[threading.Thread] = []
        self._workers_run = False

    # -- partition access ---------------------------------------------------
    def live(self, partition: int) -> IPartitionLambda:
        """The LIVE lambda owning a partition (post-crash-restart this
        is the rebuilt instance — never cache it across restarts)."""
        return self.manager.pumps[partition].lambda_

    def partition_for(self, document_id: str) -> int:
        return self.router.partition_for(document_id)

    def delta_partition_for(self, document_id: str) -> int:
        """EMIT-side (deltas/broadcast) routing anchor: always the base
        md5 home, never a rebalance override — a document's output
        stream stays on one partition forever, which is what makes
        per-doc delivery order across a live rebalance total within one
        partition by construction (no consumer-side reordering gate)."""
        return self.router.base_partition_for(document_id)

    def sequencer_for(self, document_id: str) -> IPartitionLambda:
        """The live sequencer lambda owning a document's home partition."""
        return self.live(self.partition_for(document_id))

    def sequencers(self) -> List[IPartitionLambda]:
        return [self.live(p) for p in sorted(self.manager.pumps)]

    # -- live rebalancing ----------------------------------------------------
    def rebalance_doc(self, document_id: str, target: int) -> int:
        """Re-home one document's raw-topic sequencing to ``target``
        with NO fleet drain — returns the new routing epoch.

        Protocol (every step crash-replayable, docs/ingest_sharding.md):

          1. arm the target partition's buffering (``expect``) so a
             post-bump submit can never reach its sequencer unowned;
          2. persist the override (epoch bump) — submits now route to
             the target, where they buffer behind the in-flight state;
          3. append the handoff marker on the SOURCE partition; when the
             old owner pumps it, it exports the document's state,
             durably produces the adopt record onto the target, and
             drops the document (tombstoned checkpoint row).

        Because the marker rides the raw topic itself, everything the
        old owner sequenced BEFORE the override keeps its order, and
        everything after drains on the target after adoption — per-doc
        emit order is identical to the no-rebalance run."""
        document_id = str(document_id)
        source = self.router.partition_for(document_id)
        target = int(target)
        if target == source:
            return self.router.epoch
        if not 0 <= target < self.partitions:
            raise ValueError(
                f"rebalance target {target} out of range "
                f"[0, {self.partitions})")
        # Hook validation up front: the TPU-batched sequencer checkpoints
        # whole-lane state (one kind=="tpu-sequencer" row) and has no
        # per-document export surface — fail BEFORE any state changes.
        for p, role in ((source, "source"), (target, "target")):
            if p not in self.manager.pumps:
                raise RuntimeError(
                    f"rebalance_doc: {role} partition {p} is not owned "
                    "by this process (partitions_owned subset) — invoke "
                    "the rebalance on a host owning both partitions")
            lam = self.live(p)
            for hook in ("export_doc", "adopt_doc", "drop_doc"):
                if not callable(getattr(lam, hook, None)):
                    raise RuntimeError(
                        f"rebalance_doc: {role} partition {p} lambda "
                        f"({type(getattr(lam, 'inner', lam)).__name__}) "
                        f"has no {hook}() — live per-document handoff "
                        "requires the scalar DeliLambda sequencer")
        wrapper = self.manager.pumps[target].lambda_
        if isinstance(wrapper, _RebalancingSequencer):
            wrapper.expect(document_id)
        epoch = self.router.install_override(document_id, target)
        self._persist_routing()
        self.log.send_to(
            self.topic, source, document_id,
            {REBALANCE_KEY: "handoff", "doc": document_id,
             "target": target, "epoch": epoch})
        increment("ingest.rebalance_requests")
        return epoch

    def _persist_routing(self) -> None:
        """Durably record the override table in the shared checkpoint
        collection (ingestPartition=-1 keeps the row out of every
        partition's scoped view) — a restarted tier re-derives the same
        routes, so restart stability now includes live-rebalance moves."""
        if self.checkpoints is None:
            return
        row = {"kind": _ROUTING_ROW_KIND, "ingestPartition": -1}
        row.update(self.router.snapshot())
        self.checkpoints.upsert(
            lambda d: d.get("kind") == _ROUTING_ROW_KIND, row)

    def _load_routing(self) -> None:
        if self.checkpoints is None:
            return
        row = self.checkpoints.find_one(
            lambda d: d.get("kind") == _ROUTING_ROW_KIND)
        if row is not None:
            self.router.restore(row)

    # -- pumping ------------------------------------------------------------
    def pump_partition(self, partition: int, limit: int = 10 ** 9) -> int:
        """Drain one partition (busy-time accounted). Does NOT flush
        batched acks — round drivers flush once per round; workers flush
        after each call (their rounds are per-partition)."""
        pump = self.manager.pumps[partition]
        t0 = time.perf_counter()
        n = pump.pump(limit=limit)
        dt = time.perf_counter() - t0
        if n:
            with self._stats_lock:
                st = self.stats[partition]
                st.records += n
                st.pump_calls += 1
                st.busy_s += dt
        return n

    def pump_round(self, limit_per_partition: int = 10 ** 9) -> int:
        """One round-robin pass over every partition + one batched ack
        flush — the single-threaded drive loop (benches, tests). Refuses
        to run while workers own the partitions."""
        with self._stats_lock:
            workers_running = self._workers_run
        if workers_running:
            raise RuntimeError(
                "pump_round while partition workers are running: a "
                "partition must only ever be pumped by its one owner")
        total = 0
        for p in sorted(self.manager.pumps):
            total += self.pump_partition(p, limit_per_partition)
        self.flush_acks()
        return total

    def flush_acks(self) -> int:
        return self.acks.flush() if self.acks is not None else 0

    # -- per-partition worker threads ----------------------------------------
    def start_workers(self, idle_sleep_s: float = 0.0005) -> None:
        """One daemon worker per partition — the deployment shape (one
        worker per core). The hosting server must stop driving the deli
        stage itself (auto_pump off / round pumps refused) while workers
        run; downstream stages (scriptorium/scribe/broadcaster) still
        pump wherever they always did."""
        with self._stats_lock:
            if self._workers_run:
                return
            self._workers_run = True
        self._workers = [
            threading.Thread(target=self._worker, args=(p, idle_sleep_s),
                             name=f"ingest-partition-{p}", daemon=True)
            for p in sorted(self.manager.pumps)]
        for t in self._workers:
            t.start()

    def stop_workers(self, timeout: float = 5.0) -> None:
        with self._stats_lock:
            self._workers_run = False
        stuck = []
        for t in self._workers:
            t.join(timeout=timeout)
            if t.is_alive():
                stuck.append(t.name)
        if stuck:
            # A worker wedged inside its pump (device compile, stalled
            # lambda) still OWNS its partition: silently returning would
            # let the caller's pump_round become a second concurrent
            # driver of the same non-thread-safe sequencer. Re-flag and
            # refuse.
            with self._stats_lock:
                self._workers_run = True
            raise RuntimeError(
                f"partition workers still alive after {timeout}s: "
                f"{stuck} — the partitions stay worker-owned; retry "
                "stop_workers with a longer timeout")
        self._workers = []
        self.flush_acks()

    @property
    def workers_running(self) -> bool:
        with self._stats_lock:
            return self._workers_run

    def _worker(self, partition: int, idle_sleep_s: float) -> None:
        while True:
            with self._stats_lock:
                if not self._workers_run:
                    return
            n = self.pump_partition(partition)
            if n:
                self.flush_acks()
            else:
                time.sleep(idle_sleep_s)

    # -- occupancy / introspection -------------------------------------------
    def raw_backlog_partition(self, partition: int) -> int:
        """One partition's un-pumped broker-record backlog (end offset
        minus the group's committed offset) — the unit admission's queue
        accounting polls (one submit batch == one boxcar record; see the
        PR 6 phantom-drain fix)."""
        part = self.log.topic(self.topic).partitions[partition]
        return max(0, part.end_offset
                   - self.log.committed(self.group, self.topic, partition))

    def raw_backlog_by_partition(self) -> Dict[int, int]:
        return {p: self.raw_backlog_partition(p)
                for p in sorted(self.manager.pumps)}

    def raw_backlog(self) -> int:
        return sum(self.raw_backlog_by_partition().values())

    def occupancy_partition(self, partition: int) -> dict:
        """Raw backlog + the owning sequencer's occupancy hints for one
        partition (hints absent for lambdas that publish none)."""
        out = {"partition": partition,
               "backlog": self.raw_backlog_partition(partition)}
        lam = self.live(partition)
        hints = getattr(lam, "occupancy_hints", None)
        if hints is not None:
            out["hints"] = hints()
        return out

    def partition_stats(self) -> List[dict]:
        """Per-partition health/metrics block (monitor watch_partitions):
        offsets, lag, staged work, and the pump accounting."""
        topic_obj = self.log.topic(self.topic)
        out = []
        with self._stats_lock:
            pump_stats = {p: st.as_dict() for p, st in self.stats.items()}
        for p in sorted(self.manager.pumps):
            end = topic_obj.partitions[p].end_offset
            committed = self.log.committed(self.group, self.topic, p)
            row = {"partition": p, "endOffset": end,
                   "committedOffset": committed,
                   "lag": max(0, end - committed)}
            lam = self.live(p)
            hints = getattr(lam, "occupancy_hints", None)
            if hints is not None:
                h = hints()
                row["stagedOps"] = int(h.get("staged_ops", 0))
                row["ringOccupancy"] = int(h.get("ring_occupancy", 0))
            row.update(pump_stats.get(p, {}))
            out.append(row)
        return out

    def refresh_watermarks(self, tenant: str = "local") -> None:
        """Stamp the ingest tier's watermarks (telemetry/watermarks.py)
        from live state: raw_end/raw_ingested from the partition
        offsets, ticketed from each sequencer's per-doc head sequence
        numbers. Pull model — called at scrape/probe/soak-tick time, so
        the op path pays nothing; replayed offsets and sequence numbers
        fold to zero inside the monotonic table."""
        topic_obj = self.log.topic(self.topic)
        for p in sorted(self.manager.pumps):
            watermarks.advance(watermarks.RAW_END, p,
                               topic_obj.partitions[p].end_offset,
                               tenant=tenant)
            committed = self.log.committed(self.group, self.topic, p)
            watermarks.advance(watermarks.RAW_INGESTED, p,
                               max(0, committed or 0), tenant=tenant)
            seqs = getattr(self.live(p), "doc_sequence_numbers", None)
            if seqs is not None:
                for doc, seq in seqs().items():
                    watermarks.advance_doc(watermarks.TICKETED, p,
                                           doc, seq, tenant=tenant)

    # -- admission wiring ----------------------------------------------------
    def register_admission(self, controller, tenant_id: str) -> None:
        """Register one occupancy source per partition with the
        admission controller's PARTITION channel (fairness gate). These
        feeds do NOT add into the controller's global queue depth — the
        hosting server's aggregate ``core:<tenant>`` source already
        counts every partition's backlog, and summing both would
        re-introduce exactly the phantom-depth inflation the PR 6
        record accounting removed (regression-tested in
        tests/test_sharded_ingest.py)."""
        for p in sorted(self.manager.pumps):
            lam = self.live(p)
            has_hints = getattr(lam, "occupancy_hints", None) is not None
            controller.add_partition_source(
                p,
                queue_depth=lambda p=p: self.raw_backlog_partition(p),
                # Resolve the lambda at poll time: a crash-restart swaps
                # the instance and a captured handle would go stale.
                hints=(lambda p=p: self.live(p).occupancy_hints())
                if has_hints else None,
                # Tenant-scoped: alfred shares ONE controller across
                # tenant cores, and each core's feeds must coexist.
                scope=tenant_id)

    # -- crash / restart ------------------------------------------------------
    def restart_partition(self, partition: int) -> None:
        """Crash-restart one partition's lambda (rebuilt from its scoped
        checkpoints; the pump replays from the last committed offset)."""
        self.flush_acks()
        self.manager.pumps[partition].restart()
        with self._stats_lock:
            self.stats[partition].restarts += 1

    def restart_all(self) -> None:
        self.manager.restart()
        with self._stats_lock:
            for st in self.stats.values():
                st.restarts += 1
