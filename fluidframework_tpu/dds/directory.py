"""SharedDirectory: hierarchical key-value DDS.

Capability parity with reference packages/dds/map/src/directory.ts (1624
LoC): a tree of subdirectories, each with its own MapKernel-style key store;
ops carry the subdirectory path; subdirectory create/delete are ops too.
"""

from __future__ import annotations

import json
from typing import Any, Dict, Iterator, List, Optional, Tuple

from ..protocol.summary import SummaryTree
from .map import MapKernel
from .shared_object import SharedObject, collect_handles


class SubDirectory:
    def __init__(self, directory: "SharedDirectory", path: str):
        self.directory = directory
        self.path = path  # absolute, "/" is root
        self.kernel = MapKernel()
        self.subdirs: Dict[str, "SubDirectory"] = {}

    # -- keys --------------------------------------------------------------
    def get(self, key: str, default: Any = None) -> Any:
        return self.kernel.data.get(key, default)

    def set(self, key: str, value: Any) -> "SubDirectory":
        op = self.kernel.set(key, value)
        self.directory._submit_storage_op(self.path, op)
        return self

    def delete(self, key: str) -> None:
        self.directory._submit_storage_op(self.path, self.kernel.delete(key))

    def clear(self) -> None:
        self.directory._submit_storage_op(self.path, self.kernel.clear())

    def has(self, key: str) -> bool:
        return key in self.kernel.data

    def wait(self, key: str, timeout: Optional[float] = None) -> Any:
        """Block until `key` exists in THIS subdirectory and return its
        value (reference IDirectory.wait). Resolution rules match
        SharedMap.wait; events are watched on the owning SharedDirectory
        (kernel checks keep this path-scoped)."""
        from .map import wait_for
        return wait_for(
            self.directory, "valueChanged",
            lambda: (key in self.kernel.data, self.kernel.data.get(key)),
            timeout)

    def keys(self) -> Iterator[str]:
        return iter(list(self.kernel.data.keys()))

    def items(self) -> Iterator[Tuple[str, Any]]:
        return iter(list(self.kernel.data.items()))

    def __len__(self) -> int:
        return len(self.kernel.data)

    # -- subdirectories ----------------------------------------------------
    def create_sub_directory(self, name: str) -> "SubDirectory":
        sub = self.subdirs.get(name)
        if sub is None:
            sub = self._create_child(name)
            self.directory._submit_create_op(self.path, name)
        return sub

    def _create_child(self, name: str) -> "SubDirectory":
        path = self.path.rstrip("/") + "/" + name
        sub = SubDirectory(self.directory, path)
        self.subdirs[name] = sub
        return sub

    def get_sub_directory(self, name: str) -> Optional["SubDirectory"]:
        return self.subdirs.get(name)

    def delete_sub_directory(self, name: str) -> None:
        if name in self.subdirs:
            del self.subdirs[name]
            self.directory._submit_delete_op(self.path, name)

    def subdirectories(self) -> Iterator[Tuple[str, "SubDirectory"]]:
        return iter(list(self.subdirs.items()))

    # -- snapshot ----------------------------------------------------------
    def to_dict(self) -> dict:
        return {
            "storage": self.kernel.data,
            "subdirectories": {name: sub.to_dict()
                               for name, sub in sorted(self.subdirs.items())},
        }

    def load_dict(self, data: dict) -> None:
        self.kernel.data = dict(data.get("storage", {}))
        for name, sub_data in data.get("subdirectories", {}).items():
            self._create_child(name).load_dict(sub_data)


class SharedDirectory(SharedObject):
    TYPE = "https://graph.microsoft.com/types/directory"

    def __init__(self, object_id: str, runtime=None):
        super().__init__(object_id, runtime)
        self.root = SubDirectory(self, "/")
        # In-flight subdirectory create/delete ops (resubmitted on reconnect
        # before storage ops so their target paths exist).
        self._pending_subdir_ops: List[dict] = []

    # Root passthrough (reference ISharedDirectory extends IDirectory).
    def get(self, key, default=None):
        return self.root.get(key, default)

    def set(self, key, value):
        self.root.set(key, value)
        return self

    def delete(self, key):
        self.root.delete(key)

    def has(self, key):
        return self.root.has(key)

    def wait(self, key, timeout=None):
        return self.root.wait(key, timeout)

    def keys(self):
        return self.root.keys()

    def items(self):
        return self.root.items()

    def create_sub_directory(self, name):
        return self.root.create_sub_directory(name)

    def get_sub_directory(self, name):
        return self.root.get_sub_directory(name)

    def get_working_directory(self, path: str) -> Optional[SubDirectory]:
        node = self.root
        for part in path.strip("/").split("/"):
            if not part:
                continue
            node = node.get_sub_directory(part)
            if node is None:
                return None
        return node

    # -- op plumbing -------------------------------------------------------
    def _submit_storage_op(self, path: str, op: dict) -> None:
        self.submit_local_message({"type": "storage", "path": path, "op": op})

    def _submit_create_op(self, path: str, name: str) -> None:
        op = {"type": "createSubDirectory", "path": path, "name": name}
        self._pending_subdir_ops.append(op)
        self.submit_local_message(op)

    def _submit_delete_op(self, path: str, name: str) -> None:
        op = {"type": "deleteSubDirectory", "path": path, "name": name}
        self._pending_subdir_ops.append(op)
        self.submit_local_message(op)

    def connect(self) -> None:
        if not self.attached:
            def scrub(sub: SubDirectory):
                sub.kernel.pending_keys.clear()
                sub.kernel.pending_clear_count = 0
                for child in sub.subdirs.values():
                    scrub(child)
            scrub(self.root)
            self._pending_subdir_ops.clear()
        super().connect()

    def process_core(self, contents, local, seq, ref_seq, client_ordinal,
                     min_seq) -> None:
        t = contents["type"]
        if t == "storage":
            sub = self.get_working_directory(contents["path"])
            if sub is not None:
                sub.kernel.process(contents["op"], local)
                self.emit("valueChanged", contents["path"],
                          contents["op"].get("key"), local)
        elif t == "createSubDirectory":
            if local:
                self._retire_subdir_op(t, contents)
            parent = self.get_working_directory(contents["path"])
            if parent is not None and contents["name"] not in parent.subdirs:
                parent._create_child(contents["name"])
                self.emit("subDirectoryCreated", contents["path"],
                          contents["name"], local)
        elif t == "deleteSubDirectory":
            if local:
                self._retire_subdir_op(t, contents)
            parent = self.get_working_directory(contents["path"])
            # Apply on the submitter too (idempotent pop): the optimistic
            # local delete already removed it, but a concurrent remote
            # create sequenced before this op resurrects the subdir — the
            # sequenced delete must then win identically on every replica.
            if parent is not None and \
                    contents["name"] in parent.subdirs:
                parent.subdirs.pop(contents["name"], None)
                self.emit("subDirectoryDeleted", contents["path"],
                          contents["name"], local)

    def _retire_subdir_op(self, op_type: str, contents: dict) -> None:
        for i, op in enumerate(self._pending_subdir_ops):
            if op["type"] == op_type and op["path"] == contents["path"] \
                    and op["name"] == contents["name"]:
                del self._pending_subdir_ops[i]
                return

    def resubmit_pending(self) -> List[Any]:
        ops: List[dict] = list(self._pending_subdir_ops)

        def walk(sub: SubDirectory):
            for op in sub.kernel.pending_ops():
                ops.append({"type": "storage", "path": sub.path, "op": op})
            for child in sub.subdirs.values():
                walk(child)
        walk(self.root)
        return ops

    def summarize_core(self) -> SummaryTree:
        return SummaryTree().add_blob(
            "header", json.dumps(self.root.to_dict(), sort_keys=True))

    def load_core(self, tree: SummaryTree) -> None:
        from .shared_object import decode_handles
        self.root.load_dict(
            decode_handles(json.loads(tree.entries["header"].content)))

    def get_gc_data(self) -> List[str]:
        routes: List[str] = []

        def walk(sub: SubDirectory):
            collect_handles(sub.kernel.data, routes)
            for child in sub.subdirs.values():
                walk(child)
        walk(self.root)
        return routes
