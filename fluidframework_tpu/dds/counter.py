"""SharedCounter: commutative increment DDS.

Capability parity with reference packages/dds/counter/src/counter.ts —
increments commute, so remote and pending-local deltas just add; acks retire
pending records (value already applied).
"""

from __future__ import annotations

import json
from typing import Any, List

from ..protocol.summary import SummaryTree
from .shared_object import SharedObject


class SharedCounter(SharedObject):
    TYPE = "https://graph.microsoft.com/types/counter"

    def __init__(self, object_id: str, runtime=None):
        super().__init__(object_id, runtime)
        self.value = 0
        self._pending: List[int] = []

    def increment(self, delta: int = 1) -> None:
        if not isinstance(delta, int):
            raise TypeError("SharedCounter increments must be integers")
        self.value += delta
        self._pending.append(delta)
        self.emit("incremented", delta, self.value)
        self.submit_local_message({"type": "increment", "delta": delta})

    def connect(self) -> None:
        if not self.attached:
            self._pending.clear()
        super().connect()

    def process_core(self, contents, local, seq, ref_seq, client_ordinal,
                     min_seq) -> None:
        if local:
            self._pending.pop(0)
            return
        self.value += contents["delta"]
        self.emit("incremented", contents["delta"], self.value)

    def resubmit_pending(self) -> List[Any]:
        return [{"type": "increment", "delta": d} for d in self._pending]

    def summarize_core(self) -> SummaryTree:
        # Snapshot the *acked* value: pending deltas re-apply via ops.
        acked = self.value - sum(self._pending)
        return SummaryTree().add_blob("header", json.dumps({"value": acked}))

    def load_core(self, tree: SummaryTree) -> None:
        self.value = json.loads(tree.entries["header"].content)["value"]
