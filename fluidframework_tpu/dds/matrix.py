"""SharedMatrix: 2-D cells addressed through two permutation vectors.

Capability parity with reference packages/dds/matrix/src/{matrix.ts:75,
permutationvector.ts:126}: rows and columns are each a merge-tree sequence
of *runs* of stable ids (the reference's handle allocation becomes run
payloads carrying (client, counter, offset) ids — the same origin-lineage
trick the device kernel uses for text). Cells live in a sparse dict keyed by
stable (row_id, col_id), so cell writes never conflict with row/col
insertion or removal; set-vs-set conflicts resolve LWW with pending-local
shadowing (reference conflict-resolution + handle recycling via zamboni).
"""

from __future__ import annotations

import json
import random
from typing import Any, Dict, List, Optional, Tuple

from ..mergetree.client import MergeTreeClient, OP_INSERT, OP_REMOVE
from ..mergetree.constants import SEG_TEXT, UNASSIGNED_SEQ
from ..mergetree.runs import Run, id_key as _id_key
from ..protocol.summary import SummaryTree
from .shared_object import SharedObject, collect_handles


class PermutationVector:
    """A merge-tree client whose payloads are Runs (reference
    permutationvector.ts: PermutationVector extends Client).

    Run id bases use a per-session random nonce, not the client ordinal:
    the base ships inside the insert op, so replica consistency never
    depends on join timing (a pre-join insert must not collide)."""

    def __init__(self, client_id: int = -1):
        self.client = MergeTreeClient(client_id)
        self.run_counter = 0
        self.nonce = random.getrandbits(48)

    @property
    def tree(self):
        return self.client.tree

    def count(self) -> int:
        return self.client.get_length()

    def insert_local(self, pos: int, count: int) -> dict:
        self.run_counter += 1
        run = Run((self.nonce, self.run_counter), 0, count)
        tree = self.client.tree
        from ..mergetree.oracle import Segment
        seg = Segment(kind=SEG_TEXT, text=run)
        tree.insert(pos, seg, tree.current_seq, self.client.client_id,
                    UNASSIGNED_SEQ)
        return {"type": OP_INSERT, "pos1": pos,
                "seg": {"run": run.encode()}}

    def remove_local(self, pos: int, count: int) -> dict:
        return self.client.remove_range_local(pos, pos + count)

    def apply_remote(self, op: dict, seq: int, ref_seq: int, client: int):
        if op["type"] == OP_INSERT:
            run = Run.decode(op["seg"]["run"])
            tree = self.client.tree
            from ..mergetree.oracle import Segment
            seg = Segment(kind=SEG_TEXT, text=run)
            tree.insert(op["pos1"], seg, ref_seq, client, seq)
            tree.update_seq(seq)
        else:
            self.client.apply_msg(op, seq, ref_seq, client)

    def ack(self, seq: int) -> None:
        self.client.tree.ack(seq)
        self.client.tree.update_seq(seq)

    def ids_in_order(self) -> List[Tuple[int, int, int]]:
        tree = self.client.tree
        out: List[Tuple[int, int, int]] = []
        for seg in tree.segments:
            if tree.visible_length(seg, tree.current_seq,
                                   self.client.client_id) > 0:
                out.extend(seg.text.ids())
        return out

    def id_at(self, index: int) -> Tuple[int, int, int]:
        tree = self.client.tree
        acc = 0
        for seg in tree.segments:
            vlen = tree.visible_length(seg, tree.current_seq,
                                       self.client.client_id)
            if acc + vlen > index:
                return seg.text[index - acc]
            acc += vlen
        raise IndexError(index)

    def changes_for_seq(self, seq: int) -> List[Tuple[int, int]]:
        """Visible-position deltas applied by the op sequenced at `seq`:
        [(pos, +count)] for inserts, [(pos, -count)] for removes, in
        ascending position order. This is how remote axis ops resolve to
        consumer notifications (reference permutationvector.ts onDelta →
        rows/colsChanged positions) — the flat segment walk replaces the
        reference's tracked-segment-group machinery."""
        tree = self.client.tree
        out: List[Tuple[int, int]] = []
        acc = 0
        for seg in tree.segments:
            if seg.rem_seq == seq:
                # Removed by this op. If our own pending remove was
                # overwritten by it (rem_overlap carries our client id),
                # the segment was already hidden locally — no view change.
                if self.client.client_id in seg.rem_overlap:
                    continue
                # Position (for the notification) is where it used to sit.
                if out and out[-1][1] < 0 and out[-1][0] == acc:
                    out[-1] = (acc, out[-1][1] - seg.length)
                else:
                    out.append((acc, -seg.length))
                continue
            vlen = tree.visible_length(seg, tree.current_seq,
                                       self.client.client_id)
            if seg.ins_seq == seq and vlen > 0:
                if out and out[-1][1] > 0 and out[-1][0] + out[-1][1] == acc:
                    out[-1] = (out[-1][0], out[-1][1] + vlen)
                else:
                    out.append((acc, vlen))
            acc += vlen
        return out

    def index_of_id(self, key: str) -> Optional[int]:
        """Current visible index of a stable id (None if removed).
        O(#segments): runs carry contiguous id spans, so one range check
        per segment replaces materializing every id."""
        a, b, c = (int(x) for x in key.split("."))
        tree = self.client.tree
        acc = 0
        for seg in tree.segments:
            vlen = tree.visible_length(seg, tree.current_seq,
                                       self.client.client_id)
            if vlen == 0:
                continue
            run = seg.text
            if run.base == (a, b) and run.start <= c < run.start + vlen:
                return acc + (c - run.start)
            acc += vlen
        return None

    def snapshot(self) -> dict:
        snap = self.client.snapshot()
        for entry in snap["segments"]:
            if isinstance(entry.get("text"), Run):
                entry["text"] = {"run": entry["text"].encode()}
        return snap

    def load(self, snap: dict, client_id: int) -> None:
        for entry in snap["segments"]:
            if isinstance(entry.get("text"), dict) and "run" in entry["text"]:
                entry["text"] = Run.decode(entry["text"]["run"])
        self.client = MergeTreeClient.load(snap, client_id=client_id)
        self.run_counter = 0


class SharedMatrix(SharedObject):
    """The matrix DDS + the IMatrixProducer surface: views register via
    open_matrix(consumer) and receive rows_changed / cols_changed /
    cells_changed callbacks for local AND remote changes with resolved
    visible positions (reference matrix.ts IMatrixProducer/IMatrixConsumer
    from @tiny-calc/nano; handle recycling is unnecessary here — stable
    (nonce, counter, offset) ids never get reused, so there is no free
    list to manage)."""

    TYPE = "https://graph.microsoft.com/types/sharedmatrix"

    def __init__(self, object_id: str, runtime=None):
        super().__init__(object_id, runtime)
        self.rows = PermutationVector(self.local_client_id)
        self.cols = PermutationVector(self.local_client_id)
        # cell key "(rowid,colid)" -> value; pending LWW shadow counts
        self.cells: Dict[str, Any] = {}
        self._pending_cells: Dict[str, int] = {}
        self._consumers: List[Any] = []

    # -- IMatrixProducer ----------------------------------------------------
    def open_matrix(self, consumer: Any) -> "SharedMatrix":
        """Register a change consumer (reference IMatrixProducer.
        openMatrix). Consumers implement any of rows_changed(pos, delta),
        cols_changed(pos, delta), cells_changed(row, col, value)."""
        if consumer not in self._consumers:
            self._consumers.append(consumer)
        return self

    def close_matrix(self, consumer: Any) -> None:
        if consumer in self._consumers:
            self._consumers.remove(consumer)

    def _notify(self, method: str, *args) -> None:
        for consumer in list(self._consumers):
            fn = getattr(consumer, method, None)
            if fn is not None:
                fn(*args)

    # -- lifecycle ---------------------------------------------------------
    def adopt_client_ordinal(self, ordinal: int) -> None:
        self.rows.client.update_client_id(ordinal)
        self.cols.client.update_client_id(ordinal)

    def connect(self) -> None:
        if not self.attached:
            self.rows.client.commit_detached()
            self.cols.client.commit_detached()
            self._pending_cells.clear()
        super().connect()

    # -- dimensions --------------------------------------------------------
    @property
    def row_count(self) -> int:
        return self.rows.count()

    @property
    def col_count(self) -> int:
        return self.cols.count()

    def insert_rows(self, pos: int, count: int) -> None:
        op = self.rows.insert_local(pos, count)
        self.submit_local_message({"target": "rows", "op": op})
        self.emit("rowsChanged", pos, count, True, None)
        self._notify("rows_changed", pos, count)

    def insert_cols(self, pos: int, count: int) -> None:
        op = self.cols.insert_local(pos, count)
        self.submit_local_message({"target": "cols", "op": op})
        self.emit("colsChanged", pos, count, True, None)
        self._notify("cols_changed", pos, count)

    def _capture_axis(self, axis: str, pos: int, count: int) -> dict:
        """Cell contents of the rows/cols about to be removed, keyed by the
        OTHER axis's stable ids — the undo provider reinserts fresh
        rows/cols and restores by surviving-axis identity (reference
        matrix undoprovider.ts revert via tracked segments)."""
        if axis == "rows":
            gone = [_id_key(r) for r in self.rows.ids_in_order()[
                pos:pos + count]]
            other = [_id_key(c) for c in self.cols.ids_in_order()]
            cells = [{c: self.cells[g + "|" + c] for c in other
                      if g + "|" + c in self.cells} for g in gone]
        else:
            gone = [_id_key(c) for c in self.cols.ids_in_order()[
                pos:pos + count]]
            other = [_id_key(r) for r in self.rows.ids_in_order()]
            cells = [{r: self.cells[r + "|" + g] for r in other
                      if r + "|" + g in self.cells} for g in gone]
        return {"cells": cells}

    def remove_rows(self, pos: int, count: int) -> None:
        captured = self._capture_axis("rows", pos, count)
        op = self.rows.remove_local(pos, count)
        self.submit_local_message({"target": "rows", "op": op})
        self.emit("rowsChanged", pos, -count, True, captured)
        self._notify("rows_changed", pos, -count)

    def remove_cols(self, pos: int, count: int) -> None:
        captured = self._capture_axis("cols", pos, count)
        op = self.cols.remove_local(pos, count)
        self.submit_local_message({"target": "cols", "op": op})
        self.emit("colsChanged", pos, -count, True, captured)
        self._notify("cols_changed", pos, -count)

    # -- undo support -------------------------------------------------------
    def restore_rows(self, pos: int, captured: dict) -> None:
        """Reinsert removed rows and restore their cells against columns
        that still exist (by stable column id)."""
        cells = captured["cells"]
        self.insert_rows(pos, len(cells))
        col_ids = {_id_key(c): i
                   for i, c in enumerate(self.cols.ids_in_order())}
        for i, row_cells in enumerate(cells):
            for col_id, value in row_cells.items():
                if col_id in col_ids:
                    self.set_cell(pos + i, col_ids[col_id], value)

    def restore_cols(self, pos: int, captured: dict) -> None:
        cells = captured["cells"]
        self.insert_cols(pos, len(cells))
        row_ids = {_id_key(r): i
                   for i, r in enumerate(self.rows.ids_in_order())}
        for i, col_cells in enumerate(cells):
            for row_id, value in col_cells.items():
                if row_id in row_ids:
                    self.set_cell(row_ids[row_id], pos + i, value)

    # -- cells ---------------------------------------------------------------
    def _cell_key(self, row: int, col: int) -> str:
        return _id_key(self.rows.id_at(row)) + "|" + \
            _id_key(self.cols.id_at(col))

    def set_cell(self, row: int, col: int, value: Any) -> None:
        key = self._cell_key(row, col)
        previous = self.cells.get(key)
        self.cells[key] = value
        self._pending_cells[key] = self._pending_cells.get(key, 0) + 1
        self.submit_local_message(
            {"target": "cell", "key": key, "value": value})
        self.emit("cellChanged", row, col, value, True, previous)
        self._notify("cells_changed", row, col, value)

    def set_cells(self, row_start: int, col_start: int, col_count: int,
                  values) -> None:
        """Write a rectangular run row-major (reference matrix.ts:189
        setCells: col_count wide, wrapping to the next row)."""
        values = list(values)
        if col_count <= 0:
            raise ValueError("col_count must be positive")
        for i, value in enumerate(values):
            self.set_cell(row_start + i // col_count,
                          col_start + i % col_count, value)

    def get_cell(self, row: int, col: int) -> Any:
        return self.cells.get(self._cell_key(row, col))

    def extract(self) -> List[List[Any]]:
        row_ids = [_id_key(r) for r in self.rows.ids_in_order()]
        col_ids = [_id_key(c) for c in self.cols.ids_in_order()]
        return [[self.cells.get(r + "|" + c) for c in col_ids]
                for r in row_ids]

    # -- processing ----------------------------------------------------------
    def process_core(self, contents, local, seq, ref_seq, client_ordinal,
                     min_seq) -> None:
        target = contents["target"]
        if target == "cell":
            key = contents["key"]
            if local:
                n = self._pending_cells.get(key, 0)
                if n > 1:
                    self._pending_cells[key] = n - 1
                else:
                    self._pending_cells.pop(key, None)
                return
            if key in self._pending_cells:
                return  # pending local write shadows (reference set-vs-set)
            previous = self.cells.get(key)
            self.cells[key] = contents["value"]
            if not self._consumers and \
                    self.listener_count("cellChanged") == 0:
                return  # nobody to notify: skip index resolution entirely
            # Resolve the stable cell id to current visible indices (None
            # when the row/col has since been removed — the write still
            # lands by identity and reappears if the axis is restored).
            row_key, _, col_key = key.partition("|")
            row = self.rows.index_of_id(row_key)
            col = self.cols.index_of_id(col_key)
            self.emit("cellChanged", row, col, contents["value"], False,
                      previous)
            if row is not None and col is not None:
                self._notify("cells_changed", row, col, contents["value"])
            return
        vector = self.rows if target == "rows" else self.cols
        if local:
            vector.ack(seq)
        else:
            vector.apply_remote(contents["op"], seq, ref_seq, client_ordinal)
            event = "rowsChanged" if target == "rows" else "colsChanged"
            method = "rows_changed" if target == "rows" else "cols_changed"
            if not self._consumers and self.listener_count(event) == 0:
                return  # nobody to notify: skip the position walk
            for pos, delta in vector.changes_for_seq(seq):
                self.emit(event, pos, delta, False, None)
                self._notify(method, pos, delta)

    def resubmit_pending(self) -> List[Any]:
        ops = []
        for op in self.rows.client.regenerate_pending_ops():
            if "seg" in op and isinstance(op["seg"].get("text"), Run):
                op["seg"] = {"run": op["seg"]["text"].encode()}
            ops.append({"target": "rows", "op": op})
        for op in self.cols.client.regenerate_pending_ops():
            if "seg" in op and isinstance(op["seg"].get("text"), Run):
                op["seg"] = {"run": op["seg"]["text"].encode()}
            ops.append({"target": "cols", "op": op})
        for key in self._pending_cells:
            self._pending_cells[key] = 1
            ops.append({"target": "cell", "key": key,
                        "value": self.cells.get(key)})
        return ops

    # -- summary -------------------------------------------------------------
    def summarize_core(self) -> SummaryTree:
        tree = SummaryTree()
        tree.add_blob("rows", json.dumps(self.rows.snapshot()))
        tree.add_blob("cols", json.dumps(self.cols.snapshot()))
        tree.add_blob("cells", json.dumps(self.cells, sort_keys=True))
        return tree

    def load_core(self, tree: SummaryTree) -> None:
        self.rows.load(json.loads(tree.entries["rows"].content),
                       self.local_client_id)
        self.cols.load(json.loads(tree.entries["cols"].content),
                       self.local_client_id)
        self.cells = json.loads(tree.entries["cells"].content)

    def get_gc_data(self) -> List[str]:
        routes: List[str] = []
        collect_handles(self.cells, routes)
        return routes
