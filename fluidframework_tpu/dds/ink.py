"""Ink: freehand stroke DDS.

Capability parity with reference packages/dds/ink/src/ink.ts: strokes are
created with a pen (color/thickness), points append monotonically per
stroke, clear wipes the canvas. Ink ops are commutative per stroke (points
append in sequenced order), so there is no pending/shadow machinery —
matching the reference's straightforward op application.
"""

from __future__ import annotations

import itertools
import json
from typing import Any, Dict, List, Optional

from ..protocol.summary import SummaryTree
from .shared_object import SharedObject

_stroke_uid = itertools.count(1)


class Ink(SharedObject):
    TYPE = "https://graph.microsoft.com/types/ink"

    def __init__(self, object_id: str, runtime=None):
        super().__init__(object_id, runtime)
        # stroke id -> {"pen": {...}, "points": [{x, y, time, pressure}]}
        self.strokes: Dict[str, dict] = {}
        self._order: List[str] = []

    # -- api (ink.ts createStroke/appendPointToStroke/clear) ---------------
    def create_stroke(self, pen: Optional[dict] = None) -> str:
        stroke_id = f"stroke-{self.local_client_id}-{next(_stroke_uid)}"
        op = {"type": "createStroke", "id": stroke_id, "pen": pen or {}}
        self._apply(op)
        self.submit_local_message(op)
        return stroke_id

    def append_point_to_stroke(self, stroke_id: str, point: dict) -> None:
        op = {"type": "stylus", "id": stroke_id, "point": point}
        self._apply(op)
        self.submit_local_message(op)

    def clear(self) -> None:
        op = {"type": "clear"}
        self._apply(op)
        self.submit_local_message(op)

    def get_stroke(self, stroke_id: str) -> Optional[dict]:
        return self.strokes.get(stroke_id)

    def get_strokes(self) -> List[dict]:
        return [self.strokes[sid] for sid in self._order]

    # -- op application ----------------------------------------------------
    def _apply(self, op: dict) -> None:
        t = op["type"]
        if t == "createStroke":
            if op["id"] not in self.strokes:
                self.strokes[op["id"]] = {"id": op["id"], "pen": op["pen"],
                                          "points": []}
                self._order.append(op["id"])
        elif t == "stylus":
            stroke = self.strokes.get(op["id"])
            if stroke is not None:
                stroke["points"].append(op["point"])
        elif t == "clear":
            self.strokes = {}
            self._order = []

    def process_core(self, contents, local, seq, ref_seq, client_ordinal,
                     min_seq) -> None:
        if local:
            return  # applied eagerly at submit; append order already fixed
        self._apply(contents)
        self.emit("ink", contents, False)

    def resubmit_pending(self) -> List[Any]:
        # Ink ops are idempotent-enough for the canvas use case; the
        # reference resubmits verbatim as well (no position rewrite needed).
        return []

    # -- snapshot ----------------------------------------------------------
    def summarize_core(self) -> SummaryTree:
        return SummaryTree().add_blob("header", json.dumps(
            {"order": self._order, "strokes": self.strokes},
            sort_keys=True))

    def load_core(self, tree: SummaryTree) -> None:
        data = json.loads(tree.entries["header"].content)
        self.strokes = data["strokes"]
        self._order = data["order"]
