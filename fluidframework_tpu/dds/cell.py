"""SharedCell: a single LWW value (reference packages/dds/cell/src/cell.ts).

Same pending-local-shadow discipline as the map kernel, for one slot.
"""

from __future__ import annotations

import json
from typing import Any, List

from ..protocol.summary import SummaryTree
from .shared_object import SharedObject, collect_handles


class SharedCell(SharedObject):
    TYPE = "https://graph.microsoft.com/types/cell"

    _EMPTY = object()

    def __init__(self, object_id: str, runtime=None):
        super().__init__(object_id, runtime)
        self.value: Any = None
        self._has_value = False
        self._pending_count = 0

    def get(self) -> Any:
        return self.value

    def set(self, value: Any) -> None:
        self.value = value
        self._has_value = True
        self._pending_count += 1
        self.emit("valueChanged", value, True)
        from .shared_object import encode_handles
        self.submit_local_message({"type": "setCell",
                                   "value": encode_handles(value)})

    def delete(self) -> None:
        self.value = None
        self._has_value = False
        self._pending_count += 1
        self.emit("delete", True)
        self.submit_local_message({"type": "deleteCell"})

    def empty(self) -> bool:
        return not self._has_value

    def connect(self) -> None:
        if not self.attached:
            self._pending_count = 0
        super().connect()

    def process_core(self, contents, local, seq, ref_seq, client_ordinal,
                     min_seq) -> None:
        if local:
            if self._pending_count > 0:
                self._pending_count -= 1
            return
        if self._pending_count > 0:
            return  # pending local write shadows remote
        if contents["type"] == "setCell":
            from .shared_object import decode_handles
            self.value = decode_handles(contents["value"])
            self._has_value = True
            self.emit("valueChanged", self.value, False)
        else:
            self.value = None
            self._has_value = False
            self.emit("delete", False)

    def resubmit_pending(self) -> List[Any]:
        if self._pending_count == 0:
            return []
        # Collapse to the latest local intent.
        self._pending_count = 1
        if self._has_value:
            return [{"type": "setCell", "value": self.value}]
        return [{"type": "deleteCell"}]

    def summarize_core(self) -> SummaryTree:
        blob = json.dumps({"value": self.value, "hasValue": self._has_value})
        return SummaryTree().add_blob("header", blob)

    def load_core(self, tree: SummaryTree) -> None:
        data = json.loads(tree.entries["header"].content)
        self.value = data["value"]
        self._has_value = data["hasValue"]

    def get_gc_data(self) -> List[str]:
        routes: List[str] = []
        collect_handles(self.value, routes)
        return routes
