"""ConsensusOrderedCollection: a distributed work queue with acquire leases.

Capability parity with reference packages/dds/ordered-collection/src/
consensusOrderedCollection.ts:34-61 — add/acquire/complete/release op
protocol: `acquire` removes the head only when the op is sequenced and
grants it to the acquiring client; `complete` finishes the item; `release`
(or the holder leaving the quorum) returns it to the queue, giving
crash-safe task distribution.
"""

from __future__ import annotations

import json
import uuid
from typing import Any, Callable, Dict, List, Optional

from ..protocol.summary import SummaryTree
from .shared_object import SharedObject


class ConsensusQueue(SharedObject):
    TYPE = "https://graph.microsoft.com/types/consensus-queue"

    def __init__(self, object_id: str, runtime=None):
        super().__init__(object_id, runtime)
        self.items: List[dict] = []  # {"id", "value"}
        # acquired id -> {"value", "clientId"} (in-flight leases)
        self.jobs: Dict[str, dict] = {}
        self._acquire_waiters: Dict[str, Callable[[Optional[Any]], None]] = {}
        # In-flight ops, retired FIFO at local ack; non-acquire ops are
        # resubmitted after a reconnect so queued work is never lost.
        self._inflight: List[dict] = []

    def _submit(self, op: dict) -> None:
        self._inflight.append(op)
        self.submit_local_message(op)

    # -- producers ---------------------------------------------------------
    def add(self, value: Any) -> None:
        item = {"id": uuid.uuid4().hex, "value": value}
        if not self.attached:
            self.items.append(item)
            return
        self._submit({"type": "add", "item": item})

    # -- consumers -----------------------------------------------------------
    def acquire(self, callback: Callable[[Optional[str], Optional[Any]], None]
                ) -> None:
        """Request the queue head. callback(item_id, value) fires when our
        acquire op sequences — (None, None) if the queue was empty."""
        req = uuid.uuid4().hex
        self._acquire_waiters[req] = callback
        self._submit({"type": "acquire", "req": req})

    def complete(self, item_id: str) -> None:
        self._submit({"type": "complete", "id": item_id})

    def release(self, item_id: str) -> None:
        self._submit({"type": "release", "id": item_id})

    # -- processing ----------------------------------------------------------
    def process_core(self, contents, local, seq, ref_seq, client_ordinal,
                     min_seq) -> None:
        t = contents["type"]
        if local and self._inflight:
            self._inflight.pop(0)
        if t == "add":
            self.items.append(contents["item"])
            self.emit("add", contents["item"]["value"], local)
        elif t == "acquire":
            granted = self.items.pop(0) if self.items else None
            if granted is not None:
                self.jobs[granted["id"]] = {
                    "value": granted["value"], "client": client_ordinal}
                self.emit("acquire", granted["value"], client_ordinal)
            if local:
                waiter = self._acquire_waiters.pop(contents["req"], None)
                if waiter:
                    if granted is None:
                        waiter(None, None)
                    else:
                        waiter(granted["id"], granted["value"])
        elif t == "complete":
            job = self.jobs.pop(contents["id"], None)
            if job is not None:
                self.emit("complete", job["value"])
        elif t == "release":
            job = self.jobs.pop(contents["id"], None)
            if job is not None:
                self.items.insert(0, {"id": contents["id"],
                                      "value": job["value"]})
                self.emit("localRelease" if local else "release", job["value"])

    def client_left(self, client_ordinal: int) -> None:
        """Quorum-leave hook: release every lease held by the departed
        client (reference releaseAll on removeMember)."""
        for item_id in [i for i, j in self.jobs.items()
                        if j["client"] == client_ordinal]:
            job = self.jobs.pop(item_id)
            self.items.insert(0, {"id": item_id, "value": job["value"]})
            self.emit("release", job["value"])

    def resubmit_pending(self) -> List[Any]:
        # Adds/completes/releases replay (idempotent against current state);
        # consensus acquires don't — their waiters are failed out.
        for waiter in self._acquire_waiters.values():
            waiter(None, None)
        self._acquire_waiters.clear()
        out = [op for op in self._inflight if op["type"] != "acquire"]
        self._inflight = list(out)
        return out

    def summarize_core(self) -> SummaryTree:
        blob = json.dumps({"items": self.items, "jobs": self.jobs},
                          sort_keys=True)
        return SummaryTree().add_blob("header", blob)

    def load_core(self, tree: SummaryTree) -> None:
        data = json.loads(tree.entries["header"].content)
        self.items = data["items"]
        self.jobs = data["jobs"]
