"""SharedSegmentSequence + SharedString over the merge-tree client.

Capability parity with reference packages/dds/sequence/src/{sequence.ts:51,
sharedString.ts:36}: text insert/remove/annotate, markers, position queries,
delta events, snapshot (header + chunked body, snapshotV1.ts:33-40), and
reconnect resubmission delegated to the merge-tree client's pending-op
rewrite (client.ts:863).
"""

from __future__ import annotations

import json
from typing import Any, List, Optional

from ..mergetree.client import MergeTreeClient
from ..mergetree.constants import SNAPSHOT_CHUNK_SIZE
from ..protocol.summary import SummaryTree
from .shared_object import SharedObject


class SharedSegmentSequence(SharedObject):
    TYPE = "https://graph.microsoft.com/types/mergeTree"

    def __init__(self, object_id: str, runtime=None):
        super().__init__(object_id, runtime)
        self.client = MergeTreeClient(client_id=self.local_client_id)
        self.client.on("delta", lambda args, local:
                       self.emit("sequenceDelta", args, local))

    def bind_to_runtime(self, runtime) -> None:
        super().bind_to_runtime(runtime)
        # Adopt the runtime's client ordinal (retags pending segments too).
        self.client.update_client_id(runtime.client_ordinal)

    # -- queries -----------------------------------------------------------
    def get_length(self) -> int:
        return self.client.get_length()

    # -- lifecycle ---------------------------------------------------------
    def adopt_client_ordinal(self, ordinal: int) -> None:
        self.client.update_client_id(ordinal)

    def connect(self) -> None:
        if not self.attached and self.client.tree.pending_groups:
            # Detached edits fold into the attach summary, not ops.
            self.client.commit_detached()
        super().connect()

    # -- channel plumbing --------------------------------------------------
    def process_core(self, contents, local, seq, ref_seq, client_ordinal,
                     min_seq) -> None:
        self.client.apply_msg(contents, seq, ref_seq, client_ordinal,
                              min_seq=min_seq)

    def resubmit_pending(self) -> List[Any]:
        return self.client.regenerate_pending_ops()

    def summarize_core(self) -> SummaryTree:
        """Chunked snapshot: header with collab window + body chunks of
        bounded size (reference snapshotV1.ts chunking, chunkSize=10000)."""
        snap = self.client.snapshot()
        segments = snap["segments"]
        chunks: List[List[dict]] = [[]]
        size = 0
        for seg in segments:
            seg_size = len(seg.get("text", "")) + 1
            if size + seg_size > SNAPSHOT_CHUNK_SIZE and chunks[-1]:
                chunks.append([])
                size = 0
            chunks[-1].append(seg)
            size += seg_size
        tree = SummaryTree()
        tree.add_blob("header", json.dumps({
            "seq": snap["seq"],
            "minSeq": snap["minSeq"],
            "chunkCount": len(chunks),
        }))
        for i, chunk in enumerate(chunks):
            tree.add_blob(f"body_{i}", json.dumps(chunk))
        return tree

    def load_core(self, tree: SummaryTree) -> None:
        header = json.loads(tree.entries["header"].content)
        segments: List[dict] = []
        for i in range(header["chunkCount"]):
            segments.extend(json.loads(tree.entries[f"body_{i}"].content))
        self.client = MergeTreeClient.load(
            {"segments": segments, "seq": header["seq"],
             "minSeq": header["minSeq"]},
            client_id=self.local_client_id)
        self.client.on("delta", lambda args, local:
                       self.emit("sequenceDelta", args, local))


class SharedString(SharedSegmentSequence):
    """Reference sharedString.ts:36 API: collaborative rich text."""

    TYPE = "https://graph.microsoft.com/types/mergeTree/string"

    def insert_text(self, pos: int, text: str,
                    props: Optional[dict] = None) -> None:
        self.submit_local_message(
            self.client.insert_text_local(pos, text, props))

    def insert_marker(self, pos: int, props: Optional[dict] = None) -> None:
        self.submit_local_message(self.client.insert_marker_local(pos, props))

    def remove_text(self, start: int, end: int) -> None:
        self.submit_local_message(self.client.remove_range_local(start, end))

    def annotate_range(self, start: int, end: int, props: dict) -> None:
        self.submit_local_message(
            self.client.annotate_range_local(start, end, props))

    def replace_text(self, start: int, end: int, text: str,
                     props: Optional[dict] = None) -> None:
        # Insert-then-remove in one turn (reference groupOperation shape).
        self.insert_text(end, text, props)
        self.remove_text(start, end)

    def get_text(self) -> str:
        return self.client.get_text()
