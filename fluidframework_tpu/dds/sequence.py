"""SharedSegmentSequence + SharedString over the merge-tree client.

Capability parity with reference packages/dds/sequence/src/{sequence.ts:51,
sharedString.ts:36}: text insert/remove/annotate, markers, position queries,
delta events, snapshot (header + chunked body, snapshotV1.ts:33-40), and
reconnect resubmission delegated to the merge-tree client's pending-op
rewrite (client.ts:863).
"""

from __future__ import annotations

import itertools
import json
from typing import Any, Dict, Iterator, List, Optional

from ..core.events import TypedEventEmitter
from ..mergetree.client import MergeTreeClient
from ..mergetree.constants import SEG_MARKER, SNAPSHOT_CHUNK_SIZE
from ..mergetree.costmodel import device_bulk_wins
from ..mergetree.oracle import REF_SLIDE_ON_REMOVE, LocalReference
from ..protocol.summary import SummaryTree
from .shared_object import SharedObject

_interval_uid = itertools.count(1)


class SequenceInterval:
    """An [start, end] position pair anchored by local references
    (reference sequence/src/intervalCollection.ts SequenceInterval)."""

    def __init__(self, interval_id: str, start_ref: LocalReference,
                 end_ref: LocalReference,
                 properties: Optional[dict] = None):
        self.interval_id = interval_id
        self.start_ref = start_ref
        self.end_ref = end_ref
        self.properties = dict(properties or {})


class IntervalCollection(TypedEventEmitter):
    """A labeled set of intervals over one sequence, kept consistent via
    interval ops on the sequence's op stream (reference
    intervalCollection.ts:264-274; events addInterval/deleteInterval/
    changeInterval). Queries resolve through the live local references, so
    interval positions track concurrent edits."""

    def __init__(self, label: str, sequence: "SharedSegmentSequence"):
        super().__init__()
        self.label = label
        self.sequence = sequence
        self.intervals: Dict[str, SequenceInterval] = {}
        # Pending-local shadows (reference intervalCollection pendingChange
        # tracking): a remote change on an interval with an in-flight local
        # change is ignored — the sequencer orders the local one later, so
        # every replica converges on it. Counters survive interval deletion
        # (late acks must still retire them).
        self._pending_changes: Dict[str, int] = {}
        self._pending_prop_keys: Dict[str, Dict[str, int]] = {}

    # -- local mutations ---------------------------------------------------
    def add(self, start: int, end: int,
            properties: Optional[dict] = None) -> SequenceInterval:
        iid = f"iv-{self.sequence.local_client_id}-{next(_interval_uid)}"
        interval = self._attach(iid, start, end, properties)
        self.sequence._submit_interval_op(self.label, {
            "opName": "add", "intervalId": iid, "start": start, "end": end,
            "properties": dict(properties or {})})
        self.emit("addInterval", interval, True)
        return interval

    def remove_interval_by_id(self, interval_id: str) -> None:
        interval = self.intervals.pop(interval_id, None)
        if interval is None:
            return
        self._detach(interval)
        self.sequence._submit_interval_op(self.label, {
            "opName": "delete", "intervalId": interval_id})
        self.emit("deleteInterval", interval, True)

    def change(self, interval_id: str, start: int, end: int) -> None:
        interval = self.intervals.get(interval_id)
        if interval is None:
            return
        self._reanchor(interval, start, end)
        if self.sequence.attached:
            # Detached submits are dropped (state ships in the attach
            # summary) — no ack will ever retire a counter taken here.
            self._pending_changes[interval_id] = \
                self._pending_changes.get(interval_id, 0) + 1
        self.sequence._submit_interval_op(self.label, {
            "opName": "change", "intervalId": interval_id,
            "start": start, "end": end})
        self.emit("changeInterval", interval, True)

    def change_properties(self, interval_id: str, props: dict) -> None:
        interval = self.intervals.get(interval_id)
        if interval is None:
            return
        interval.properties.update(props)
        if self.sequence.attached:
            pending = self._pending_prop_keys.setdefault(interval_id, {})
            for key in props:
                pending[key] = pending.get(key, 0) + 1
        self.sequence._submit_interval_op(self.label, {
            "opName": "changeProperties", "intervalId": interval_id,
            "properties": props})
        self.emit("changeInterval", interval, True)

    # -- queries -----------------------------------------------------------
    def get_interval_by_id(self, interval_id: str
                           ) -> Optional[SequenceInterval]:
        return self.intervals.get(interval_id)

    def endpoints(self, interval: SequenceInterval) -> tuple:
        tree = self.sequence.client.tree
        return (tree.local_reference_position(interval.start_ref),
                tree.local_reference_position(interval.end_ref))

    def find_overlapping_intervals(self, start: int, end: int
                                   ) -> List[SequenceInterval]:
        out = []
        for interval in self.intervals.values():
            s, e = self.endpoints(interval)
            if not (e < start or s > end):
                out.append(interval)
        out.sort(key=lambda iv: self.endpoints(iv))
        return out

    def __iter__(self) -> Iterator[SequenceInterval]:
        return iter(sorted(self.intervals.values(),
                           key=lambda iv: self.endpoints(iv)))

    def __len__(self) -> int:
        return len(self.intervals)

    # -- op application ----------------------------------------------------
    def _process(self, op: dict, local: bool, ref_seq: int,
                 client_ordinal: int) -> None:
        name = op["opName"]
        iid = op["intervalId"]
        if local:
            # Ack: state applied at submit; retire the pending shadow.
            if name == "change":
                n = self._pending_changes.get(iid, 0)
                if n > 1:
                    self._pending_changes[iid] = n - 1
                else:
                    self._pending_changes.pop(iid, None)
            elif name == "changeProperties":
                pending = self._pending_prop_keys.get(iid)
                if pending:
                    for key in op.get("properties", {}):
                        n = pending.get(key, 0)
                        if n > 1:
                            pending[key] = n - 1
                        else:
                            pending.pop(key, None)
                    if not pending:
                        self._pending_prop_keys.pop(iid, None)
            return
        if name == "add":
            interval = self._attach(iid, op["start"], op["end"],
                                    op.get("properties"),
                                    ref_seq=ref_seq, client=client_ordinal)
            self.emit("addInterval", interval, False)
        elif name == "delete":
            interval = self.intervals.pop(iid, None)
            if interval is not None:
                self._detach(interval)
                self.emit("deleteInterval", interval, False)
        elif name == "change":
            interval = self.intervals.get(iid)
            if interval is not None and \
                    not self._pending_changes.get(iid):
                self._reanchor(interval, op["start"], op["end"],
                               ref_seq=ref_seq, client=client_ordinal)
                self.emit("changeInterval", interval, False)
        elif name == "changeProperties":
            interval = self.intervals.get(iid)
            if interval is not None:
                pending = self._pending_prop_keys.get(iid, {})
                applied = {k: v for k, v in op["properties"].items()
                           if not pending.get(k)}
                if applied:
                    interval.properties.update(applied)
                    self.emit("changeInterval", interval, False)

    # -- internals ---------------------------------------------------------
    def _attach(self, iid: str, start: int, end: int,
                properties: Optional[dict],
                ref_seq: Optional[int] = None,
                client: Optional[int] = None) -> SequenceInterval:
        tree = self.sequence.client.tree
        interval = SequenceInterval(
            iid,
            tree.create_local_reference(start, REF_SLIDE_ON_REMOVE,
                                        ref_seq=ref_seq, client=client),
            tree.create_local_reference(end, REF_SLIDE_ON_REMOVE,
                                        ref_seq=ref_seq, client=client),
            properties)
        self.intervals[iid] = interval
        return interval

    def _detach(self, interval: SequenceInterval) -> None:
        tree = self.sequence.client.tree
        tree.remove_local_reference(interval.start_ref)
        tree.remove_local_reference(interval.end_ref)

    def _reanchor(self, interval: SequenceInterval, start: int, end: int,
                  ref_seq: Optional[int] = None,
                  client: Optional[int] = None) -> None:
        tree = self.sequence.client.tree
        self._detach(interval)
        interval.start_ref = tree.create_local_reference(
            start, REF_SLIDE_ON_REMOVE, ref_seq=ref_seq, client=client)
        interval.end_ref = tree.create_local_reference(
            end, REF_SLIDE_ON_REMOVE, ref_seq=ref_seq, client=client)


class _ShapeCheckBuilder:
    """Payload-free OpBuilder stand-in: wire_to_host_ops drives it for
    PREVALIDATION only — every shape branch still runs (so Unmodelable
    raises exactly as in the real conversion) but nothing is built or
    retained."""

    def _noop(self, *args, **kwargs):
        return None

    insert_text = insert_marker = remove = annotate = _noop


class SharedSegmentSequence(SharedObject):
    TYPE = "https://graph.microsoft.com/types/mergeTree"

    def __init__(self, object_id: str, runtime=None):
        super().__init__(object_id, runtime)
        # Lazy snapshot load (reference sequence.ts:489,664): when set,
        # body chunks have NOT been parsed — (tree, header) pending.
        self._lazy = None
        self._lazy_len = 0
        self._lazy_ordinal: Optional[int] = None
        self._deferred_remote: List[tuple] = []
        self.client = MergeTreeClient(client_id=self.local_client_id)
        self.client.on("delta", lambda args, local:
                       self.emit("sequenceDelta", args, local))
        self._interval_collections: Dict[str, IntervalCollection] = {}
        self.bulk_catchup_count = 0  # device bulk applies (telemetry/tests)
        # In-flight interval ops by uid (resubmitted verbatim on reconnect;
        # interval ops carry ids, not positions needing rewrite).
        self._pending_interval_ops: Dict[int, dict] = {}
        self._interval_op_uid = itertools.count(1)

    def bind_to_runtime(self, runtime) -> None:
        super().bind_to_runtime(runtime)
        # Adopt the runtime's client ordinal (retags pending segments too).
        self.client.update_client_id(runtime.client_ordinal)

    # -- lazy body ---------------------------------------------------------
    @property
    def client(self) -> MergeTreeClient:
        """Anything touching merge-tree state materializes a pending lazy
        body first; header-only queries (get_length) never come here."""
        if self._lazy is not None:
            self._materialize_body()
        return self._client

    @client.setter
    def client(self, value: MergeTreeClient) -> None:
        self._client = value

    def _materialize_body(self) -> None:
        tree, header = self._lazy
        self._lazy = None
        segments: List[dict] = []
        for i in range(header["chunkCount"]):
            segments.extend(json.loads(tree.entries[f"body_{i}"].content))
        segments = self._decode_snapshot_segments(segments)
        self._client = MergeTreeClient.load(
            {"segments": segments, "seq": header["seq"],
             "minSeq": header["minSeq"]},
            client_id=self.local_client_id)
        self._client.on("delta", lambda args, local:
                        self.emit("sequenceDelta", args, local))
        if self._lazy_ordinal is not None:
            self._client.update_client_id(self._lazy_ordinal)
            self._lazy_ordinal = None
        if "intervals" in tree.entries:
            payload = json.loads(tree.entries["intervals"].content)
            for label, entries in payload.items():
                coll = self.get_interval_collection(label)
                for entry in entries:
                    coll._attach(entry["intervalId"], entry["start"],
                                 entry["end"], entry.get("properties"))
        # Ops deferred while the body was pending replay in order.
        deferred, self._deferred_remote = self._deferred_remote, []
        for contents, seq, ref_seq, ordinal, min_seq in deferred:
            self._client.apply_msg(contents, seq, ref_seq, ordinal,
                                   min_seq=min_seq)

    @staticmethod
    def _op_contains_remove(contents) -> bool:
        if not isinstance(contents, dict):
            return True  # unknown shape: treat as removing (conservative)
        t = contents.get("type")
        if t == 1:
            return True
        if t == 3:
            return any(SharedSegmentSequence._op_contains_remove(sub)
                       for sub in contents.get("ops", []))
        return t not in (0, 2)

    def _op_len_delta(self, contents, ref_seq=None,
                      ordinal=None) -> Optional[int]:
        """Visible-length delta of a wire op, computable WITHOUT the body
        (None = shape unknown: materialize instead of deferring).

        Removes defer only when provably whole: if the remover saw the
        snapshot and every other client's deferred remove
        (ref_seq >= their seqs), its visible range [pos1, pos2) is
        entirely live text — concurrent unseen inserts land inside the
        range but survive a merge-tree remove — so the length shrinks by
        exactly pos2-pos1. Otherwise the range may overlap an
        already-removed span (which only the body knows), so materialize."""
        if not isinstance(contents, dict):
            return None
        t = contents.get("type")
        if t == 0:  # insert
            seg = contents.get("seg") or {}
            if seg.get("marker"):
                return 1
            if isinstance(seg.get("text"), str):
                return len(seg["text"])
            if isinstance(seg.get("items"), list):
                return len(seg["items"])
            return None
        if t == 1:  # remove
            if ref_seq is None or self._lazy is None:
                return None
            if ref_seq < int(self._lazy[1].get("seq", 0)):
                return None  # may overlap removes baked into the snapshot
            # Deferrals append in ascending seq order: walk the unseen
            # suffix only (seq > ref_seq) so absorbing a long catch-up
            # tail stays O(tail x window), not O(tail^2).
            for _c, s2, _r, o2, _m in reversed(self._deferred_remote):
                if s2 <= ref_seq:
                    break
                if o2 != ordinal and self._op_contains_remove(_c):
                    return None  # unseen concurrent remove: overlap unknown
            p1, p2 = contents.get("pos1"), contents.get("pos2")
            if not isinstance(p1, int) or not isinstance(p2, int) or \
                    p2 < p1 or isinstance(p1, bool) or isinstance(p2, bool):
                return None
            return p1 - p2
        if t == 2:  # annotate
            return 0
        if t == 3:  # group
            total = 0
            for sub in contents.get("ops", []):
                d = self._op_len_delta(sub, ref_seq, ordinal)
                if d is None:
                    return None
                total += d
            return total
        return None

    # -- queries -----------------------------------------------------------
    def get_length(self) -> int:
        if self._lazy is not None:
            # Header-only: totalLength adjusted by deferred remote ops.
            return self._lazy_len
        return self._client.get_length()

    # -- lifecycle ---------------------------------------------------------
    def adopt_client_ordinal(self, ordinal: int) -> None:
        if self._lazy is not None:
            self._lazy_ordinal = ordinal  # applied at materialization
            return
        self.client.update_client_id(ordinal)

    # -- read-path catch-up adoption (docs/read_path.md) -------------------
    def can_adopt_catchup(self) -> bool:
        """Whether this channel's state may be REPLACED wholesale by a
        server catch-up artifact: nothing local may be live — no pending
        (unacked) edits, no interval collections (their anchors are live
        local references that do not survive a state swap), no in-flight
        interval ops. A lazy, untouched body trivially qualifies."""
        if self._interval_collections or self._pending_interval_ops:
            return False
        if self._lazy is not None:
            return True  # fresh from snapshot: no local state can exist
        tree = self._client.tree
        return not tree.pending_groups \
            and not any(seg.local_refs for seg in tree.segments)

    def adopt_catchup_core(self, entries: List[dict], seq: int,
                           min_seq: int, total_length: int) -> None:
        """Swap in server-materialized snapshot entries at `seq` — the
        delta half of `summary + delta` catch-up. The swap re-enters the
        ordinary lazy-load path (a synthetic header + one body chunk in
        the summarize_core wire format), so payload decoding, ordinal
        adoption, and the delta-event wiring are EXACTLY the fresh-load
        code — no second deserialization path to keep conformant. Any
        remote ops deferred against the previous lazy body are covered
        by the artifact (their seqs are <= `seq`) and drop."""
        if not self.can_adopt_catchup():
            raise ValueError("channel has live local state")
        if self._lazy is None:
            # Preserve the materialized body's ordinal adoption across
            # the swap (bind_to_runtime/adopt_client_ordinal already ran).
            ordinal = self._client.client_id
            if ordinal is not None and ordinal >= 0:
                self._lazy_ordinal = ordinal
        tree = SummaryTree()
        tree.add_blob("header", json.dumps({
            "seq": seq, "minSeq": min_seq, "chunkCount": 1,
            "totalLength": total_length}))
        tree.add_blob("body_0", json.dumps(entries))
        self._client = None
        self._deferred_remote = []
        self._lazy = (tree, json.loads(tree.entries["header"].content))
        self._lazy_len = int(total_length)
        self.change_epoch += 1  # adopted state is NOT durably summarized

    def connect(self) -> None:
        # A lazily-loaded channel is fresh from a snapshot: it cannot have
        # detached edits, so the pending-groups probe must not defeat the
        # lazy body by touching merge-tree state.
        if self._lazy is None and not self.attached and \
                self._client.tree.pending_groups:
            # Detached edits fold into the attach summary, not ops.
            self._client.commit_detached()
        super().connect()

    # -- local references (client.ts createLocalReferencePosition) --------
    def create_local_reference_position(
            self, pos: int, ref_type: int = REF_SLIDE_ON_REMOVE,
            properties: Optional[dict] = None) -> LocalReference:
        return self.client.tree.create_local_reference(pos, ref_type,
                                                       properties)

    def local_reference_to_position(self, ref: LocalReference) -> int:
        return self.client.tree.local_reference_position(ref)

    def remove_local_reference_position(self, ref: LocalReference) -> None:
        self.client.tree.remove_local_reference(ref)

    # -- interval collections ---------------------------------------------
    def get_interval_collection(self, label: str) -> IntervalCollection:
        if label not in self._interval_collections:
            self._interval_collections[label] = IntervalCollection(label,
                                                                   self)
        return self._interval_collections[label]

    def _submit_interval_op(self, label: str, op: dict) -> None:
        uid = next(self._interval_op_uid)
        contents = {"type": "intervalCollection", "label": label,
                    "uid": uid, "op": op}
        self._pending_interval_ops[uid] = contents
        self.submit_local_message(contents)

    # -- channel plumbing --------------------------------------------------
    def process_core(self, contents, local, seq, ref_seq, client_ordinal,
                     min_seq) -> None:
        if self._lazy is not None and not local:
            # Body still pending: queue remote ops whose length effect is
            # computable from the wire shape (reference: incoming ops are
            # deferred until the needed body chunk arrives,
            # sequence.ts:664); anything else materializes first.
            delta = self._op_len_delta(contents, ref_seq, client_ordinal)
            if delta is not None:
                self._deferred_remote.append(
                    (contents, seq, ref_seq, client_ordinal, min_seq))
                self._lazy_len += delta
                self.change_epoch += 1  # deferred != unchanged
                return
        if isinstance(contents, dict) and \
                contents.get("type") == "intervalCollection":
            if local:
                self._pending_interval_ops.pop(contents.get("uid"), None)
            self.get_interval_collection(contents["label"])._process(
                contents["op"], local, ref_seq, client_ordinal)
            self.client.tree.update_seq(seq)
            if min_seq is not None and min_seq > self.client.tree.min_seq:
                self.client.tree.set_min_seq(min_seq)
            return
        self.client.apply_msg(contents, seq, ref_seq, client_ordinal,
                              min_seq=min_seq)

    def process_bulk_core(self, batch) -> None:
        """Device bulk catch-up: apply a run of remote sequenced ops
        [(contents, seq, ref_seq, client_ordinal, min_seq)] through the
        merge-tree kernel (mergetree/catchup.py; reference
        deltaManager.ts:1380-1401 catch-up, vectorized).

        Interval ops never touch segment state, so the batch SPLITS at
        them: merge runs ride the kernel, interval ops apply host-side
        between runs at their own (ref_seq, client) perspectives — the
        reference's shape-agnostic catch-up without giving up the device
        path for the whole tail. Merge runs executed while live local
        references exist (interval anchors created earlier in this very
        batch, or pre-existing) go scalar per-op instead: references
        slide per-op and do not survive the kernel round trip.

        Raises Unmodelable/ValueError — with channel state UNTOUCHED —
        only from prevalidation (own sequenced merge ops, unmodelable
        shapes); once application starts, a surprise kernel refusal
        finishes the remaining runs scalar rather than raising."""
        from ..mergetree.catchup import Unmodelable, wire_to_host_ops

        def is_interval(contents) -> bool:
            return isinstance(contents, dict) and \
                contents.get("type") == "intervalCollection"

        if self._lazy is not None:
            # Lazy body pending: absorb the run as deferrals so the doc
            # STAYS lazy through catch-up (touching self.client below
            # would materialize just to probe preconditions; a fresh
            # snapshot load has no local refs or pendings, so those
            # probes are vacuous while lazy). All-or-nothing: on any
            # non-deferrable op (incl. interval ops, which need live
            # anchors) the tentative deferrals roll back, the body
            # materializes, and the run-splitting path below takes over.
            mark = len(self._deferred_remote)
            len0, ok = self._lazy_len, True
            for contents, seq, ref_seq, ordinal, min_seq in batch:
                if is_interval(contents):
                    ok = False
                    break
                d = self._op_len_delta(contents, ref_seq, ordinal)
                if d is None:
                    ok = False
                    break
                self._deferred_remote.append(
                    (contents, seq, ref_seq, ordinal, min_seq))
                self._lazy_len += d
            if ok:
                self.change_epoch += 1
                self.bulk_catchup_count += 1  # whole run absorbed lazily
                return
            del self._deferred_remote[mark:]
            self._lazy_len = len0

        # --- split into alternating merge runs / interval ops ------------
        runs: List[tuple] = []
        for item in batch:
            if is_interval(item[0]):
                runs.append(("interval", item))
            else:
                if not runs or runs[-1][0] != "merge":
                    runs.append(("merge", []))
                runs[-1][1].append(item)

        # --- prevalidation (the all-or-nothing contract) ------------------
        my_ordinal = self.client.client_id
        shape_check = _ShapeCheckBuilder()
        for kind, data in runs:
            if kind != "merge":
                continue
            for contents, seq, ref_seq, ordinal, min_seq in data:
                if ordinal == my_ordinal:
                    raise Unmodelable(
                        "own sequenced ops in tail need ack pairing")
                # Payload-free shape check — raises Unmodelable on
                # content the kernel cannot represent, BEFORE any state
                # changes (the real conversion happens once, inside
                # apply_bulk).
                wire_to_host_ops(shape_check, contents, seq, ref_seq,
                                 ordinal, min_seq or 0, allow_items=True)

        # --- apply --------------------------------------------------------
        # Past this point nothing may raise Unmodelable/ValueError: the
        # container's scalar fallback assumes channel state is untouched,
        # and earlier runs HAVE applied — an escaping error would
        # double-apply the batch. Unexpected errors surface as
        # RuntimeError, which the fallback does not catch.
        kernel_used = False
        try:
            for kind, data in runs:
                if kind == "interval":
                    contents, seq, ref_seq, ordinal, min_seq = data
                    local = ordinal == my_ordinal
                    if local:
                        self._pending_interval_ops.pop(
                            contents.get("uid"), None)
                    self.get_interval_collection(
                        contents["label"])._process(
                        contents["op"], local, ref_seq, ordinal)
                    self.client.tree.update_seq(seq)
                    if min_seq is not None and \
                            min_seq > self.client.tree.min_seq:
                        self.client.tree.set_min_seq(min_seq)
                    continue
                # Route per run: the device path must actually win for
                # this (backend, tail length, live segments) — the B=1
                # kernel loses to scalar on CPU and under the TPU
                # dispatch floor for short tails (mergetree/costmodel.py,
                # round-4 verdict's 4x single-doc pessimization).
                scalar = (any(seg.local_refs
                              for seg in self.client.tree.segments)
                          or not device_bulk_wins(
                              len(data),
                              len(self.client.tree.segments)))
                if not scalar:
                    try:
                        self.client.apply_bulk(data)
                        kernel_used = True
                        continue
                    except (Unmodelable, ValueError):
                        scalar = True  # rare late refusal (capacity
                        # ceiling): finish this run per-op
                for contents, seq, ref_seq, ordinal, min_seq in data:
                    self.client.apply_msg(contents, seq, ref_seq, ordinal,
                                          min_seq=min_seq)
        except (Unmodelable, ValueError) as err:
            raise RuntimeError(
                f"bulk catch-up failed mid-application: {err}") from err
        if kernel_used:
            self.bulk_catchup_count += 1

    def resubmit_pending(self) -> List[Any]:
        if self._lazy is not None:
            # Lazily loaded = fresh from snapshot: no merge-tree pendings
            # can exist, and the probe must not materialize the body.
            return list(self._pending_interval_ops.values())
        return (self.client.regenerate_pending_ops()
                + list(self._pending_interval_ops.values()))

    def summarize_core(self) -> SummaryTree:
        """Chunked snapshot: header with collab window + body chunks of
        bounded size (reference snapshotV1.ts chunking, chunkSize=10000)."""
        snap = self.client.snapshot()
        # Measured BEFORE encoding: _encode_snapshot_segments mutates
        # payloads in place (Items -> {"items": [...]}).
        total = sum(self._segment_visible_len(seg)
                    for seg in snap["segments"]
                    if seg.get("removedSeq") is None)
        segments = self._encode_snapshot_segments(snap["segments"])
        chunks: List[List[dict]] = [[]]
        size = 0
        for seg in segments:
            payload = seg.get("text", "")
            seg_size = (len(payload) if isinstance(payload, str)
                        else len(json.dumps(payload))) + 1
            if size + seg_size > SNAPSHOT_CHUNK_SIZE and chunks[-1]:
                chunks.append([])
                size = 0
            chunks[-1].append(seg)
            size += seg_size
        tree = SummaryTree()
        tree.add_blob("header", json.dumps({
            "seq": snap["seq"],
            "minSeq": snap["minSeq"],
            "chunkCount": len(chunks),
            # Enables header-only get_length on lazy load.
            "totalLength": total,
        }))
        for i, chunk in enumerate(chunks):
            tree.add_blob(f"body_{i}", json.dumps(chunk))
        if any(self._interval_collections.values()):
            # Interval positions serialize resolved at the snapshot
            # perspective (reference: intervalCollection valuetype snapshot).
            payload = {}
            for label, coll in self._interval_collections.items():
                payload[label] = [
                    {"intervalId": iv.interval_id,
                     "start": coll.endpoints(iv)[0],
                     "end": coll.endpoints(iv)[1],
                     "properties": iv.properties}
                    for iv in coll]
            tree.add_blob("intervals", json.dumps(payload))
        return tree

    def _segment_visible_len(self, seg: dict) -> int:
        """Visible-length contribution of a DECODED snapshot segment
        (header totalLength; item sequences count items, not payload
        encoding)."""
        if seg.get("kind") == SEG_MARKER:
            return 1
        return len(seg.get("text", ""))

    def _encode_snapshot_segments(self, segments: List[dict]) -> List[dict]:
        """Hook: make segment payloads JSON-safe (item sequences override)."""
        return segments

    def _decode_snapshot_segments(self, segments: List[dict]) -> List[dict]:
        return segments

    def load_core(self, tree: SummaryTree) -> None:
        header = json.loads(tree.entries["header"].content)
        if "totalLength" in header and "intervals" not in tree.entries:
            # Header-first lazy load: body chunks parse (and, with a lazy
            # storage tree, transfer) only when merge-tree state is first
            # touched; catch-up memory stays proportional to the header.
            # Interval-bearing snapshots load eagerly — interval anchors
            # resolve against live segments.
            self._lazy = (tree, header)
            self._lazy_len = int(header["totalLength"])
            return
        # Legacy snapshot (no totalLength): eager load.
        self._lazy = (tree, header)
        self._materialize_body()


class SharedItemsSequence(SharedSegmentSequence):
    """Sequence of JSON values over the merge-tree engine (reference
    sequence/src/sharedSequence.ts SharedSequence<T>: insert :64,
    remove :45, getItems :90 over SubSequence segments)."""

    def insert_range(self, pos: int, values, props: Optional[dict] = None
                     ) -> None:
        values = list(values)  # one-shot iterables are consumed repeatedly
        if not values:
            return
        self.submit_local_message(
            self.client.insert_items_local(pos, values, props))

    def remove_range(self, start: int, end: int) -> None:
        self.submit_local_message(self.client.remove_range_local(start, end))

    def annotate_range(self, start: int, end: int, props: dict) -> None:
        self.submit_local_message(
            self.client.annotate_range_local(start, end, props))

    def get_item_count(self) -> int:
        return self.get_length()

    def get_items(self, start: int = 0, end: Optional[int] = None) -> list:
        from ..mergetree.oracle import Items
        tree = self.client.tree
        out: list = []
        for seg in tree.segments:
            if tree.visible_length(seg, tree.current_seq,
                                   self.client.client_id) > 0:
                if isinstance(seg.text, Items):
                    out.extend(seg.text.values)
        return out[start:end]

    def _segment_visible_len(self, seg: dict) -> int:
        from ..mergetree.oracle import Items
        text = seg.get("text")
        if isinstance(text, Items):
            return len(text.values)
        if isinstance(text, dict) and "items" in text:
            return len(text["items"])
        return super()._segment_visible_len(seg)

    # Items payloads are not JSON until wrapped; ONE codec owns the
    # {"items": [...]} wire shape (mergetree/runs.py — shared with the
    # server lane extraction, so client and server snapshots can never
    # drift apart).
    def _encode_snapshot_segments(self, segments: List[dict]) -> List[dict]:
        from ..mergetree.runs import encode_entry_payloads
        return encode_entry_payloads(segments)

    def _decode_snapshot_segments(self, segments: List[dict]) -> List[dict]:
        from ..mergetree.runs import decode_entry_payloads
        return decode_entry_payloads(segments)


class SharedNumberSequence(SharedItemsSequence):
    """Reference sequence/src/sharedNumberSequence.ts: sequence of numbers."""

    TYPE = "https://graph.microsoft.com/types/mergeTree/numberSequence"


class SharedObjectSequence(SharedItemsSequence):
    """Reference sequence/src/sharedObjectSequence.ts: sequence of
    serializable values."""

    TYPE = "https://graph.microsoft.com/types/mergeTree/objectSequence"


class SharedString(SharedSegmentSequence):
    """Reference sharedString.ts:36 API: collaborative rich text."""

    TYPE = "https://graph.microsoft.com/types/mergeTree/string"

    def insert_text(self, pos: int, text: str,
                    props: Optional[dict] = None) -> None:
        self.submit_local_message(
            self.client.insert_text_local(pos, text, props))

    def insert_marker(self, pos: int, props: Optional[dict] = None) -> None:
        self.submit_local_message(self.client.insert_marker_local(pos, props))

    def remove_text(self, start: int, end: int) -> None:
        self.submit_local_message(self.client.remove_range_local(start, end))

    def annotate_range(self, start: int, end: int, props: dict) -> None:
        self.submit_local_message(
            self.client.annotate_range_local(start, end, props))

    def replace_text(self, start: int, end: int, text: str,
                     props: Optional[dict] = None) -> None:
        # Insert-then-remove in one turn (reference groupOperation shape).
        self.insert_text(end, text, props)
        self.remove_text(start, end)

    def get_text(self) -> str:
        return self.client.get_text()

    # -- marker queries (reference mergeTree getMarkerFromId /
    #    searchForMarker via tile labels) -----------------------------------
    def _visible_markers(self):
        """Yield (position, props) per visible marker, ascending."""
        from ..mergetree.constants import SEG_MARKER
        tree = self.client.tree
        acc = 0
        for seg in tree.segments:
            vlen = tree.visible_length(seg, tree.current_seq,
                                       self.client.client_id)
            if vlen == 0:
                continue
            if seg.kind == SEG_MARKER:
                yield acc, (seg.props or {})
            acc += vlen

    def get_marker_from_id(self, marker_id: str) -> Optional[tuple]:
        """(position, props) of the visible marker whose props carry
        {"markerId": marker_id} (reference reservedMarkerIdKey), or None."""
        for pos, props in self._visible_markers():
            if props.get("markerId") == marker_id:
                return pos, props
        return None

    def search_for_marker(self, start_pos: int, label: str,
                          forwards: bool = True) -> Optional[tuple]:
        """Nearest visible marker at/after (forwards) or at/before
        (backwards) start_pos whose {"tileLabels": [...]} props contain
        `label` (reference searchForMarker over tile labels). Returns
        (position, props) or None."""
        best = None
        for pos, props in self._visible_markers():
            if label not in (props.get("tileLabels") or []):
                continue
            if forwards:
                if pos >= start_pos:
                    return pos, props
            elif pos <= start_pos:
                best = (pos, props)  # keep scanning: last one wins
            else:
                break
        return best
