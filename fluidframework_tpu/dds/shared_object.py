"""SharedObject: the base class every DDS extends.

Capability parity with reference
packages/dds/shared-object-base/src/sharedObject.ts:28 — attach lifecycle,
summarize, op submit/process plumbing, GC data, handles — collapsed to the
surface a TPU-backed runtime needs. The channel boundary (IChannelFactory,
datastore-definitions/src/channel.ts:134) is preserved in *shape* so DDS
consumers are unchanged per the north star (BASELINE.json).
"""

from __future__ import annotations

from typing import Any, Dict, List, Optional, TYPE_CHECKING

from ..core.events import TypedEventEmitter
from ..protocol.summary import SummaryTree

if TYPE_CHECKING:
    from ..runtime.datastore_runtime import DataStoreRuntime


class FluidHandle:
    """An addressable reference to a shared object (reference FluidHandle).

    Serialized as {"type": "__fluid_handle__", "url": absolute_path}; the
    GC reference graph is built from handles encountered in summaries.
    """

    MARKER = "__fluid_handle__"

    def __init__(self, absolute_path: str, target: Any = None):
        self.absolute_path = absolute_path
        self._target = target

    def get(self) -> Any:
        return self._target

    def encode(self) -> dict:
        return {"type": self.MARKER, "url": self.absolute_path}

    @staticmethod
    def is_handle(value: Any) -> bool:
        return isinstance(value, dict) and value.get("type") == FluidHandle.MARKER


class SharedObject(TypedEventEmitter):
    """Base DDS. Subclasses implement process_core / summarize_core /
    load_core / resubmit_pending (+ their public mutation API).

    Lifecycle: created detached -> bind_to_runtime -> (container attach)
    connected. While detached, submits are dropped; state only ships via the
    attach summary (reference sharedObject.ts:156 load, :195 connect).
    """

    # Subclasses set: TYPE (channel factory type name).
    TYPE = "https://graph.microsoft.com/types/base"

    def __init__(self, object_id: str, runtime: Optional["DataStoreRuntime"] = None):
        super().__init__()
        self.id = object_id
        self.runtime = runtime
        self.attached = False
        self._handle: Optional[FluidHandle] = None
        # Bumped on every state change; incremental summaries emit a handle
        # to the previous summary's subtree when the epoch matches the last
        # ACKED summary (reference SummaryTracker / ISummarizeInternal
        # trackState, sharedObject.ts:210).
        self.change_epoch = 0

    # -- identity ----------------------------------------------------------
    @property
    def handle(self) -> FluidHandle:
        if self._handle is None:
            path = self.id
            if self.runtime is not None:
                path = f"/{self.runtime.id}/{self.id}"
            self._handle = FluidHandle(path, self)
        return self._handle

    @property
    def local_client_id(self) -> int:
        return self.runtime.client_ordinal if self.runtime else -1

    # -- lifecycle ---------------------------------------------------------
    def bind_to_runtime(self, runtime: "DataStoreRuntime") -> None:
        self.runtime = runtime
        runtime.bind_channel(self)

    def connect(self) -> None:
        self.attached = True

    # -- op plumbing -------------------------------------------------------
    def submit_local_message(self, contents: Any) -> None:
        """Send a channel op into the runtime (no-op while detached —
        detached state ships via the attach summary instead)."""
        self.change_epoch += 1
        if self.attached and self.runtime is not None:
            self.runtime.submit_channel_op(self.id, contents)

    def process(self, contents: Any, local: bool, seq: int, ref_seq: int,
                client_ordinal: int, min_seq: int) -> None:
        self.change_epoch += 1  # any sequenced op dirties the channel
        self.process_core(contents, local, seq, ref_seq, client_ordinal,
                          min_seq)

    # -- overridables ------------------------------------------------------
    def process_core(self, contents: Any, local: bool, seq: int, ref_seq: int,
                     client_ordinal: int, min_seq: int) -> None:
        raise NotImplementedError

    def summarize_core(self) -> SummaryTree:
        raise NotImplementedError

    def load_core(self, tree: SummaryTree) -> None:
        raise NotImplementedError

    def resubmit_pending(self) -> List[Any]:
        """Return the channel op contents to resubmit after reconnect, in
        order; replaces every previously in-flight op of this channel
        (reference reSubmitCore, sharedObject.ts:376)."""
        return []

    def get_gc_data(self) -> List[str]:
        """Outbound routes (handle paths) referenced by this object
        (reference getGCData, sharedObject.ts:244)."""
        return []

    # -- summary helpers ---------------------------------------------------
    def summarize(self) -> SummaryTree:
        tree = self.summarize_core()
        tree.add_blob(".attributes", _attributes_blob(self.TYPE))
        return tree


def _attributes_blob(type_name: str) -> str:
    import json
    return json.dumps({"type": type_name, "snapshotFormatVersion": "0.1"})


def collect_handles(value: Any, out: List[str]) -> None:
    """Recursively gather handle routes from a value (the SummarySerializer
    role: handle-tracking serialization). Matches both live FluidHandle
    objects (as stored by local set()) and their serialized dict form (as
    loaded from a summary)."""
    if isinstance(value, FluidHandle):
        out.append(value.absolute_path)
    elif FluidHandle.is_handle(value):
        out.append(value["url"])
    elif isinstance(value, dict):
        for v in value.values():
            collect_handles(v, out)
    elif isinstance(value, (list, tuple)):
        for v in value:
            collect_handles(v, out)


def encode_handles(value: Any) -> Any:
    """Serialize live FluidHandle objects into their wire dict form. Op
    contents must be plain data: they cross process boundaries (pickled by
    the native broker, deep-copied by copier's raw-op persistence)."""
    if isinstance(value, FluidHandle):
        return value.encode()
    if isinstance(value, dict):
        return {k: encode_handles(v) for k, v in value.items()}
    if isinstance(value, (list, tuple)):
        return [encode_handles(v) for v in value]
    return value


def decode_handles(value: Any) -> Any:
    """Rehydrate serialized handle dicts into FluidHandle objects after a
    summary load (inverse of the encode in each DDS's to_blob)."""
    if FluidHandle.is_handle(value):
        return FluidHandle(value["url"])
    if isinstance(value, dict):
        return {k: decode_handles(v) for k, v in value.items()}
    if isinstance(value, list):
        return [decode_handles(v) for v in value]
    return value
