"""SparseMatrix: the legacy row-major matrix from the sequence package.

Capability parity with reference packages/dds/sequence SparseMatrix (legacy,
superseded by SharedMatrix exactly as here): a fixed ~2^31 virtual column
space with sparse rows; insertRows/removeRows shift row identity, setItems
writes runs of cells. Implemented as a facade over the SharedMatrix engine
(permutation-vector rows + sparse cell store) — the legacy API surface with
the modern conflict resolution underneath.
"""

from __future__ import annotations

from typing import Any, List

from .matrix import SharedMatrix

# The reference exposes a huge fixed column space (maxCols = 2^31); columns
# are never inserted/removed, only rows.
MAX_COLS = 1 << 31


class SparseMatrix(SharedMatrix):
    TYPE = "https://graph.microsoft.com/types/mergeTree/sparse-matrix"

    @property
    def num_rows(self) -> int:
        return self.row_count

    @property
    def num_cols(self) -> int:
        return MAX_COLS

    def _ensure_cols(self, through: int) -> None:
        """Columns materialize lazily as they are touched (the virtual
        2^31-wide space would never be allocated)."""
        if self.col_count <= through:
            self.insert_cols(self.col_count, through + 1 - self.col_count)

    def insert_rows(self, row: int, count: int) -> None:  # noqa: D102
        super().insert_rows(row, count)

    def remove_rows(self, row: int, count: int) -> None:  # noqa: D102
        super().remove_rows(row, count)

    def set_items(self, row: int, col: int, values: List[Any]) -> None:
        """Write a horizontal run of cells starting at (row, col)."""
        self._ensure_cols(col + len(values) - 1)
        for i, value in enumerate(values):
            self.set_cell(row, col + i, value)

    def get_item(self, row: int, col: int) -> Any:
        if col >= self.col_count:
            return None
        return self.get_cell(row, col)
