"""SharedMap: last-write-wins key-value DDS with pending-local shadowing.

Capability parity with reference packages/dds/map/src/{map.ts:103,
mapKernel.ts:139}: set/delete/clear ops; a remote op for a key with pending
local writes is ignored (the local value shadows it until ack,
mapKernel.ts:160,619); acks pair by pending message id. Values round-trip
through the handle-aware serializer (handles stay addressable for GC).

The per-key state machine is intentionally tiny host-side code — the TPU
analog (batched LWW across thousands of maps) rides the same sequenced op
stream and is exercised by the server-side summarizer, not this class.
"""

from __future__ import annotations

import json
from typing import Any, Dict, Iterator, List, Optional, Tuple

from ..protocol.summary import SummaryTree
from .shared_object import (
    SharedObject,
    collect_handles,
    decode_handles,
    encode_handles,
)


def wait_for(emitter, event: str, check, timeout: Optional[float]):
    """Shared wait machinery for SharedMap.wait / SubDirectory.wait:
    `check()` returns (present, value). Check, subscribe, RE-check (the
    value may land on a reader thread between the first check and the
    listener registration), then block on a Deferred the listener
    resolves; the listener is always removed afterwards."""
    from ..core.events import Deferred
    present, value = check()
    if present:
        return value
    deferred = Deferred()

    def on_event(*args):
        p, v = check()
        if p:
            deferred.resolve(v)
    listener = emitter.on(event, on_event)
    try:
        present, value = check()
        if present:
            return value
        return deferred.result(timeout)
    finally:
        emitter.off(event, listener)


class _Missing:
    """Sentinel for 'key absent' in valueChanged previous-value payloads
    (distinguishes delete-on-undo from set-None-on-undo)."""

    def __repr__(self):
        return "<missing>"


MISSING = _Missing()


class MapKernel:
    """Op/state kernel shared by SharedMap and each Directory subdirectory.

    valueChanged events carry (key, local, previous) where previous is the
    pre-op value or MISSING — the undo-redo handlers revert from it."""

    def __init__(self, emit=None):
        self.data: Dict[str, Any] = {}
        # key -> list of pending local message ids (newest last)
        self.pending_keys: Dict[str, List[int]] = {}
        self.pending_clear_count = 0
        self.next_pending_id = 0
        self.emit = emit or (lambda *a: None)

    # -- local ops (return op contents + record pending) -------------------
    def set(self, key: str, value: Any) -> dict:
        previous = self.data.get(key, MISSING)
        self.data[key] = value
        pid = self._track(key)
        self.emit("valueChanged", key, True, previous)
        return {"type": "set", "key": key, "value": encode_handles(value),
                "pid": pid}

    def delete(self, key: str) -> Optional[dict]:
        existed = key in self.data
        previous = self.data.get(key, MISSING)
        self.data.pop(key, None)
        pid = self._track(key)
        if existed:
            self.emit("valueChanged", key, True, previous)
        return {"type": "delete", "key": key, "pid": pid}

    def clear(self) -> dict:
        self.data.clear()
        self.pending_clear_count += 1
        self.next_pending_id += 1
        self.emit("clear", True)
        return {"type": "clear", "pid": self.next_pending_id}

    def _track(self, key: str) -> int:
        self.next_pending_id += 1
        self.pending_keys.setdefault(key, []).append(self.next_pending_id)
        return self.next_pending_id

    # -- sequenced processing ---------------------------------------------
    def process(self, op: dict, local: bool) -> None:
        t = op["type"]
        if local:
            # Ack: retire the pending record; state already applied.
            if t == "clear":
                if self.pending_clear_count > 0:
                    self.pending_clear_count -= 1
                    return
            else:
                pending = self.pending_keys.get(op["key"])
                if pending and op.get("pid") in pending:
                    pending.remove(op["pid"])
                    if not pending:
                        del self.pending_keys[op["key"]]
                    return
            # No pending record: the optimistic local state was destroyed
            # out from under this op (the containing subdirectory was
            # deleted and recreated while it was in flight). Every other
            # replica applies the sequenced op — fall through and apply it
            # here too, or the submitter permanently diverges.
            local = False
        if t == "clear":
            # Remote clear wipes acked state; pending local keys survive
            # (their values re-assert on ack; mapKernel clear semantics).
            survivors = {k: self.data[k] for k in self.pending_keys
                         if k in self.data}
            self.data = survivors
            self.emit("clear", False)
            return
        key = op["key"]
        if key in self.pending_keys or self.pending_clear_count > 0:
            return  # shadowed by pending local write / pending local clear
        if t == "set":
            previous = self.data.get(key, MISSING)
            self.data[key] = decode_handles(op["value"])
            self.emit("valueChanged", key, False, previous)
        elif t == "delete":
            if key in self.data:
                previous = self.data[key]
                del self.data[key]
                self.emit("valueChanged", key, False, previous)

    # -- resubmit (reconnect) ---------------------------------------------
    def pending_ops(self) -> List[dict]:
        ops: List[dict] = []
        for _ in range(self.pending_clear_count):
            ops.append({"type": "clear", "pid": 0})
        for key, pids in self.pending_keys.items():
            for pid in pids:
                if key in self.data:
                    ops.append({"type": "set", "key": key,
                                "value": encode_handles(self.data[key]),
                                "pid": pid})
                else:
                    ops.append({"type": "delete", "key": key, "pid": pid})
        return ops

    # -- snapshot ----------------------------------------------------------
    def to_blob(self) -> str:
        return json.dumps(self.data, sort_keys=True, default=_encode_value)

    def load_blob(self, blob: str) -> None:
        from .shared_object import decode_handles
        self.data = decode_handles(json.loads(blob))


def _encode_value(value: Any):
    from .shared_object import FluidHandle
    if isinstance(value, FluidHandle):
        return value.encode()
    raise TypeError(f"not serializable: {type(value)!r}")


class SharedMap(SharedObject):
    """Reference map/src/map.ts:103 API surface."""

    TYPE = "https://graph.microsoft.com/types/map"

    def __init__(self, object_id: str, runtime=None):
        super().__init__(object_id, runtime)
        self.kernel = MapKernel(self._emit_kernel)

    def _emit_kernel(self, event: str, *args) -> None:
        self.emit(event, *args)

    # -- public API --------------------------------------------------------
    def get(self, key: str, default: Any = None) -> Any:
        return self.kernel.data.get(key, default)

    def set(self, key: str, value: Any) -> "SharedMap":
        self.submit_local_message(self.kernel.set(key, value))
        return self

    def delete(self, key: str) -> None:
        self.submit_local_message(self.kernel.delete(key))

    def clear(self) -> None:
        self.submit_local_message(self.kernel.clear())

    def has(self, key: str) -> bool:
        return key in self.kernel.data

    def wait(self, key: str, timeout: Optional[float] = None) -> Any:
        """Block until `key` exists and return its value (reference
        ISharedMap.wait, map.ts). Returns immediately if present. Over
        in-process drivers a peer's set lands synchronously, so by the time
        the peer's call returns this resolves without blocking; over
        network drivers the resolver runs on the reader thread."""
        return wait_for(
            self, "valueChanged",
            lambda: (key in self.kernel.data, self.kernel.data.get(key)),
            timeout)

    def keys(self) -> Iterator[str]:
        return iter(list(self.kernel.data.keys()))

    def items(self) -> Iterator[Tuple[str, Any]]:
        return iter(list(self.kernel.data.items()))

    def entries(self) -> Iterator[Tuple[str, Any]]:
        """Alias of items() (reference map.ts:173 entries)."""
        return self.items()

    def values(self) -> Iterator[Any]:
        return iter(list(self.kernel.data.values()))

    def for_each(self, fn) -> None:
        """fn(value, key, map) per entry (reference map.ts:202 forEach)."""
        for k, v in list(self.kernel.data.items()):
            fn(v, k, self)

    @property
    def size(self) -> int:
        return len(self.kernel.data)

    def __len__(self) -> int:
        return len(self.kernel.data)

    # -- channel plumbing --------------------------------------------------
    def connect(self) -> None:
        if not self.attached:
            # Detached edits ship via the attach summary; forget pendings.
            self.kernel.pending_keys.clear()
            self.kernel.pending_clear_count = 0
        super().connect()

    def process_core(self, contents, local, seq, ref_seq, client_ordinal,
                     min_seq) -> None:
        self.kernel.process(contents, local)

    def resubmit_pending(self) -> List[Any]:
        return self.kernel.pending_ops()

    def summarize_core(self) -> SummaryTree:
        tree = SummaryTree()
        tree.add_blob("header", self.kernel.to_blob())
        return tree

    def load_core(self, tree: SummaryTree) -> None:
        self.kernel.load_blob(tree.entries["header"].content)

    def get_gc_data(self) -> List[str]:
        routes: List[str] = []
        collect_handles(self.kernel.data, routes)
        return routes
