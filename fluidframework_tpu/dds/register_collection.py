"""ConsensusRegisterCollection: versioned LWW registers with atomic reads.

Capability parity with reference packages/dds/register-collection/src/
consensusRegisterCollection.ts: a write takes effect only when sequenced; a
register keeps *all* concurrent versions (writes whose refSeq precedes the
currently-stored write) so readers can choose Atomic (first/winning version)
or LWW (latest) policy. Used by leader election (agent-scheduler).
"""

from __future__ import annotations

import json
from typing import Any, Callable, Dict, List, Optional

from ..protocol.summary import SummaryTree
from .shared_object import SharedObject, collect_handles

READ_ATOMIC = "atomic"
READ_LWW = "lww"


class ConsensusRegisterCollection(SharedObject):
    TYPE = "https://graph.microsoft.com/types/consensus-register-collection"

    def __init__(self, object_id: str, runtime=None):
        super().__init__(object_id, runtime)
        # key -> list of {"value": v, "seq": s} versions (concurrent writes)
        self.data: Dict[str, List[dict]] = {}
        # In-flight writes: (key, value, on_ack); resubmitted on reconnect.
        self._inflight: List[tuple] = []

    def write(self, key: str, value: Any,
              on_ack: Optional[Callable[[bool], None]] = None) -> None:
        """Consensus write: takes effect when sequenced. on_ack(winner)
        fires at ack with whether this write won (became a stored version)."""
        if not self.attached:
            # Detached: apply immediately as the sole version.
            self.data[key] = [{"value": value, "seq": 0}]
            if on_ack:
                on_ack(True)
            return
        self._inflight.append((key, value, on_ack or (lambda won: None)))
        self.submit_local_message({"type": "write", "key": key, "value": value})

    def read(self, key: str, policy: str = READ_ATOMIC) -> Any:
        versions = self.data.get(key)
        if not versions:
            return None
        return versions[0]["value"] if policy == READ_ATOMIC \
            else versions[-1]["value"]

    def read_versions(self, key: str) -> List[Any]:
        return [v["value"] for v in self.data.get(key, [])]

    def keys(self) -> List[str]:
        return list(self.data.keys())

    def process_core(self, contents, local, seq, ref_seq, client_ordinal,
                     min_seq) -> None:
        key, value = contents["key"], contents["value"]
        versions = self.data.setdefault(key, [])
        # A write that saw every stored version (refSeq >= their seqs)
        # supersedes them; otherwise it's concurrent and appends.
        won = True
        if versions and any(v["seq"] > ref_seq for v in versions):
            versions.append({"value": value, "seq": seq})
            won = False  # concurrent: did not supersede
        else:
            self.data[key] = [{"value": value, "seq": seq}]
        self.emit("atomicChanged" if won else "versionChanged", key, value,
                  local)
        if local and self._inflight:
            self._inflight.pop(0)[2](won)

    def resubmit_pending(self) -> List[Any]:
        # Writes lost to a reconnect are re-emitted; acks fire on the new op.
        return [{"type": "write", "key": k, "value": v}
                for k, v, _ in self._inflight]

    def summarize_core(self) -> SummaryTree:
        return SummaryTree().add_blob(
            "header", json.dumps(self.data, sort_keys=True))

    def load_core(self, tree: SummaryTree) -> None:
        self.data = json.loads(tree.entries["header"].content)

    def get_gc_data(self) -> List[str]:
        routes: List[str] = []
        collect_handles(self.data, routes)
        return routes
