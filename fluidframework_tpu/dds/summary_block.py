"""SharedSummaryBlock: summary-only data, no ops.

Capability parity with reference packages/dds/shared-summary-block: values
set locally are NEVER sent as ops — they persist exclusively through the
summary tree. Used for data that only the summarizer writes (e.g. search
indexes), avoiding op-stream traffic entirely.
"""

from __future__ import annotations

import json
from typing import Any, Dict, List

from ..protocol.summary import SummaryTree
from .shared_object import SharedObject


class SharedSummaryBlock(SharedObject):
    TYPE = "https://graph.microsoft.com/types/shared-summary-block"

    def __init__(self, object_id: str, runtime=None):
        super().__init__(object_id, runtime)
        self.data: Dict[str, Any] = {}

    def get(self, key: str, default: Any = None) -> Any:
        return self.data.get(key, default)

    def set(self, key: str, value: Any) -> Any:
        """Local-only write; becomes durable at the next summary. Values
        must be JSON-serializable (they go straight into the blob)."""
        json.dumps(value)  # fail fast on non-serializable input
        self.data[key] = value
        self.change_epoch += 1  # no ops flow: dirty explicitly
        return value

    def process_core(self, contents, local, seq, ref_seq, client_ordinal,
                     min_seq) -> None:
        raise RuntimeError(
            "SharedSummaryBlock does not process ops (summary-only DDS)")

    def resubmit_pending(self) -> List[Any]:
        return []

    def summarize_core(self) -> SummaryTree:
        return SummaryTree().add_blob(
            "header", json.dumps(self.data, sort_keys=True))

    def load_core(self, tree: SummaryTree) -> None:
        self.data = json.loads(tree.entries["header"].content)
