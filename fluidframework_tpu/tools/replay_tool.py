"""Replay tool: offline op replay + snapshot determinism validation.

Capability parity with reference packages/tools/replay-tool
(`replayMessages.ts`, 1064 LoC) and the snapshot-regression rig built on it
(packages/test/snapshots `replayMultipleFiles.ts`): load a captured
document (summary + op log), replay the ops through a real container,
generate summaries at a chosen frequency, and cross-validate determinism —
(a) two independent replays must produce byte-identical summaries at every
snapshot point, and (b) a container *loaded from* a generated mid-stream
summary and fed the remaining ops must agree with the straight-through
replay (the reference's storage-vs-incremental check).
"""

from __future__ import annotations

import json
from dataclasses import dataclass, field
from typing import List, Optional

from ..loader.container import Container
from ..loader.drivers.file import FileDocumentCapture
from ..loader.drivers.replay import ReplayController, ReplayDocumentService
from ..protocol.messages import SequencedDocumentMessage
from ..protocol.summary import SummaryTree, summary_tree_to_dict


def canonical_summary(summary: SummaryTree) -> str:
    """Byte-stable serialization for comparison (sorted keys)."""
    return json.dumps(summary_tree_to_dict(summary), sort_keys=True)


@dataclass
class ReplayArgs:
    """Knobs mirroring the reference's ReplayArgs (from/to/snapFreq/
    validate)."""

    from_seq: int = 0
    to_seq: Optional[int] = None
    snap_freq: Optional[int] = None   # snapshot every N ops; None = end only
    validate_storage: bool = True     # check (b): load-from-snapshot replay
    write_dir: Optional[str] = None   # persist generated snapshots


@dataclass
class SnapshotPoint:
    sequence_number: int
    summary: SummaryTree
    canonical: str


@dataclass
class ReplayResult:
    snapshots: List[SnapshotPoint] = field(default_factory=list)
    mismatches: List[str] = field(default_factory=list)
    final_seq: int = 0

    @property
    def deterministic(self) -> bool:
        return not self.mismatches


class ReplayTool:
    def __init__(self, summary: SummaryTree,
                 ops: List[SequencedDocumentMessage]):
        self.summary = summary
        self.ops = ops

    @staticmethod
    def from_capture(directory: str) -> "ReplayTool":
        capture = FileDocumentCapture(directory)
        summary = capture.read_summary()
        if summary is None:
            raise FileNotFoundError(f"no summary in {directory}")
        return ReplayTool(summary, capture.read_ops())

    # -- core replay -------------------------------------------------------
    def _open(self, replay_to: int) -> tuple:
        controller = ReplayController(replay_to=replay_to)
        service = ReplayDocumentService(self.summary, self.ops, controller)
        container = Container.load("replay", service)
        return container, controller

    def run(self, args: Optional[ReplayArgs] = None) -> ReplayResult:
        args = args or ReplayArgs()
        result = ReplayResult()
        last = self.ops[-1].sequence_number if self.ops else 0
        end = min(args.to_seq, last) if args.to_seq is not None else last

        # Snapshot points: every snap_freq ops, plus the end.
        points: List[int] = []
        if args.snap_freq:
            seq = args.from_seq + args.snap_freq
            while seq < end:
                points.append(seq)
                seq += args.snap_freq
        points.append(end)

        container, controller = self._open(replay_to=args.from_seq)
        shadow, shadow_ctl = self._open(replay_to=args.from_seq)
        for point in points:
            controller.forward(point)
            shadow_ctl.forward(point)
            summary = container._assemble_summary()
            canonical = canonical_summary(summary)
            result.snapshots.append(SnapshotPoint(point, summary, canonical))
            # (a) Replay-vs-replay determinism.
            if canonical_summary(shadow._assemble_summary()) != canonical:
                result.mismatches.append(
                    f"replay divergence at seq {point}")
            # (b) Storage check: load from this summary + op tail.
            if args.validate_storage:
                self._validate_from_snapshot(summary, point, end,
                                             result)
        result.final_seq = end
        if args.write_dir:
            for snap in result.snapshots:
                capture = FileDocumentCapture(
                    f"{args.write_dir}/snapshot_{snap.sequence_number}")
                capture.write_summary(snap.summary)
        return result

    def _validate_from_snapshot(self, summary: SummaryTree, at_seq: int,
                                end: int, result: ReplayResult) -> None:
        controller = ReplayController(replay_to=at_seq)
        tail = [m for m in self.ops if m.sequence_number > at_seq
                and m.sequence_number <= end]
        service = ReplayDocumentService(summary, tail, controller)
        try:
            container = Container.load("replay-check", service)
            controller.forward(end)
            reference_ctl: ReplayController
            straight, reference_ctl = self._open(replay_to=end)
            if (canonical_summary(container._assemble_summary())
                    != canonical_summary(straight._assemble_summary())):
                result.mismatches.append(
                    f"storage replay divergence from snapshot at {at_seq}")
        except Exception as exc:  # noqa: BLE001 — report, don't abort tool
            result.mismatches.append(
                f"storage replay failed from snapshot at {at_seq}: {exc!r}")
