"""Micro-perf harness: µs/op timings for the core engines.

Capability parity with reference merge-tree `wordUnitTests.ts:18-60` and
`beastTest.ts` (timed micro-loops over insert/remove/annotate/snapshot,
reported in µs/op) plus the internal perf counters surfaced by
`MergeTreeStats` (mergeTree.ts:185). Run:

    python -m fluidframework_tpu.tools.microbench [n_ops]

Prints one row per probe: name, ops, total ms, µs/op. The device-kernel
probe reports throughput on whatever backend is active (set
BENCH_PLATFORM=cpu to force the host backend)."""

from __future__ import annotations

import random
import time
from typing import Callable, Dict, List, Tuple

from ..mergetree.client import MergeTreeClient
from ..mergetree.constants import UNASSIGNED_SEQ
from ..mergetree.oracle import MergeTreeOracle


def _timed(fn: Callable[[], int]) -> Tuple[int, float]:
    start = time.perf_counter()
    n = fn()
    return n, time.perf_counter() - start


def probe_oracle_insert(n_ops: int) -> Tuple[int, float]:
    tree = MergeTreeOracle(local_client=0)
    rng = random.Random(0)

    def run():
        seq = 0
        for i in range(n_ops):
            seq += 1
            tree.insert_text(rng.randint(0, tree.get_length()), "word ",
                             seq - 1, 0, seq)
            tree.update_seq(seq)
        return n_ops

    return _timed(run)


def probe_oracle_remove(n_ops: int) -> Tuple[int, float]:
    tree = MergeTreeOracle(local_client=0)
    seq = 0
    for _ in range(n_ops):
        seq += 1
        tree.insert_text(0, "xxxx", seq - 1, 0, seq)
        tree.update_seq(seq)
    rng = random.Random(1)

    def run():
        nonlocal seq
        for _ in range(n_ops // 2):
            seq += 1
            length = tree.get_length()
            if length < 4:
                break
            start = rng.randint(0, length - 2)
            tree.remove_range(start, min(length, start + 2), seq - 1, 0, seq)
            tree.update_seq(seq)
        return n_ops // 2

    return _timed(run)


def probe_client_roundtrip(n_ops: int) -> Tuple[int, float]:
    """Local submit + ack (the interactive latency path)."""
    client = MergeTreeClient(client_id=0)
    rng = random.Random(2)

    def run():
        seq = 0
        for _ in range(n_ops):
            seq += 1
            client.insert_text_local(
                rng.randint(0, client.get_length()), "w")
            client.apply_msg({"type": 0, "pos1": 0,
                              "seg": {"text": "w"}}, seq, seq - 1, 0)
        return n_ops

    return _timed(run)


def probe_snapshot(n_segments: int) -> Tuple[int, float]:
    tree = MergeTreeOracle(local_client=0)
    seq = 0
    for _ in range(n_segments):
        seq += 1
        tree.insert_text(0, "seg", seq - 1, 1, seq)  # distinct clients block
        tree.update_seq(seq)

    def run():
        for _ in range(10):
            tree.snapshot_segments()
        return 10

    return _timed(run)


def probe_kernel_throughput(n_docs: int = 512, n_ops: int = 64
                            ) -> Tuple[int, float]:
    import jax
    import jax.numpy as jnp
    from bench import gen_traces
    from ..mergetree import kernel
    from ..mergetree.oppack import PackedOps
    from ..mergetree.state import make_state

    cols = gen_traces(n_docs, n_ops, seed=0)
    ops = PackedOps(**{f: jnp.asarray(cols[f]) for f in PackedOps._fields})
    state = make_state(128, 1, batch=n_docs)
    step = jax.jit(kernel.apply_ops_batched)
    jax.block_until_ready(step(state, ops))  # compile

    def run():
        out = step(state, ops)
        jax.block_until_ready(out)
        return n_docs * n_ops

    return _timed(run)


PROBES: Dict[str, Callable[[int], Tuple[int, float]]] = {
    "oracle.insert": probe_oracle_insert,
    "oracle.remove": probe_oracle_remove,
    "client.roundtrip": probe_client_roundtrip,
    "oracle.snapshot(10x)": probe_snapshot,
}


def run_all(n_ops: int = 2000, with_kernel: bool = True) -> List[dict]:
    rows = []
    for name, probe in PROBES.items():
        n, elapsed = probe(n_ops)
        rows.append({"probe": name, "ops": n,
                     "total_ms": round(elapsed * 1000, 2),
                     "us_per_op": round(elapsed / max(1, n) * 1e6, 2)})
    if with_kernel:
        n, elapsed = probe_kernel_throughput()
        rows.append({"probe": "kernel.apply_batched", "ops": n,
                     "total_ms": round(elapsed * 1000, 2),
                     "us_per_op": round(elapsed / max(1, n) * 1e6, 3)})
    return rows


def main() -> None:
    import sys
    n_ops = int(sys.argv[1]) if len(sys.argv) > 1 else 2000
    for row in run_all(n_ops):
        print(f"{row['probe']:24} {row['ops']:>8} ops  "
              f"{row['total_ms']:>9.2f} ms  {row['us_per_op']:>8.2f} µs/op")


if __name__ == "__main__":
    main()
