"""Fetch tool: download a document's snapshot + ops for offline debugging.

Capability parity with reference packages/tools/fetch-tool (1,844 LoC):
connect to any service through its driver factory, pull the latest summary
and the full (or ranged) op log, report statistics (op counts by type,
summary tree shape/sizes), and optionally write a FileDocumentCapture
directory that the replay tool / file driver can reload.
"""

from __future__ import annotations

import json
from dataclasses import dataclass, field
from typing import Dict, List, Optional

from ..loader.drivers.base import IDocumentServiceFactory
from ..loader.drivers.file import FileDocumentCapture
from ..protocol.messages import SequencedDocumentMessage
from ..protocol.summary import SummaryBlob, SummaryTree, summary_tree_to_dict


@dataclass
class FetchStats:
    document_id: str
    op_count: int = 0
    first_seq: int = 0
    last_seq: int = 0
    ops_by_type: Dict[str, int] = field(default_factory=dict)
    ops_by_client: Dict[str, int] = field(default_factory=dict)
    summary_blob_count: int = 0
    summary_bytes: int = 0
    summary_paths: List[str] = field(default_factory=list)

    def report(self) -> str:
        lines = [f"document {self.document_id}:",
                 f"  ops {self.first_seq}..{self.last_seq} "
                 f"({self.op_count} total)"]
        for mtype, n in sorted(self.ops_by_type.items()):
            lines.append(f"    {mtype}: {n}")
        lines.append(f"  summary: {self.summary_blob_count} blobs, "
                     f"{self.summary_bytes} bytes")
        return "\n".join(lines)


def _walk_summary(node, path: str, stats: FetchStats) -> None:
    if isinstance(node, SummaryBlob):
        stats.summary_blob_count += 1
        content = node.content
        stats.summary_bytes += len(content if isinstance(content, (bytes,
                                                                   bytearray))
                                   else str(content).encode())
        stats.summary_paths.append(path)
    elif isinstance(node, SummaryTree):
        for name, child in node.entries.items():
            _walk_summary(child, f"{path}/{name}", stats)


def fetch_document(factory: IDocumentServiceFactory, document_id: str,
                   out_dir: Optional[str] = None,
                   from_seq: int = 0, to_seq: Optional[int] = None
                   ) -> tuple:
    """Returns (summary, ops, FetchStats); writes a capture when out_dir is
    given."""
    service = factory.create_document_service(document_id)
    storage = service.connect_to_storage()
    summary = storage.get_summary()
    ops: List[SequencedDocumentMessage] = service.connect_to_delta_storage() \
        .get(from_seq, to_seq)

    stats = FetchStats(document_id)
    stats.op_count = len(ops)
    if ops:
        stats.first_seq = ops[0].sequence_number
        stats.last_seq = ops[-1].sequence_number
    for m in ops:
        stats.ops_by_type[m.type] = stats.ops_by_type.get(m.type, 0) + 1
        client = m.client_id or "<service>"
        stats.ops_by_client[client] = stats.ops_by_client.get(client, 0) + 1
    if summary is not None:
        _walk_summary(summary, "", stats)

    if out_dir is not None:
        capture = FileDocumentCapture(out_dir)
        if summary is not None:
            capture.write_summary(summary)
        capture.write_ops(ops)
        with open(f"{out_dir}/stats.json", "w") as f:
            json.dump({"opCount": stats.op_count,
                       "opsByType": stats.ops_by_type,
                       "summaryBlobs": stats.summary_blob_count,
                       "summaryBytes": stats.summary_bytes}, f, indent=1)
    return summary, ops, stats
