"""Offline tools (reference layer 8: packages/tools)."""

from .fetch_tool import FetchStats, fetch_document
from .mergetree_replay import MergeTreeReplayer
from .replay_tool import ReplayArgs, ReplayResult, ReplayTool
