"""Merge-tree client replay: re-run a merge-tree op log against test
clients.

Capability parity with reference packages/tools/merge-tree-client-replay
(494 LoC): given a recorded log of sequenced merge-tree ops, build one
replica per participating client plus a read-only observer, apply every op
from each replica's own perspective (its ops ack; others apply remote), and
assert all replicas converge — the offline debugging harness for merge-tree
divergence reports.

Log entry shape: {"op": <merge-tree wire op>, "seq": n, "refSeq": n,
"client": ordinal, "minSeq": n?} — the same fields a SequencedDocumentMessage
carries for a sequence-DDS op.
"""

from __future__ import annotations

from typing import Dict, List, Optional

from ..mergetree.client import MergeTreeClient


class MergeTreeReplayer:
    OBSERVER = -999  # never appears as a writer ordinal

    def __init__(self):
        self.clients: Dict[int, MergeTreeClient] = {}

    def _client(self, ordinal: int) -> MergeTreeClient:
        if ordinal not in self.clients:
            self.clients[ordinal] = MergeTreeClient(client_id=ordinal)
        return self.clients[ordinal]

    def replay(self, log: List[dict]) -> str:
        """Apply the full log; returns the converged text. Raises
        AssertionError with a divergence report if replicas disagree."""
        writers = sorted({e["client"] for e in log})
        for ordinal in writers + [self.OBSERVER]:
            self._client(ordinal)
        for entry in sorted(log, key=lambda e: e["seq"]):
            self.apply(entry)
        return self.assert_converged()

    def apply(self, entry: dict) -> None:
        op, seq = entry["op"], entry["seq"]
        ref_seq = entry.get("refSeq", seq - 1)
        origin = entry["client"]
        min_seq = entry.get("minSeq")
        for ordinal, client in self.clients.items():
            if ordinal == origin:
                # The originator must hold the pending local op; recreate it
                # at its recorded refSeq perspective, then ack.
                client.tree.current_seq = ref_seq
                self._apply_local(client, op)
                client.apply_msg(op, seq, ref_seq, origin, min_seq=min_seq)
                client.tree.current_seq = seq
            else:
                client.apply_msg(op, seq, ref_seq, origin, min_seq=min_seq)

    @staticmethod
    def _apply_local(client: MergeTreeClient, op: dict) -> None:
        from ..mergetree.client import OP_ANNOTATE, OP_INSERT, OP_REMOVE
        t = op["type"]
        if t == OP_INSERT:
            seg = op["seg"]
            if seg.get("marker"):
                client.insert_marker_local(op["pos1"], seg.get("props"))
            elif "items" in seg:
                client.insert_items_local(op["pos1"], seg["items"],
                                          seg.get("props"))
            else:
                client.insert_text_local(op["pos1"], seg["text"],
                                         seg.get("props"))
        elif t == OP_REMOVE:
            client.remove_range_local(op["pos1"], op["pos2"])
        elif t == OP_ANNOTATE:
            client.annotate_range_local(op["pos1"], op["pos2"], op["props"])

    def assert_converged(self) -> str:
        """All replicas must show identical text; returns it."""
        texts = {ordinal: client.get_text()
                 for ordinal, client in self.clients.items()}
        unique = set(texts.values())
        if len(unique) > 1:
            report = "\n".join(f"  client {o}: {t!r}"
                               for o, t in sorted(texts.items()))
            raise AssertionError(f"merge-tree divergence:\n{report}")
        return next(iter(unique))


def record_from_sequence_ops(messages: List[dict]) -> List[dict]:
    """Convert captured sequence-DDS channel ops (as found in a document op
    log) into replayer entries; non-merge-tree messages are skipped."""
    out = []
    for m in messages:
        contents = m.get("contents") or {}
        inner = (contents.get("contents") or {}).get("contents")
        if not isinstance(inner, dict) or "type" not in inner:
            continue
        out.append({"op": inner, "seq": m["sequenceNumber"],
                    "refSeq": m["referenceSequenceNumber"],
                    "client": m["clientOrdinal"],
                    "minSeq": m.get("minimumSequenceNumber")})
    return out
