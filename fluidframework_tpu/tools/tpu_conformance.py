"""On-chip kernel conformance: fused Pallas apply vs scan kernel,
bit-identity on the REAL device.

The test suite proves fused==scan under the Pallas interpreter on CPU
(tests/test_pallas_apply.py); this tool re-proves it on actual TPU
hardware, where Mosaic lowering — not the interpreter — executes the
kernel. Run before trusting a new chip/toolchain/jax version:

    python -m fluidframework_tpu.tools.tpu_conformance          # quick
    python -m fluidframework_tpu.tools.tpu_conformance --heavy  # +cap-1024

Exits nonzero on any mismatch. Timing uses jitted chained reps (eager
dispatch over a tunneled device pays a ~30-70 ms RPC floor per call and
produces phantom numbers — PERF.md measurement note)."""

from __future__ import annotations

import argparse
import random
import sys
import time


def _traces(b: int, t: int, seed: int, removes: bool = True):
    from fluidframework_tpu.mergetree.oppack import HostOp, OpKind

    rng = random.Random(seed)
    out = []
    for d in range(b):
        ops, length, seq = [], 0, 0
        for i in range(t):
            seq += 1
            if removes and length > 4 and rng.random() < 0.25:
                a = rng.randrange(length - 2)
                width = rng.randrange(1, 3)
                ops.append(HostOp(kind=OpKind.REMOVE, seq=seq,
                                  ref_seq=seq - 1, client=d % 3,
                                  pos1=a, pos2=a + width, op_id=i))
                length -= width
                continue
            n = rng.randrange(1, 4)
            ops.append(HostOp(kind=OpKind.INSERT, seq=seq, ref_seq=seq - 1,
                              client=d % 3, pos1=rng.randrange(length + 1),
                              op_id=i, new_len=n))
            length += n
        out.append(ops)
    return out


def check(b: int, t: int, cap: int, seed: int) -> bool:
    import jax
    import numpy as np

    from fluidframework_tpu.mergetree import kernel
    from fluidframework_tpu.mergetree.oppack import pack_ops
    from fluidframework_tpu.mergetree.pallas_apply import (
        apply_ops_fused_pallas, tile_for_capacity)
    from fluidframework_tpu.mergetree.state import make_state

    packed = jax.device_put(pack_ops(_traces(b, t, seed)))
    scan_j = jax.jit(lambda s, o: kernel.apply_ops_batched_keep(s, o))
    # fluidlint: disable=MISSING_DONATE — conformance re-runs both kernels
    # over the SAME inputs to diff outputs; donation would corrupt the ref.
    fused_j = jax.jit(apply_ops_fused_pallas)

    results = {}
    for name, fn in (("scan", scan_j), ("fused", fused_j)):
        st = jax.device_put(make_state(cap, 2, batch=b))
        out = fn(st, packed)
        jax.device_get(out.count)  # full completion
        t0 = time.perf_counter()
        chained = fn(jax.device_put(make_state(cap, 2, batch=b)), packed)
        for _ in range(2):
            chained = fn(chained._replace(overflow=out.overflow), packed)
        jax.device_get(chained.count)
        results[name] = (out, (time.perf_counter() - t0) / 3)

    ref, scan_dt = results["scan"]
    got, fused_dt = results["fused"]
    ok = True
    for f in ref._fields:
        a, c = np.asarray(jax.device_get(getattr(ref, f))), \
            np.asarray(jax.device_get(getattr(got, f)))
        if not (a == c).all():
            print(f"  MISMATCH in {f} (b={b} t={t} cap={cap} seed={seed})")
            ok = False
    tile = tile_for_capacity(cap)
    print(f"  b={b} t={t} cap={cap} tile={tile}: "
          f"{'OK' if ok else 'FAIL'}  scan {scan_dt*1e3:.1f}ms "
          f"fused {fused_dt*1e3:.1f}ms")
    return ok


def check_runs(b: int, t_ops: int, cap: int, seed: int) -> bool:
    """INSERT_RUN Mosaic conformance: pack typing-burst streams and
    compare the fused runs variant against the scan kernel WITH the same
    RunCols — the packed apply itself differential-checked on chip."""
    import jax
    import jax.numpy as jnp
    import numpy as np

    from fluidframework_tpu.mergetree import kernel
    from fluidframework_tpu.mergetree.oppack import (HostOp, OpKind,
                                                     RunCols,
                                                     pack_run_slots,
                                                     pack_slots)
    from fluidframework_tpu.mergetree.pallas_apply import (
        apply_ops_fused_pallas)
    from fluidframework_tpu.mergetree.state import make_state

    rng = random.Random(seed)
    docs = []
    for d in range(b):
        ops, length, seq = [], 0, 0
        while len(ops) < t_ops:
            if rng.random() < 0.7:  # typing burst, frozen ref
                ref = seq
                pos = rng.randrange(length + 1) if length else 0
                for _ in range(rng.randrange(3, 12)):
                    seq += 1
                    ops.append(HostOp(kind=OpKind.INSERT, seq=seq,
                                      ref_seq=ref, client=1, pos1=pos,
                                      op_id=len(ops), new_len=1))
                    pos += 1
                    length += 1
            elif length > 4:
                seq += 1
                a = rng.randrange(length - 2)
                ops.append(HostOp(kind=OpKind.REMOVE, seq=seq,
                                  ref_seq=seq - 1, client=1, pos1=a,
                                  pos2=a + 1, op_id=len(ops)))
                length -= 1
            else:
                seq += 1
                ops.append(HostOp(kind=OpKind.INSERT, seq=seq,
                                  ref_seq=seq - 1, client=1, pos1=0,
                                  op_id=len(ops), new_len=2))
                length += 2
        docs.append(pack_run_slots(ops[:t_ops], base_seq=0))
    t_slots = max(len(s) for s in docs)
    packed_l, runs_l = zip(*(pack_slots(s, steps=t_slots) for s in docs))
    packed = type(packed_l[0])(*[
        jnp.stack([getattr(p, f) for p in packed_l])
        for f in packed_l[0]._fields])
    runs = RunCols(*[jnp.stack([getattr(r, f) for r in runs_l])
                     for f in RunCols._fields])
    packed, runs = jax.device_put((packed, runs))

    out_scan = kernel._scan_ops(jax.device_put(make_state(cap, 2, batch=b)),
                                packed, batched=True, runs=runs)
    out_fused = apply_ops_fused_pallas(
        jax.device_put(make_state(cap, 2, batch=b)), packed, runs=runs)
    ok = True
    for f in out_scan._fields:
        a = np.asarray(jax.device_get(getattr(out_scan, f)))
        c = np.asarray(jax.device_get(getattr(out_fused, f)))
        if not (a == c).all():
            print(f"  RUNS MISMATCH in {f} (b={b} t={t_ops} cap={cap} "
                  f"seed={seed})")
            ok = False
    print(f"  runs b={b} t={t_ops} cap={cap}: {'OK' if ok else 'FAIL'}")
    return ok


def main() -> int:
    parser = argparse.ArgumentParser()
    parser.add_argument("--heavy", action="store_true")
    args = parser.parse_args()

    import jax
    backend = jax.default_backend()
    print(f"backend: {backend}")
    if backend not in ("tpu", "axon"):
        print("no TPU reachable — run tests/test_pallas_apply.py for the "
              "interpreter conformance instead")
        return 2
    from fluidframework_tpu.mergetree.pallas_apply import fused_available
    if not fused_available():
        print("fused kernel failed its probe on this backend")
        return 3

    shapes = [(512, 64, 256, 0), (2048, 100, 256, 1), (128, 48, 512, 2)]
    if args.heavy:
        shapes.append((512, 128, 1024, 3))   # narrow-tile 3-D op path
    results = [check(*s) for s in shapes]  # run EVERY shape
    # INSERT_RUN Mosaic variant (round 4): probe, then differential.
    from fluidframework_tpu.mergetree.pallas_apply import (
        fused_runs_available)
    if fused_runs_available():
        results.append(check_runs(256, 64, 256, 7))
        if args.heavy:
            results.append(check_runs(512, 96, 512, 8))
    else:
        print("fused INSERT_RUN variant failed its probe on this backend "
              "(serving will pack on the scan path)")
    results.append(check_fused_sp(64, 48, 256, 11))
    ok = all(results)
    print("CONFORMANCE", "OK" if ok else "FAILED")
    return 0 if ok else 1


def check_fused_sp(b: int, t: int, cap: int, seed: int) -> bool:
    """Round-5: the fused×sp GSPMD body (mergetree/fused_sp.py —
    two-level reshape prefix sums) lowers through real XLA:TPU, not the
    interpreter; a single chip executes the sp>1 formulation with the
    collectives degenerating, so this validates the LOWERING now and the
    multi-chip placement stays covered by dryrun_multichip."""
    import numpy as np

    from fluidframework_tpu.mergetree import fused_sp, kernel
    from fluidframework_tpu.mergetree.oppack import pack_ops
    from fluidframework_tpu.mergetree.state import make_state

    packed = pack_ops(_traces(b, t, seed))
    ref = kernel.apply_ops_batched_keep(make_state(cap, 2, batch=b),
                                        packed)
    out = fused_sp.apply_ops_fused_sp(make_state(cap, 2, batch=b),
                                      packed, 4)
    ok = all(
        bool(np.array_equal(np.asarray(getattr(ref, f)),
                            np.asarray(getattr(out, f))))
        for f in ref._fields)
    print(f"fused_sp b={b} t={t} cap={cap} sp=4: "
          f"{'OK' if ok else 'MISMATCH'}")
    return ok


if __name__ == "__main__":
    sys.exit(main())
