"""Layer check: machine-enforced package layering.

Capability parity with reference tools/build-tools/src/layerCheck (the
build step that validates the dependency DAG documented in
docs/PACKAGES.md; README.md:52-54): every subpackage declares which
subpackages it may import; an import outside the matrix fails the build.
Imports guarded by `if TYPE_CHECKING:` are type-only and exempt (they
erase at runtime), mirroring layer-check's type-only allowance.

Beyond the layering matrix, the checker also builds the module-level
IMPORT-TIME graph (top-level imports only — deferred function-body
imports are the sanctioned cycle-breaking idiom here) and fails hard on
any cycle, printing the offending edges: an import cycle is a layering
violation the matrix cannot express (two modules in the same layer may
still not need each other at import time), and Python resolves one
"successfully" just often enough to ship a partially-initialized module.

Run: `python -m fluidframework_tpu.tools.layer_check` (exit 1 on
violation or cycle); `make layer-check` wires it into `make check`, and
`tests/test_quality_gates.py` runs both gates in CI.
"""

from __future__ import annotations

import ast
import os
from typing import Dict, List, NamedTuple, Optional, Set

# The layering matrix, bottom-up (SURVEY.md §1 mapped onto this package).
ALLOWED: Dict[str, Set[str]] = {
    "core": set(),
    "protocol": {"core"},
    "telemetry": {"core", "protocol"},
    "parallel": {"core"},
    "mergetree": {"core", "protocol", "telemetry", "parallel"},
    # native is the C++ transport under the server; it shares the server's
    # queued-message types (the reference's librdkafka binding lives inside
    # the services package the same way).
    "native": {"core", "server"},
    "dds": {"core", "protocol", "mergetree"},
    "runtime": {"core", "protocol", "dds"},
    "server": {"core", "protocol", "mergetree", "native", "telemetry",
               "parallel"},
    # loader's local/network drivers bind to the in-process server (the
    # reference's local-driver -> local-server edge, SURVEY.md §1).
    "loader": {"core", "protocol", "runtime", "telemetry", "server", "dds"},
    "framework": {"core", "protocol", "dds", "runtime"},
    # capacity is the fleet-soak subsystem: open-loop workload models +
    # the whole-pipeline grader. It drives the server stack directly and
    # sits BELOW testing (the load rig folds its op-mix/schedule onto
    # capacity.workload); chaos plans are injected duck-typed, so the
    # edge to testing/faultinject never exists at import time.
    "capacity": {"core", "protocol", "mergetree", "telemetry", "server"},
    # testing hosts the load rig + snapshot corpus, which drive the full
    # stack like the reference's test-utils/localLoader does; the fault
    # injector counts its injected faults (telemetry sits below server,
    # which testing already imports); the load rig's op mix + schedule
    # live in capacity.workload (one arrival-process implementation).
    "testing": {"core", "protocol", "dds", "runtime", "loader", "server",
                "telemetry", "capacity"},
    "hosts": {"core", "loader", "runtime", "framework"},
    "client_api": {"core", "dds", "loader"},
    "agents": {"core", "dds", "loader", "framework"},
    "tools": {"core", "protocol", "mergetree", "loader"},
    # fluidlint (the AST analyzer) reads the canonical device dtypes from
    # mergetree/constants.py; it must not depend on anything above that.
    "analysis": {"mergetree"},
}

# Per-module exceptions (module path relative to the package root).
EXCEPTIONS: Dict[str, Set[str]] = {
    # The gateway is a host service that happens to live under server/
    # (reference server/gateway is S3 aux, above the client stack).
    "server/gateway.py": {"loader", "framework"},
    # oppack lazily binds the native C packer (native/src/oppack.cpp, the
    # ingest hot path). File-scoped, NOT a package-level edge: native also
    # imports server (oplog wire adapter), so admitting mergetree->native
    # package-wide would put a cycle in the matrix the checker assumes is
    # a DAG.
    "mergetree/oppack.py": {"native"},
    # The runtime lockset verifier is fluidlint v3's dynamic half: its
    # static_guards() derives guard maps from the analysis layer's
    # concurrency model (deferred, function-body import). File-scoped —
    # the rest of testing/ stays below analysis, and analysis never
    # imports testing, so the edge is acyclic.
    "testing/lockcheck.py": {"analysis"},
    # The runtime sharding verifier is fluidlint v4's dynamic half: it
    # asserts actual .sharding against mergetree/partition_rules.py's
    # rule table, so it must import the table it verifies. File-scoped —
    # mergetree never imports testing, so the edge is acyclic.
    "testing/shardcheck.py": {"mergetree"},
}


class Violation(NamedTuple):
    module: str
    line: int
    imports: str
    reason: str

    def __str__(self) -> str:
        return (f"{self.module}:{self.line}: imports {self.imports!r} — "
                f"{self.reason}")


def _runtime_imports(tree: ast.AST) -> List[ast.stmt]:
    """All import nodes NOT under an `if TYPE_CHECKING:` guard."""
    type_only: Set[int] = set()
    for node in ast.walk(tree):
        if isinstance(node, ast.If):
            test = node.test
            name = (test.id if isinstance(test, ast.Name) else
                    test.attr if isinstance(test, ast.Attribute) else None)
            if name == "TYPE_CHECKING":
                for child in ast.walk(node):
                    type_only.add(id(child))
    return [node for node in ast.walk(tree)
            if isinstance(node, (ast.Import, ast.ImportFrom))
            and id(node) not in type_only]


def _target_package(node, module_rel_parts: List[str],
                    package_name: str) -> Optional[str]:
    """Top-level subpackage an import lands in, or None if external."""
    if isinstance(node, ast.Import):
        for alias in node.names:
            if alias.name == package_name or \
                    alias.name.startswith(package_name + "."):
                parts = alias.name.split(".")
                return parts[1] if len(parts) > 1 else None
        return None
    if node.module and node.module.startswith(package_name):
        parts = node.module.split(".")
        return parts[1] if len(parts) > 1 else None
    if node.level:  # relative import
        base = module_rel_parts[:-1]
        up = node.level - 1
        if up:
            base = base[:-up] if up <= len(base) else []
        mod_parts = (node.module or "").split(".") if node.module else []
        full = base + [p for p in mod_parts if p]
        return full[0] if full else None
    return None


def check(package_root: str, allowed: Optional[Dict[str, Set[str]]] = None,
          exceptions: Optional[Dict[str, Set[str]]] = None
          ) -> List[Violation]:
    allowed = ALLOWED if allowed is None else allowed
    exceptions = EXCEPTIONS if exceptions is None else exceptions
    package_name = os.path.basename(os.path.abspath(package_root))
    violations: List[Violation] = []
    for root, _dirs, files in os.walk(package_root):
        for fname in files:
            if not fname.endswith(".py"):
                continue
            path = os.path.join(root, fname)
            rel = os.path.relpath(path, package_root)
            parts = rel.split(os.sep)
            pkg = parts[0][:-3] if parts[0].endswith(".py") else parts[0]
            if pkg not in allowed:
                continue  # top-level modules (e.g. client_api.py) map by name
            permitted = allowed[pkg] | exceptions.get(
                rel.replace(os.sep, "/"), set())
            tree = ast.parse(open(path).read())
            for node in _runtime_imports(tree):
                target = _target_package(node, parts, package_name)
                if target and target != pkg and target not in permitted:
                    violations.append(Violation(
                        rel, node.lineno, target,
                        f"layer {pkg!r} may import only "
                        f"{sorted(permitted)}"))
    return violations


# ---------------------------------------------------------------------------
# import-time cycle detection
# ---------------------------------------------------------------------------

def _toplevel_imports(tree: ast.Module) -> List[ast.stmt]:
    """Imports that execute at module import time: module-body
    statements, descending into top-level If/Try (version guards,
    optional-dependency fallbacks) and class bodies (they execute at
    import), but NOT into function bodies — a deferred function-scope
    import is the sanctioned way to break a would-be cycle."""
    out: List[ast.stmt] = []

    def visit(stmts) -> None:
        for node in stmts:
            if isinstance(node, (ast.Import, ast.ImportFrom)):
                out.append(node)
            elif isinstance(node, ast.If):
                test = node.test
                name = (test.id if isinstance(test, ast.Name) else
                        test.attr if isinstance(test, ast.Attribute)
                        else None)
                if name != "TYPE_CHECKING":
                    visit(node.body)
                # type-only body erases at runtime; the else branch
                # (if any) still executes at import time either way
                visit(node.orelse)
            elif isinstance(node, (ast.Try, ast.ClassDef)):
                for field in ("body", "orelse", "finalbody"):
                    visit(getattr(node, field, []) or [])
                for handler in getattr(node, "handlers", []):
                    visit(handler.body)
    visit(tree.body)
    return out


def _import_target_module(node, module_rel: str, package_name: str,
                          modules: Set[str]) -> List[str]:
    """In-package module(s) (as "server/serve_step"-style keys) that an
    import statement binds at import time."""
    def to_key(dotted: str) -> Optional[str]:
        key = dotted.replace(".", "/")
        if key in modules:
            return key
        if f"{key}/__init__" in modules:
            return f"{key}/__init__"
        return None

    targets: List[str] = []
    if isinstance(node, ast.Import):
        for alias in node.names:
            if alias.name == package_name or \
                    alias.name.startswith(package_name + "."):
                dotted = alias.name[len(package_name) + 1:]
                key = to_key(dotted) if dotted else "__init__"
                if key:
                    targets.append(key)
        return targets
    # ImportFrom: resolve the base package, then each name — a name may
    # be a submodule (edge to it) or a symbol (edge to the base).
    if node.level == 0:
        if not (node.module or "").startswith(package_name):
            return targets
        base = (node.module or "")[len(package_name):].lstrip(".")
    else:
        parts = module_rel.split("/")[:-1]
        up = node.level - 1
        if up > len(parts):
            return targets
        parts = parts[:len(parts) - up] if up else parts
        base = "/".join(parts).replace("/", ".")
        if node.module:
            base = f"{base}.{node.module}" if base else node.module
        base = base.replace("/", ".")
    base_key = base.replace(".", "/") if base else ""
    for alias in node.names:
        if alias.name == "*":
            continue
        sub = to_key(f"{base_key}/{alias.name}" if base_key
                     else alias.name)
        if sub:
            targets.append(sub)
        else:
            key = to_key(base_key) if base_key else "__init__"
            if key:
                targets.append(key)
    return list(dict.fromkeys(targets))


def import_graph(package_root: str) -> Dict[str, Set[str]]:
    """module key ("server/serve_step") -> in-package modules its
    import-time imports bind."""
    package_name = os.path.basename(os.path.abspath(package_root))
    modules: Set[str] = set()
    trees: Dict[str, ast.Module] = {}
    for root, _dirs, files in os.walk(package_root):
        for fname in files:
            if not fname.endswith(".py"):
                continue
            path = os.path.join(root, fname)
            rel = os.path.relpath(path, package_root)
            if "__pycache__" in rel.split(os.sep):
                continue
            key = rel[:-3].replace(os.sep, "/")
            modules.add(key)
            try:
                trees[key] = ast.parse(open(path).read())
            except SyntaxError:
                continue
    graph: Dict[str, Set[str]] = {m: set() for m in modules}
    for key, tree in trees.items():
        for node in _toplevel_imports(tree):
            for target in _import_target_module(node, key, package_name,
                                                modules):
                if target != key:
                    graph[key].add(target)
    return graph


def find_cycles(graph: Dict[str, Set[str]]) -> List[List[str]]:
    """Elementary cycles via DFS back-edges; each reported once, as the
    path of module keys with the closing edge repeated last."""
    WHITE, GREY, BLACK = 0, 1, 2
    color = {n: WHITE for n in graph}
    stack: List[str] = []
    cycles: List[List[str]] = []
    seen: Set[frozenset] = set()

    def dfs(n: str) -> None:
        color[n] = GREY
        stack.append(n)
        for m in sorted(graph.get(n, ())):
            if color.get(m, BLACK) == GREY:
                cyc = stack[stack.index(m):] + [m]
                key = frozenset(cyc)
                if key not in seen:
                    seen.add(key)
                    cycles.append(cyc)
            elif color.get(m) == WHITE:
                dfs(m)
        stack.pop()
        color[n] = BLACK

    for n in sorted(graph):
        if color[n] == WHITE:
            dfs(n)
    return cycles


def main(argv: Optional[List[str]] = None) -> int:
    import argparse
    parser = argparse.ArgumentParser(
        prog="python -m fluidframework_tpu.tools.layer_check")
    parser.add_argument("--root", default=os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))),
        help="package root to check (default: this package; the cycle "
             "gate's exit-1 contract is tested against seeded trees)")
    args = parser.parse_args(argv)
    root = args.root
    found = check(root)
    for violation in found:
        print(violation)
    cycles = find_cycles(import_graph(root))
    for cyc in cycles:
        edges = " -> ".join(cyc)
        print(f"import cycle: {edges} (break the "
              f"`{cyc[-2]} -> {cyc[-1]}` edge, e.g. defer it into the "
              f"function that needs it)")
    print(f"layer-check: {len(found)} violation(s), "
          f"{len(cycles)} import cycle(s)")
    return 1 if (found or cycles) else 0


if __name__ == "__main__":
    raise SystemExit(main())
