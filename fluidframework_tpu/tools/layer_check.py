"""Layer check: machine-enforced package layering.

Capability parity with reference tools/build-tools/src/layerCheck (the
build step that validates the dependency DAG documented in
docs/PACKAGES.md; README.md:52-54): every subpackage declares which
subpackages it may import; an import outside the matrix fails the build.
Imports guarded by `if TYPE_CHECKING:` are type-only and exempt (they
erase at runtime), mirroring layer-check's type-only allowance.

Run: `python -m fluidframework_tpu.tools.layer_check` (exit 1 on
violation); `tests/test_quality_gates.py` runs it in CI.
"""

from __future__ import annotations

import ast
import os
from typing import Dict, List, NamedTuple, Optional, Set

# The layering matrix, bottom-up (SURVEY.md §1 mapped onto this package).
ALLOWED: Dict[str, Set[str]] = {
    "core": set(),
    "protocol": {"core"},
    "telemetry": {"core", "protocol"},
    "parallel": {"core"},
    "mergetree": {"core", "protocol", "telemetry", "parallel"},
    # native is the C++ transport under the server; it shares the server's
    # queued-message types (the reference's librdkafka binding lives inside
    # the services package the same way).
    "native": {"core", "server"},
    "dds": {"core", "protocol", "mergetree"},
    "runtime": {"core", "protocol", "dds"},
    "server": {"core", "protocol", "mergetree", "native", "telemetry",
               "parallel"},
    # loader's local/network drivers bind to the in-process server (the
    # reference's local-driver -> local-server edge, SURVEY.md §1).
    "loader": {"core", "protocol", "runtime", "telemetry", "server", "dds"},
    "framework": {"core", "protocol", "dds", "runtime"},
    # testing hosts the load rig + snapshot corpus, which drive the full
    # stack like the reference's test-utils/localLoader does; the fault
    # injector counts its injected faults (telemetry sits below server,
    # which testing already imports).
    "testing": {"core", "protocol", "dds", "runtime", "loader", "server",
                "telemetry"},
    "hosts": {"core", "loader", "runtime", "framework"},
    "client_api": {"core", "dds", "loader"},
    "agents": {"core", "dds", "loader", "framework"},
    "tools": {"core", "protocol", "mergetree", "loader"},
    # fluidlint (the AST analyzer) reads the canonical device dtypes from
    # mergetree/constants.py; it must not depend on anything above that.
    "analysis": {"mergetree"},
}

# Per-module exceptions (module path relative to the package root).
EXCEPTIONS: Dict[str, Set[str]] = {
    # The gateway is a host service that happens to live under server/
    # (reference server/gateway is S3 aux, above the client stack).
    "server/gateway.py": {"loader", "framework"},
    # oppack lazily binds the native C packer (native/src/oppack.cpp, the
    # ingest hot path). File-scoped, NOT a package-level edge: native also
    # imports server (oplog wire adapter), so admitting mergetree->native
    # package-wide would put a cycle in the matrix the checker assumes is
    # a DAG.
    "mergetree/oppack.py": {"native"},
}


class Violation(NamedTuple):
    module: str
    line: int
    imports: str
    reason: str

    def __str__(self) -> str:
        return (f"{self.module}:{self.line}: imports {self.imports!r} — "
                f"{self.reason}")


def _runtime_imports(tree: ast.AST) -> List[ast.stmt]:
    """All import nodes NOT under an `if TYPE_CHECKING:` guard."""
    type_only: Set[int] = set()
    for node in ast.walk(tree):
        if isinstance(node, ast.If):
            test = node.test
            name = (test.id if isinstance(test, ast.Name) else
                    test.attr if isinstance(test, ast.Attribute) else None)
            if name == "TYPE_CHECKING":
                for child in ast.walk(node):
                    type_only.add(id(child))
    return [node for node in ast.walk(tree)
            if isinstance(node, (ast.Import, ast.ImportFrom))
            and id(node) not in type_only]


def _target_package(node, module_rel_parts: List[str],
                    package_name: str) -> Optional[str]:
    """Top-level subpackage an import lands in, or None if external."""
    if isinstance(node, ast.Import):
        for alias in node.names:
            if alias.name == package_name or \
                    alias.name.startswith(package_name + "."):
                parts = alias.name.split(".")
                return parts[1] if len(parts) > 1 else None
        return None
    if node.module and node.module.startswith(package_name):
        parts = node.module.split(".")
        return parts[1] if len(parts) > 1 else None
    if node.level:  # relative import
        base = module_rel_parts[:-1]
        up = node.level - 1
        if up:
            base = base[:-up] if up <= len(base) else []
        mod_parts = (node.module or "").split(".") if node.module else []
        full = base + [p for p in mod_parts if p]
        return full[0] if full else None
    return None


def check(package_root: str, allowed: Optional[Dict[str, Set[str]]] = None,
          exceptions: Optional[Dict[str, Set[str]]] = None
          ) -> List[Violation]:
    allowed = ALLOWED if allowed is None else allowed
    exceptions = EXCEPTIONS if exceptions is None else exceptions
    package_name = os.path.basename(os.path.abspath(package_root))
    violations: List[Violation] = []
    for root, _dirs, files in os.walk(package_root):
        for fname in files:
            if not fname.endswith(".py"):
                continue
            path = os.path.join(root, fname)
            rel = os.path.relpath(path, package_root)
            parts = rel.split(os.sep)
            pkg = parts[0][:-3] if parts[0].endswith(".py") else parts[0]
            if pkg not in allowed:
                continue  # top-level modules (e.g. client_api.py) map by name
            permitted = allowed[pkg] | exceptions.get(
                rel.replace(os.sep, "/"), set())
            tree = ast.parse(open(path).read())
            for node in _runtime_imports(tree):
                target = _target_package(node, parts, package_name)
                if target and target != pkg and target not in permitted:
                    violations.append(Violation(
                        rel, node.lineno, target,
                        f"layer {pkg!r} may import only "
                        f"{sorted(permitted)}"))
    return violations


def main() -> int:
    import sys
    root = os.path.join(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))))
    found = check(root)
    for violation in found:
        print(violation)
    print(f"layer-check: {len(found)} violation(s)")
    return 1 if found else 0


if __name__ == "__main__":
    raise SystemExit(main())
