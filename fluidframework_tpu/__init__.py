"""fluidframework_tpu — a TPU-native collaborative-data framework.

A from-scratch re-design of the capabilities of Fluid Framework
(reference: AnthonyYates/FluidFramework): distributed data structures kept
eventually consistent by total-order broadcast, with summarization,
reconnect/resubmit, and a partitioned server-side ordering service.

Unlike the reference's pointer-chasing TypeScript merge-tree and Kafka
lambda pipeline, the hot paths here are structure-of-arrays JAX/XLA
kernels that apply batches of ops across thousands of documents per
`jit`/`shard_map` step (see `fluidframework_tpu.mergetree.kernel` and
`fluidframework_tpu.server.ticket_kernel`).

Layering (mirrors reference layer map, SURVEY.md §1):
  protocol/   wire types, quorum, protocol state machine   (layers 1-2)
  core/       collections + utils shared client/server      (layer 2)
  mergetree/  the sequence engine: oracle, device kernel,
              client, snapshots                             (layer 6 core)
  dds/        SharedString/Map/Directory/Matrix/...         (layer 6)
  runtime/    container+datastore runtime, pending state,
              summarizer, GC                                (layer 5)
  loader/     container loader, delta manager, drivers      (layers 3-4)
  server/     ordering service: deli/scribe/scriptorium/
              broadcaster lambdas, partition host, storage  (layers S1-S2)
  parallel/   device mesh, sharding, sequence-parallel scan
  native/     C++ op-log (librdkafka-equivalent role) + ctypes
  telemetry/  loggers, traces, perf counters                (§5 aux)
"""

__version__ = "0.1.0"
