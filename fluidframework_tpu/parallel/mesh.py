"""Mesh construction + sharding specs for batched document state.

Multi-chip design: documents shard over 'dp' (embarrassingly parallel — the
kernel is vmap over docs, so GSPMD partitions it with zero collectives);
the segment capacity axis can shard over 'sp' for very long documents, where
the position prefix-sum becomes local-cumsum + cross-shard offset (XLA
inserts the collectives from the sharding annotations; see seq_scan for the
explicit shard_map formulation).
"""

from __future__ import annotations

from typing import Optional, Sequence

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P


def make_mesh(dp: Optional[int] = None, sp: int = 1,
              devices: Optional[Sequence] = None) -> Mesh:
    devices = list(devices if devices is not None else jax.devices())
    n = len(devices)
    if dp is None:
        dp = n // sp
    if dp * sp != n:
        raise ValueError(f"dp({dp}) x sp({sp}) != device count {n}")
    arr = np.asarray(devices).reshape(dp, sp)
    return Mesh(arr, axis_names=("dp", "sp"))


def shard_docs(mesh: Mesh, state, seq_sharded: bool = False):
    """Place a batched pytree: leading axis over 'dp'; optionally the
    second (capacity) axis of rank>=2 leaves over 'sp'."""
    sp = mesh.shape.get("sp", 1)

    def place(x):
        if x.ndim == 0:
            return jax.device_put(x, NamedSharding(mesh, P()))
        spec = [None] * x.ndim
        spec[0] = "dp"
        # Shard the capacity axis only when it divides evenly (side tables
        # with small dim-1, e.g. ticket client tables, replicate along sp).
        if seq_sharded and x.ndim >= 2 and sp > 1 and x.shape[1] % sp == 0:
            spec[1] = "sp"
        return jax.device_put(x, NamedSharding(mesh, P(*spec)))
    return jax.tree_util.tree_map(place, state)


def replicate(mesh: Mesh, tree):
    return jax.tree_util.tree_map(
        lambda x: jax.device_put(x, NamedSharding(mesh, P())), tree)
