"""Device-mesh parallelism: sharding layouts + sequence-parallel scans.

The reference's parallelism axes (SURVEY.md §2.6) map onto the mesh as:
- partition/document parallelism  -> 'dp' (documents axis of every batch)
- long-sequence scaling            -> 'sp' (segment-capacity axis, with the
  prefix-sum hierarchically decomposed: local cumsum + all-gathered shard
  totals, the moral analog of ring/blockwise attention for positions)
- pipeline across stages           -> host-side async dispatch (ticket batch
  N+1 while batch N's summary write flushes), see server.partition
"""

from .mesh import make_mesh, shard_docs, replicate
from .seq_scan import sharded_cumsum
