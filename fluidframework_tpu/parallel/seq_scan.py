"""Sequence-parallel prefix sum via shard_map.

For documents whose segment table is sharded along the capacity axis
('sp'), position resolution needs a cross-shard exclusive prefix sum. The
decomposition is the standard two-level scan (How to Scale Your Model's
collective-scan recipe): each shard cumsums locally, shard totals are
all-gathered (tiny: one scalar per shard), and each shard adds the sum of
its predecessors. Cost: one psum-sized collective per scan instead of
serializing the whole axis.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

try:  # jax >= 0.8
    from jax import shard_map
except ImportError:  # pragma: no cover — older jax
    from jax.experimental.shard_map import shard_map


def sharded_cumsum(x: jnp.ndarray, mesh: Mesh, axis_name: str = "sp",
                   exclusive: bool = False) -> jnp.ndarray:
    """Cumsum along the last axis of [B_local..., C] with C sharded over
    `axis_name`; batch axes may be sharded over 'dp'."""

    def local(block):
        c = jnp.cumsum(block, axis=-1)
        total = c[..., -1:]
        # Exclusive scan of shard totals: all-gather totals, mask my prefix.
        totals = jax.lax.all_gather(total, axis_name, axis=-1,
                                    tiled=True)  # [..., S]
        idx = jax.lax.axis_index(axis_name)
        mask = jnp.arange(totals.shape[-1]) < idx
        offset = jnp.sum(jnp.where(mask, totals, 0), axis=-1, keepdims=True)
        out = c + offset
        if exclusive:
            out = out - block
        return out

    spec = P(*(["dp"] + [None] * (x.ndim - 2) + [axis_name]))
    return shard_map(local, mesh=mesh, in_specs=(spec,), out_specs=spec)(x)
