"""MockLogger for test assertions (reference telemetry-utils/src/
mockLogger.ts:14): records every event; match helpers assert that expected
events arrived (in order), as mockLogger.matchEvents does."""

from __future__ import annotations

from typing import Any, Dict, List, Sequence

from .logger import TelemetryLogger


class MockLogger(TelemetryLogger):
    def __init__(self):
        super().__init__()
        self.events: List[Dict[str, Any]] = []

    def send(self, event: Dict[str, Any]) -> None:
        self.events.append(self.prepare_event(event))

    def clear(self) -> None:
        self.events = []

    def match_events(self, expected: Sequence[Dict[str, Any]]) -> bool:
        """True iff `expected` is an ordered subsequence, where each expected
        dict is a subset-match of a recorded event."""
        it = iter(self.events)
        for want in expected:
            for got in it:
                if all(got.get(k) == v for k, v in want.items()):
                    break
            else:
                return False
        return True

    def assert_match_any(self, expected: Dict[str, Any]) -> None:
        assert any(all(e.get(k) == v for k, v in expected.items())
                   for e in self.events), \
            f"no event matching {expected!r} in {self.events!r}"
