"""Multi-window SLO burn-rate engine (docs/observability.md v3).

Generalizes server/monitor.SloPolicy (one stage, one ratio check) to
the SRE-workbook shape: an *objective* declares a target good-event
fraction (e.g. 99% of flushes inside budget); producers feed good/bad
event counts; the engine evaluates the **burn rate** — the observed
bad fraction divided by the error budget (1 - target) — over a fast
and a slow window simultaneously. Burn rate 1.0 spends the budget
exactly at the sustainable pace; an alert fires only when BOTH windows
exceed their thresholds, so a brief spike (fast window only) and a
long-ago incident (slow window only) both stay quiet.

The clock is injectable (same contract as AdmissionController) so the
virtual-clock capacity soak grades burn rates deterministically, and
`evaluate()` returns per-objective attribution for /fleet/health.

State is O(buckets): events land in fixed-width time buckets pruned
past the slow window.
"""

from __future__ import annotations

import threading
import time
from collections import deque
from typing import Callable, Deque, Dict, List, Optional

# Default thresholds per the multiwindow alerting recipe: the fast
# window catches "burning 2% of a 30-day budget in an hour" (14.4x),
# the slow window confirms it is sustained (6x). The absolute numbers
# matter less than the two-window AND.
FAST_BURN = 14.4
SLOW_BURN = 6.0


class Objective:
    """One SLO: `target` is the required good fraction (0 < t < 1)."""

    __slots__ = ("name", "target", "description")

    def __init__(self, name: str, target: float,
                 description: str = ""):
        if not 0.0 < target < 1.0:
            raise ValueError(f"target must be in (0, 1): {target}")
        self.name = name
        self.target = float(target)
        self.description = description

    @property
    def error_budget(self) -> float:
        return 1.0 - self.target


class BurnRateEngine:
    """Time-bucketed good/bad counters + two-window burn evaluation."""

    def __init__(self, objectives: List[Objective],
                 clock: Callable[[], float] = time.monotonic,
                 fast_window_s: float = 300.0,
                 slow_window_s: float = 3600.0,
                 fast_burn: float = FAST_BURN,
                 slow_burn: float = SLOW_BURN,
                 bucket_s: Optional[float] = None):
        if fast_window_s <= 0 or slow_window_s < fast_window_s:
            raise ValueError("need 0 < fast_window_s <= slow_window_s")
        self._lock = threading.Lock()
        self._clock = clock
        self.fast_window_s = float(fast_window_s)
        self.slow_window_s = float(slow_window_s)
        self.fast_burn = float(fast_burn)
        self.slow_burn = float(slow_burn)
        # Bucket width: 12 buckets across the fast window keeps the
        # fast-window estimate honest while the slow window stays
        # O(slow/fast * 12) buckets.
        self.bucket_s = float(bucket_s) if bucket_s else \
            self.fast_window_s / 12.0
        self.objectives: Dict[str, Objective] = {
            o.name: o for o in objectives}
        # name -> deque of [bucket_start, good, bad]
        self._buckets: Dict[str, Deque[list]] = {
            name: deque() for name in self.objectives}

    # -- feeding -------------------------------------------------------
    def record(self, objective: str, good: int = 0, bad: int = 0,
               now: Optional[float] = None) -> None:
        if good <= 0 and bad <= 0:
            return
        with self._lock:
            if objective not in self.objectives:
                raise KeyError(f"unknown objective: {objective}")
            if now is None:
                now = self._clock()
            start = now - (now % self.bucket_s)
            buckets = self._buckets[objective]
            if buckets and buckets[-1][0] == start:
                buckets[-1][1] += good
                buckets[-1][2] += bad
            else:
                buckets.append([start, good, bad])
            self._prune(buckets, now)

    def _prune(self, buckets: Deque[list], now: float) -> None:
        horizon = now - self.slow_window_s - self.bucket_s
        while buckets and buckets[0][0] < horizon:
            buckets.popleft()

    # -- evaluation ----------------------------------------------------
    def _window_bad_fraction(self, buckets: Deque[list], now: float,
                             window_s: float) -> Optional[float]:
        cut = now - window_s
        good = bad = 0
        for start, g, b in buckets:
            if start + self.bucket_s > cut:
                good += g
                bad += b
        total = good + bad
        if total == 0:
            return None
        return bad / total

    def burn_rates(self, objective: str,
                   now: Optional[float] = None):
        """(fast, slow) burn rates; None where the window saw no
        events (no data is not a breach)."""
        obj = self.objectives[objective]
        with self._lock:
            if now is None:
                now = self._clock()
            buckets = self._buckets[objective]
            self._prune(buckets, now)
            out = []
            for window in (self.fast_window_s, self.slow_window_s):
                frac = self._window_bad_fraction(buckets, now, window)
                out.append(None if frac is None
                           else frac / obj.error_budget)
            return tuple(out)

    def evaluate(self, now: Optional[float] = None) -> dict:
        """Verdict for /fleet/health: per-objective burn rates +
        breach bits, overall ok, and attribution (the worst-burning
        breached objective, or None)."""
        verdict: Dict[str, dict] = {}
        worst_name, worst_burn = None, 0.0
        for name, obj in self.objectives.items():
            fast, slow = self.burn_rates(name, now=now)
            breach = (fast is not None and slow is not None
                      and fast >= self.fast_burn
                      and slow >= self.slow_burn)
            verdict[name] = {
                "target": obj.target,
                "fastBurn": fast,
                "slowBurn": slow,
                "breach": breach,
            }
            if obj.description:
                verdict[name]["description"] = obj.description
            if breach and (fast or 0.0) >= worst_burn:
                worst_name, worst_burn = name, fast or 0.0
        return {
            "ok": worst_name is None,
            "objectives": verdict,
            "attribution": worst_name,
        }

    # -- lifecycle -----------------------------------------------------
    def set_clock(self, clock: Callable[[], float]) -> None:
        with self._lock:
            self._clock = clock

    def reset(self) -> None:
        with self._lock:
            for buckets in self._buckets.values():
                buckets.clear()
