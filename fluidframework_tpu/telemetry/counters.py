"""Process-wide named counters + the jit retrace probe.

This is the runtime side of fluidlint: wherever a broad exception
handler deliberately swallows an error on an op-pipeline path, it calls
``record_swallow(site)`` so the drop is visible as a rate instead of
silence; and the ``JitRetraceProbe`` wrapper counts compile-cache misses
on the hot jitted kernels so the static RETRACE_HAZARD rule has a
runtime cross-check. ``server/monitor.py`` exports ``snapshot()``
through ``/healthz``.

Kept dependency-free (stdlib only) so every layer — mergetree, loader,
server — can import it without cycles.
"""

from __future__ import annotations

import threading
from typing import Callable, Dict, Optional

_lock = threading.Lock()
_counters: Dict[str, float] = {}


def increment(name: str, by: float = 1.0) -> float:
    with _lock:
        _counters[name] = value = _counters.get(name, 0.0) + by
        return value


def get(name: str) -> float:
    with _lock:
        return _counters.get(name, 0.0)


def snapshot() -> Dict[str, float]:
    with _lock:
        return dict(_counters)


def reset() -> None:
    """Test isolation only."""
    with _lock:
        _counters.clear()


def record_swallow(site: str) -> None:
    """Count a deliberately swallowed exception at ``site``. The point is
    the rate: a handful of swallows is a degraded dependency, a climbing
    counter is an outage hiding behind a broad except."""
    increment(f"swallowed.{site}")


class JitRetraceProbe:
    """Transparent wrapper over a jitted callable that counts compile-
    cache growth observed across THIS probe's calls. The first growth a
    probe observes is an expected compile (``<name>.compiles``); growth
    on a later call is a retrace — a new (shape, dtype, structure)
    signature on a path the static analyzer believes is shape-stable —
    counted as ``<name>.retraces`` and aggregated into
    ``kernel.retrace_count``.

    The cache baseline snapshots lazily on the probe's first call (not
    at construction), so compiles other callers made earlier against the
    same shared jit cache are neither charged to this probe nor
    misread as retraces. Growth caused by a concurrent other-caller
    compile during one of our calls is still attributed here — the
    counter is an operational rate signal, not an exact ledger.
    """

    def __init__(self, fn: Callable, name: str):
        self._fn = fn
        self.name = name
        # Module-global probes are shared across partition/worker threads:
        # guard the cache-size accounting so two concurrent first compiles
        # don't read as a phantom retrace (or lose a real one).
        self._probe_lock = threading.Lock()
        self._last: Optional[int] = None
        self._seen_compile = False

    def _cache_size(self) -> int:
        size = getattr(self._fn, "_cache_size", None)
        if size is None:
            return -1  # not a jitted callable (or an old jax): probe off
        try:
            return int(size())
        except (TypeError, ValueError):
            return -1

    def __call__(self, *args, **kwargs):
        with self._probe_lock:
            if self._last is None:  # lazy baseline: first probed call
                self._last = self._cache_size()
        out = self._fn(*args, **kwargs)
        size = self._cache_size()
        with self._probe_lock:
            if size >= 0 and self._last >= 0 and size > self._last:
                grew = size - self._last
                increment(f"{self.name}.compiles", grew)
                if self._seen_compile:
                    increment(f"{self.name}.retraces", grew)
                    increment("kernel.retrace_count", grew)
                self._seen_compile = True
            if size >= 0:
                self._last = size
        return out

    def __getattr__(self, item):
        # Passthrough (lower/trace/cache introspection on the wrapped jit).
        return getattr(self._fn, item)
