"""Process-wide named counters + the jit retrace probe.

This is the runtime side of fluidlint: wherever a broad exception
handler deliberately swallows an error on an op-pipeline path, it calls
``record_swallow(site)`` so the drop is visible as a rate instead of
silence; and the ``JitRetraceProbe`` wrapper counts compile-cache misses
on the hot jitted kernels so the static RETRACE_HAZARD rule has a
runtime cross-check. ``server/monitor.py`` exports ``snapshot()``
through ``/healthz``.

Kept dependency-free (stdlib only) so every layer — mergetree, loader,
server — can import it without cycles.
"""

from __future__ import annotations

import math
import os
import threading
from typing import Callable, Dict, List, Optional, Sequence, Tuple

_lock = threading.Lock()
_counters: Dict[str, float] = {}

# -- cardinality guard -------------------------------------------------------
# /metrics.prom renders every counter name as a sample line, so a name
# minted per tenant/shard/document grows the exposition text
# monotonically under churn (the UNBOUNDED_QUEUE class, for metrics).
# Two bounds: `bounded()` caps each declared dynamic family at
# FAMILY_CAP distinct labels (overflow collapses into one
# `<family>.__other__` bucket), and a global name cap backstops any
# site that mints names directly — past it, new names collapse into
# their two-segment family's overflow bucket. Every collapse counts in
# telemetry.metrics_dropped so the condition is visible, not silent.

FAMILY_CAP = int(os.environ.get("FLUID_METRIC_FAMILY_CAP", "64"))
MAX_COUNTER_NAMES = int(os.environ.get("FLUID_METRIC_NAME_CAP", "4096"))
OVERFLOW_LABEL = "__other__"
_families: Dict[str, set] = {}


def _guarded_name(name: str) -> str:
    """Global-cap backstop; call with _lock held."""
    if name in _counters or name == "telemetry.metrics_dropped" \
            or len(_counters) < MAX_COUNTER_NAMES:
        return name
    _counters["telemetry.metrics_dropped"] = \
        _counters.get("telemetry.metrics_dropped", 0.0) + 1.0
    family = ".".join(name.split(".")[:2])
    return f"{family}.{OVERFLOW_LABEL}"


def bounded(family: str, label) -> str:
    """The bounded name for a dynamic-label counter family: the first
    FAMILY_CAP distinct labels get their own `<family>.<label>` name;
    later labels share `<family>.__other__` (and count a drop). Use for
    any per-tenant / per-shard / per-document metric."""
    label = str(label)
    with _lock:
        seen = _families.setdefault(family, set())
        if label in seen:
            return f"{family}.{label}"
        if len(seen) < FAMILY_CAP:
            seen.add(label)
            return f"{family}.{label}"
        _counters["telemetry.metrics_dropped"] = \
            _counters.get("telemetry.metrics_dropped", 0.0) + 1.0
    return f"{family}.{OVERFLOW_LABEL}"


def increment(name: str, by: float = 1.0) -> float:
    with _lock:
        name = _guarded_name(name)
        _counters[name] = value = _counters.get(name, 0.0) + by
        return value


def gauge(name: str, value: float) -> None:
    """Set an absolute reading (probe outputs like decay_probe's
    per-wave rate — the LAST observation is the signal, not a sum)."""
    with _lock:
        _counters[_guarded_name(name)] = float(value)


def get(name: str) -> float:
    with _lock:
        return _counters.get(name, 0.0)


def snapshot() -> Dict[str, float]:
    with _lock:
        return dict(_counters)


# -- latency histograms ------------------------------------------------------
# Per-stage latency distributions (the serving flush's named sub-spans,
# historian reads, ...): a rolling sample window for percentile/SLO math
# plus cumulative Prometheus-style buckets (with the last trace id seen
# per bucket as an exemplar) for /metrics.prom exposition.

LATENCY_BUCKETS_MS: Tuple[float, ...] = (
    0.25, 0.5, 1.0, 2.5, 5.0, 10.0, 25.0, 50.0, 100.0, 250.0, 500.0,
    1000.0, 2500.0, 10000.0, math.inf)

LATENCY_WINDOW = 512


class _Hist:
    __slots__ = ("samples", "bucket_counts", "exemplars", "total", "count")

    def __init__(self):
        self.samples: List[float] = []      # rolling window
        self.bucket_counts = [0] * len(LATENCY_BUCKETS_MS)  # non-cumulative
        self.exemplars: List[Optional[Tuple[str, float]]] = \
            [None] * len(LATENCY_BUCKETS_MS)
        self.total = 0.0                    # cumulative sum (ms)
        self.count = 0


_hists: Dict[str, _Hist] = {}


def nearest_rank(ordered: Sequence[float], p: float) -> float:
    """Nearest-rank percentile over an ASCENDING-sorted sample window:
    the ceil(p*N)-th smallest value (p in (0, 1]). Shared by
    MetricClient.snapshot and the SLO evaluation so both quote the same
    number for the same window — and exact at tiny N (p50 of [1, 2] is
    1, the lower median; p99 of a 100-sample window is the 99th value,
    not the max)."""
    if not ordered:
        return 0.0
    idx = max(0, math.ceil(p * len(ordered)) - 1)
    return ordered[min(idx, len(ordered) - 1)]


def observe(name: str, ms: float,
            trace_id: Optional[str] = None) -> None:
    """Record one latency sample for stage ``name``."""
    with _lock:
        h = _hists.get(name)
        if h is None:
            h = _hists[name] = _Hist()
        h.samples.append(ms)
        if len(h.samples) > LATENCY_WINDOW:
            del h.samples[:len(h.samples) - LATENCY_WINDOW]
        for i, le in enumerate(LATENCY_BUCKETS_MS):
            if ms <= le:
                h.bucket_counts[i] += 1
                if trace_id is not None:
                    h.exemplars[i] = (trace_id, ms)
                break
        h.total += ms
        h.count += 1


def latency_snapshot() -> Dict[str, Dict[str, float]]:
    """Per-stage window aggregates: {name: {count, p50, p99, max}} —
    the health-report / SLO view."""
    with _lock:
        items = [(name, list(h.samples)) for name, h in _hists.items()]
    out: Dict[str, Dict[str, float]] = {}
    for name, samples in items:
        if not samples:
            continue
        ordered = sorted(samples)
        out[name] = {"count": len(ordered),
                     "p50": nearest_rank(ordered, 0.50),
                     "p99": nearest_rank(ordered, 0.99),
                     "max": ordered[-1]}
    return out


def latency_window(name: str) -> List[float]:
    """The raw rolling window for one stage (SLO evaluation input)."""
    with _lock:
        h = _hists.get(name)
        return list(h.samples) if h is not None else []


def histogram_export() -> Dict[str, dict]:
    """Cumulative-bucket view for Prometheus text exposition: {name:
    {"buckets": [(le_ms, cumulative_count, exemplar|None)], "sum": ms,
    "count": n}} with exemplar = (trace_id, value_ms)."""
    with _lock:
        copies = [(name, list(h.bucket_counts), list(h.exemplars),
                   h.total, h.count) for name, h in _hists.items()]
    out: Dict[str, dict] = {}
    for name, bucket_counts, exemplars, total, count in copies:
        cum = 0
        buckets = []
        for le, c, ex in zip(LATENCY_BUCKETS_MS, bucket_counts, exemplars):
            cum += c
            buckets.append((le, cum, ex))
        out[name] = {"buckets": buckets, "sum": total, "count": count}
    return out


def reset_histograms() -> None:
    """Test isolation only: drop latency histograms (the rolling SLO
    window) without touching the named counters — cross-test flush
    samples would otherwise let one test's tail flip another test's
    /health verdict."""
    with _lock:
        _hists.clear()


def reset_stage(name: str) -> None:
    """Drop ONE stage's latency histogram (window + buckets). Harnesses
    that feed a private SLO stage (the capacity soak's virtual-clock
    flush window) clear it per run so back-to-back runs in one process
    grade on their own samples, not the previous run's residue."""
    with _lock:
        _hists.pop(name, None)


def reset() -> None:
    """Test isolation only."""
    with _lock:
        _counters.clear()
        _hists.clear()
        _families.clear()


def record_swallow(site: str) -> None:
    """Count a deliberately swallowed exception at ``site``. The point is
    the rate: a handful of swallows is a degraded dependency, a climbing
    counter is an outage hiding behind a broad except."""
    increment(f"swallowed.{site}")


class JitRetraceProbe:
    """Transparent wrapper over a jitted callable that counts compile-
    cache growth observed across THIS probe's calls. The first growth a
    probe observes is an expected compile (``<name>.compiles``); growth
    on a later call is a retrace — a new (shape, dtype, structure)
    signature on a path the static analyzer believes is shape-stable —
    counted as ``<name>.retraces`` and aggregated into
    ``kernel.retrace_count``.

    The cache baseline snapshots lazily on the probe's first call (not
    at construction), so compiles other callers made earlier against the
    same shared jit cache are neither charged to this probe nor
    misread as retraces. Growth caused by a concurrent other-caller
    compile during one of our calls is still attributed here — the
    counter is an operational rate signal, not an exact ledger.

    Every call also feeds the process-wide compile ledger
    (telemetry/compile_ledger.py) with the call's wall time and the
    observed cache growth — warm-vs-cold attribution and cumulative
    compile ms per symbol ride /health, /metrics.prom, and bench
    records from there.
    """

    def __init__(self, fn: Callable, name: str):
        self._fn = fn
        self.name = name
        # Module-global probes are shared across partition/worker threads:
        # guard the cache-size accounting so two concurrent first compiles
        # don't read as a phantom retrace (or lose a real one).
        self._probe_lock = threading.Lock()
        self._last: Optional[int] = None
        self._seen_compile = False

    def _cache_size(self) -> int:
        size = getattr(self._fn, "_cache_size", None)
        if size is None:
            return -1  # not a jitted callable (or an old jax): probe off
        try:
            return int(size())
        except (TypeError, ValueError):
            return -1

    def __call__(self, *args, **kwargs):
        import time as _time

        from . import compile_ledger as _ledger  # lazy: avoids a cycle

        with self._probe_lock:
            if self._last is None:  # lazy baseline: first probed call
                self._last = self._cache_size()
        t0 = _time.perf_counter()
        out = self._fn(*args, **kwargs)
        dur_ms = (_time.perf_counter() - t0) * 1000.0
        size = self._cache_size()
        grew = 0
        with self._probe_lock:
            if size >= 0 and self._last >= 0 and size > self._last:
                grew = size - self._last
                increment(f"{self.name}.compiles", grew)
                if self._seen_compile:
                    increment(f"{self.name}.retraces", grew)
                    increment("kernel.retrace_count", grew)
                self._seen_compile = True
            if size >= 0:
                self._last = size
        _ledger.ledger.watch(self.name, self._fn)
        _ledger.ledger.note_call(self.name, dur_ms, grew=grew)
        return out

    def __getattr__(self, item):
        # Passthrough (lower/trace/cache introspection on the wrapped jit).
        return getattr(self._fn, item)
