"""Device-resident telemetry planes: the host-side layout + fold.

PRs 7/8/11 moved the serving hot path inside single device programs
(fused scan bursts, paged applies on gathered page views, batched
extract epochs), so the facts that matter — per-window op mix,
noop-skipped applies, overflow flags, rows reclaimed by zamboni, lane
fill — are invisible to host spans unless a readback pays for them.
The device programs therefore emit a compact int32 stats plane IN THEIR
EXISTING readback (serve_step.py packs it into the flat16 narrow
result as lo/hi int16 halves; the paged/extract kernels return it next
to the planes they already return), and this module is the single
source of truth for the slot layout plus the host-side fold into the
counters/histogram/Prometheus surface.

Contracts (gated by ``make obs-smoke`` + tests/test_device_stats.py):

  * bit-identity-neutral — telemetry on/off produces the identical
    emit stream and lane planes (the plane is an appended output, never
    an input to the op phases);
  * zero extra dispatches and zero extra host round-trips per
    window/burst (the plane rides the flat16 the host already fetches);
  * device-counted op totals reconcile EXACTLY with host-side counts —
    every fold takes the device vector AND a host-derived mirror, and
    both land as counters (``device.serving.*`` vs ``host.serving.*``)
    so the reconciliation is a live operational check, not a test-only
    artifact.

The process-wide toggle is static at dispatch (a different compiled
program with the stats tail present/absent), so flipping it costs one
recompile, never a semantic change.
"""

from __future__ import annotations

import os
import threading
from typing import Dict, Optional, Sequence

from . import counters as _counters

# -- slot layouts ------------------------------------------------------------
# Index order is THE contract between the traced device code
# (serve_step._serve_window_impl, kernel.apply_ops_paged and friends)
# and the host decode: append-only, never reorder.

# One serving window (rides the flat16 narrow result as 2*N int16).
SERVE_SLOTS = (
    "ops_insert",          # admitted merge ops by kind (post nack/void)
    "ops_remove",
    "ops_annotate",
    "ops_ack_insert",
    "ops_ack_remove",
    "ops_insert_run",
    "lww_ops",             # admitted LWW ops (any kind)
    "ticket_admitted",     # sequenced messages (ops + joins + system)
    "ticket_nacked",
    "ticket_not_joined",
    "merge_overflow_lanes",
    "lww_overflow_lanes",
    "noop_skipped_applies",  # burst padding skips (kernel.apply_if_any)
    "merge_rows_live",     # post-window bucket fill (sum of lane counts)
    "lww_keys_live",
)
N_SERVE = len(SERVE_SLOTS)

# One paged apply / paged-burst chunk.
PAGED_SLOTS = (
    "ops_insert",
    "ops_remove",
    "ops_annotate",
    "ops_ack_insert",
    "ops_ack_remove",
    "ops_insert_run",
    "overflow_docs",
    "rows_live",           # post-apply live rows across the group
)
N_PAGED = len(PAGED_SLOTS)

# One fused zamboni+extract dispatch (bucketed or paged).
EXTRACT_SLOTS = (
    "docs",
    "rows_live",           # post-compaction live rows
    "rows_reclaimed",      # zamboni reclaim: pre minus post live rows
)
N_EXTRACT = len(EXTRACT_SLOTS)

# Slots folded as monotone counters; the rest are point-in-time gauges.
_SERVE_GAUGES = {"merge_rows_live", "lww_keys_live"}

# -- process-wide toggle -----------------------------------------------------

_lock = threading.Lock()
_enabled = os.environ.get("FLUID_DEVICE_STATS", "1") not in ("0", "false")


def enabled() -> bool:
    return _enabled


def set_enabled(on: bool) -> None:
    """Flip the process-wide toggle (static at dispatch: the next
    window compiles with/without the stats tail; results are
    bit-identical either way)."""
    global _enabled
    with _lock:
        _enabled = bool(on)


# -- folds -------------------------------------------------------------------

def _fold(prefix: str, slots: Sequence[str], vec, gauges=frozenset()):
    for name, value in zip(slots, vec):
        v = float(value)
        if name in gauges:
            _counters.gauge(f"{prefix}.{name}", v)
        elif v:
            _counters.increment(f"{prefix}.{name}", v)


def fold_serve(device_vec, host_vec=None) -> None:
    """Fold one window's device stats vector (int order = SERVE_SLOTS)
    into ``device.serving.*``; ``host_vec`` — the host-derived mirror
    computed from the same window's staging + decoded ticket results —
    lands as ``host.serving.*`` so device-vs-host reconciliation is a
    counter diff."""
    _fold("device.serving", SERVE_SLOTS, device_vec, _SERVE_GAUGES)
    if host_vec is not None:
        _fold("host.serving", SERVE_SLOTS, host_vec, _SERVE_GAUGES)
        for name, d, h in zip(SERVE_SLOTS, device_vec, host_vec):
            if name not in _SERVE_GAUGES and int(d) != int(h):
                _counters.increment("device.serving.reconcile_mismatch")
                break


def fold_paged(device_vec, host_vec=None) -> None:
    _fold("device.paged", PAGED_SLOTS, device_vec)
    if host_vec is not None:
        _fold("host.paged", PAGED_SLOTS, host_vec)
        for name, d, h in zip(PAGED_SLOTS, device_vec, host_vec):
            if name != "rows_live" and int(d) != int(h):
                _counters.increment("device.paged.reconcile_mismatch")
                break


def fold_extract(device_vec) -> None:
    _fold("device.extract", EXTRACT_SLOTS, device_vec)


# -- flush-span enrichment ---------------------------------------------------
# The serving.flush span gains device-measured sub-facts: the sequencer
# snapshots these keys at flush start and stamps the deltas at flush
# end (windows retired during the flush — including deferred windows
# from earlier flushes draining now — attribute here).

_FLUSH_KEYS = (
    ("dev_ops", ("device.serving.ops_insert",
                 "device.serving.ops_remove",
                 "device.serving.ops_annotate",
                 "device.serving.ops_ack_insert",
                 "device.serving.ops_ack_remove",
                 "device.serving.ops_insert_run",
                 "device.serving.lww_ops")),
    ("dev_admitted", ("device.serving.ticket_admitted",)),
    ("dev_nacked", ("device.serving.ticket_nacked",)),
    ("dev_overflow_lanes", ("device.serving.merge_overflow_lanes",
                            "device.serving.lww_overflow_lanes")),
    ("dev_noop_skips", ("device.serving.noop_skipped_applies",)),
    ("dev_zamboni_rows", ("device.extract.rows_reclaimed",
                          "zamboni.rows_reclaimed")),
)


def begin_flush() -> tuple:
    return tuple(sum(_counters.get(c) for c in cs)
                 for _, cs in _FLUSH_KEYS)


def flush_facts(token: tuple) -> Dict[str, int]:
    """Non-zero deltas since ``begin_flush`` — the serving.flush span's
    device-measured attributes."""
    out: Dict[str, int] = {}
    for (name, cs), before in zip(_FLUSH_KEYS, token):
        delta = sum(_counters.get(c) for c in cs) - before
        if delta:
            out[name] = int(delta)
    return out


def snapshot() -> Dict[str, float]:
    """Every device.*/host.* stats counter — the /health block."""
    return {k: v for k, v in _counters.snapshot().items()
            if k.startswith(("device.", "host."))}


def reconcile() -> Optional[dict]:
    """Device-vs-host totals for the countable serving slots: {slot:
    (device, host)} for any slot that disagrees, or None when exact."""
    snap = _counters.snapshot()
    bad = {}
    for name in SERVE_SLOTS:
        if name in _SERVE_GAUGES:
            continue
        d = snap.get(f"device.serving.{name}", 0.0)
        h = snap.get(f"host.serving.{name}", 0.0)
        if int(d) != int(h):
            bad[name] = (int(d), int(h))
    return bad or None
