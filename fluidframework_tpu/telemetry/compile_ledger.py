"""The compile/dispatch observatory: a process-wide compile ledger.

``JitRetraceProbe`` (counters.py) counts cache growth per wrapped
callable; this module generalizes it into one process-wide ledger every
surface reads from the same place:

  * per-symbol compile count (jit-cache growth observed across calls),
  * cumulative compile milliseconds (wall time of the calls during
    which the cache grew — cold-call attribution: the compile dominates
    those calls, and it is exactly the figure the r05/r06 bench bugs
    needed machine-visible: a "warm" measurement region whose ledger
    shows compiles was not warm),
  * warm-vs-cold call split (cold = the cache grew during the call),
  * shape-grid / cache-key occupancy (the jit cache's current size per
    symbol — the RETRACE_HAZARD budget is log2-bounded grids, so a
    symbol whose occupancy outgrows its grid is a leaked signature).

Two feeding paths, both lock-cheap:

  * ``JitRetraceProbe`` calls :func:`note_call` transparently for every
    wrapped kernel (kernel.merge_apply_batched, kernel.paged_apply,
    kernel.extract_gather, ...).
  * Call sites that must NOT wrap their jitted callable (the serving
    dispatches — fluidlint's donated-buffer dataflow resolves
    ``serve_step.serve_window`` to its partial-jit wrapper by name, and
    a wrapper object would blind it) register the callable once with
    :func:`watch` and stamp each call with :func:`note_call`; the
    ledger reads the jit cache size itself.

Surfaces: ``/health`` (``compileLedger``), ``/metrics.prom``
(``fluid_compile_*`` per-symbol gauges — symbol cardinality is the
fixed probe set, so no label-fan-out guard is needed), and
:func:`bench_stamp` rides top-level in every bench record.

Kept stdlib-only, like counters.py, so every layer can import it.
"""

from __future__ import annotations

import threading
import time
from typing import Callable, Dict, Optional

from . import counters as _counters


class _Entry:
    __slots__ = ("name", "fn", "compiles", "retraces", "cold_calls",
                 "warm_calls", "compile_ms", "warm_ms", "cache_size",
                 "_last_size", "_seen_compile")

    def __init__(self, name: str, fn: Optional[Callable]):
        self.name = name
        self.fn = fn
        self.compiles = 0
        self.retraces = 0
        self.cold_calls = 0
        self.warm_calls = 0
        self.compile_ms = 0.0
        self.warm_ms = 0.0
        self.cache_size = -1
        self._last_size: Optional[int] = None
        self._seen_compile = False


def _cache_size(fn) -> int:
    size = getattr(fn, "_cache_size", None)
    if size is None:
        return -1  # not a jitted callable (or an old jax): occupancy off
    try:
        return int(size())
    except (TypeError, ValueError):
        return -1


class CompileLedger:
    """Registry of watched jitted symbols + their compile attribution."""

    def __init__(self):
        self._lock = threading.Lock()
        self._entries: Dict[str, _Entry] = {}

    # -- registration -------------------------------------------------------
    def watch(self, name: str, fn: Optional[Callable] = None) -> str:
        """Register ``name`` (idempotent). ``fn`` — when it is the jitted
        callable itself — gives the ledger cache-size occupancy; probes
        that track their own cache pass fn=None and report growth via
        ``note_call(grew=...)``."""
        with self._lock:
            entry = self._entries.get(name)
            if entry is None:
                self._entries[name] = entry = _Entry(name, fn)
                if fn is not None:
                    # Baseline at registration: compiles other callers
                    # made earlier are not charged here, but the FIRST
                    # call through this site attributes its own compile
                    # (the warm-up fact bench records need).
                    size = _cache_size(fn)
                    if size >= 0:
                        entry._last_size = size
                        entry.cache_size = size
            elif fn is not None and entry.fn is None:
                entry.fn = fn
                size = _cache_size(fn)
                if size >= 0 and entry._last_size is None:
                    entry._last_size = size
                    entry.cache_size = size
            return name

    # -- attribution --------------------------------------------------------
    def note_call(self, name: str, dur_ms: float,
                  grew: Optional[int] = None) -> None:
        """Attribute one call of a watched symbol. ``grew`` — when the
        caller already measured cache growth (JitRetraceProbe) — is
        authoritative; otherwise the ledger diffs the watched callable's
        jit-cache size across calls. A call during which the cache grew
        is COLD: its wall time lands in compile_ms (the compile
        dominates it), growth past the first observed compile counts as
        a retrace (a leaked signature on a shape-stable path)."""
        with self._lock:
            entry = self._entries.get(name)
            if entry is None:
                self._entries[name] = entry = _Entry(name, None)
            if grew is None and entry.fn is not None:
                size = _cache_size(entry.fn)
                if size >= 0:
                    last = entry._last_size
                    grew = size - last if last is not None \
                        and size > last else 0
                    entry._last_size = size
                    entry.cache_size = size
            grew = int(grew or 0)
            if grew > 0:
                entry.compiles += grew
                entry.cold_calls += 1
                entry.compile_ms += dur_ms
                if entry._seen_compile:
                    entry.retraces += grew
                entry._seen_compile = True
            else:
                entry.warm_calls += 1
                entry.warm_ms += dur_ms

    def track(self, name: str, fn: Callable) -> "_Tracked":
        """Context manager for un-wrappable call sites::

            with ledger.track("serve.window", serve_step.serve_window):
                out = serve_step.serve_window(...)
        """
        self.watch(name, fn)
        return _Tracked(self, name)

    # -- views --------------------------------------------------------------
    def snapshot(self) -> dict:
        """{"symbols": {name: {...}}, "totals": {...}} — the /health and
        bench view. Occupancy refreshes lazily here for watched
        callables that have not been stamped since their cache last
        grew (a /health read must not under-report)."""
        with self._lock:
            symbols: Dict[str, dict] = {}
            tot_compiles = tot_retraces = 0
            tot_compile_ms = 0.0
            tot_cold = tot_warm = 0
            for name, e in sorted(self._entries.items()):
                if e.fn is not None:
                    size = _cache_size(e.fn)
                    if size >= 0:
                        e.cache_size = size
                symbols[name] = {
                    "compiles": e.compiles,
                    "retraces": e.retraces,
                    "coldCalls": e.cold_calls,
                    "warmCalls": e.warm_calls,
                    "compileMs": round(e.compile_ms, 3),
                    "warmMs": round(e.warm_ms, 3),
                    "cacheSize": e.cache_size,
                }
                tot_compiles += e.compiles
                tot_retraces += e.retraces
                tot_compile_ms += e.compile_ms
                tot_cold += e.cold_calls
                tot_warm += e.warm_calls
        return {
            "symbols": symbols,
            "totals": {
                "compiles": tot_compiles,
                "retraces": tot_retraces,
                "compileMs": round(tot_compile_ms, 3),
                "coldCalls": tot_cold,
                "warmCalls": tot_warm,
                "backendCompileMs": round(
                    _counters.get("compile.backend_ms"), 3),
            },
        }

    def bench_stamp(self) -> dict:
        """The bench-record form: per-symbol {compiles, compileMs,
        cacheSize} + totals — compact enough to ride every record, rich
        enough that a warm-up bug (compiles observed inside a measured
        region) is machine-visible instead of re-diagnosed."""
        snap = self.snapshot()
        return {
            "total_compiles": snap["totals"]["compiles"],
            "total_compile_ms": snap["totals"]["compileMs"],
            "retraces": snap["totals"]["retraces"],
            "symbols": {
                name: {"compiles": s["compiles"],
                       "compile_ms": s["compileMs"],
                       "cache_size": s["cacheSize"]}
                for name, s in snap["symbols"].items()},
        }

    def reset(self) -> None:
        """Test isolation only."""
        with self._lock:
            self._entries.clear()


class _Tracked:
    __slots__ = ("_ledger", "_name", "_t0")

    def __init__(self, ledger: CompileLedger, name: str):
        self._ledger = ledger
        self._name = name
        self._t0 = 0.0

    def __enter__(self) -> "_Tracked":
        self._t0 = time.perf_counter()
        return self

    def __exit__(self, *_exc) -> None:
        self._ledger.note_call(
            self._name, (time.perf_counter() - self._t0) * 1000.0)


ledger = CompileLedger()

# -- jax backend-compile listener (best effort) ------------------------------
# jax.monitoring publishes duration events for backend compilation; when
# the running jax exposes the hook, cumulative backend-compile wall time
# accumulates into the compile.backend_ms counter (the ledger's per-call
# attribution is the per-symbol view; this is the ground-truth total).
_listener_installed = False
_listener_lock = threading.Lock()


def install_jax_listener() -> bool:
    global _listener_installed
    with _listener_lock:
        if _listener_installed:
            return True
        try:
            from jax import monitoring as _mon

            def _on_duration(event: str, duration_secs: float, **_kw):
                if "compile" in event:
                    _counters.increment("compile.backend_ms",
                                        duration_secs * 1000.0)

            _mon.register_event_duration_secs_listener(_on_duration)
            _listener_installed = True
            return True
        except Exception:  # noqa: BLE001 — observatory is best-effort
            _counters.record_swallow("compile_ledger.jax_listener")
            return False
