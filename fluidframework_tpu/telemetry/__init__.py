"""Telemetry: logger hierarchy, perf spans, round-trip measurement, mocks.

Parity: reference packages/utils/telemetry-utils (see SURVEY.md §5
Metrics/logging)."""

from .logger import (
    ERROR,
    GENERIC,
    PERFORMANCE,
    ChildLogger,
    DebugLogger,
    MultiSinkLogger,
    OpRoundTripTelemetry,
    PerformanceEvent,
    TelemetryLogger,
)
from .mock import MockLogger
from . import compile_ledger
from . import counters
from . import device_stats
from . import tracing
from .counters import JitRetraceProbe, record_swallow

__all__ = [
    "ERROR", "GENERIC", "PERFORMANCE",
    "ChildLogger", "DebugLogger", "MultiSinkLogger",
    "OpRoundTripTelemetry", "PerformanceEvent", "TelemetryLogger",
    "MockLogger", "JitRetraceProbe", "compile_ledger", "counters",
    "device_stats", "record_swallow", "tracing",
]
