"""Op-lifecycle distributed tracing + the serving-stage flight recorder.

The span-context analog of the reference's ``ITelemetryLogger`` boundary
(telemetry-utils threads a logger through every layer; this threads a
``TraceContext`` through the op envelope): a client edit starts a trace,
the context rides ``DocumentMessage.metadata["trace"]`` across the
driver wire, and every pipeline stage (alfred ingest, deli ticket, the
serving flush's named sub-spans, broadcaster fan-out, scribe summarize,
historian reads) records child spans into a bounded, lock-cheap ring
buffer — the flight recorder. ``server/monitor.py`` drains it over
``/trace`` as Chrome trace-event JSON, which perfetto/chrome://tracing
open directly.

Sampling policy: head-based 1-in-N at trace creation (``configure
(sample=N)``; N=0 disables tracing entirely and every entry point
short-circuits to a shared no-op — the <2% overhead budget that
``make trace-smoke`` enforces is measured at N=1, the worst case).
Always-sample-on-slow rides on top: a span that was NOT selected still
records itself when its duration crosses ``slow_ms`` — tail latency
outliers never escape the recorder just because the sampler skipped
them.

Kept dependency-free (stdlib only) so every layer — mergetree, loader,
server — can import it without cycles, exactly like counters.py.
"""

from __future__ import annotations

import contextvars
import itertools
import json
import os
import threading
import time
from typing import Any, Dict, List, Optional

from . import counters

# DocumentMessage.metadata key the wire context rides under. Metadata is
# already propagated verbatim by SequencedDocumentMessage.from_document_
# message and by every driver serializer, so no wire-format change is
# needed for end-to-end propagation.
TRACE_KEY = "trace"


class TraceContext:
    """Identity of one trace position: (trace_id, span_id) plus the
    head-sampling decision. Child spans inherit trace_id + sampled and
    parent onto span_id."""

    __slots__ = ("trace_id", "span_id", "parent_id", "sampled", "_wire")

    def __init__(self, trace_id: str, span_id: str,
                 parent_id: Optional[str] = None, sampled: bool = True):
        self.trace_id = trace_id
        self.span_id = span_id
        self.parent_id = parent_id
        self.sampled = sampled
        self._wire: Optional[str] = None

    def to_wire(self) -> str:
        """Compact "traceId:spanId:sampled" string (cached). A string —
        not a dict — deliberately: metadata rides dataclasses.asdict on
        every persisted message, and asdict deep-copies dict values but
        passes strings through atomically, so the wire form costs ~0 on
        the scriptorium hot path."""
        wire = self._wire
        if wire is None:
            wire = self._wire = (f"{self.trace_id}:{self.span_id}:"
                                 f"{1 if self.sampled else 0}")
        return wire

    @staticmethod
    def from_wire(v: Any) -> Optional["TraceContext"]:
        if isinstance(v, str):
            parts = v.split(":")
            if len(parts) != 3 or not parts[0]:
                return None
            return TraceContext(parts[0], parts[1],
                                sampled=parts[2] != "0")
        if isinstance(v, dict) and "traceId" in v:  # legacy dict form
            return TraceContext(str(v["traceId"]),
                                str(v.get("spanId", "0")),
                                sampled=bool(v.get("sampled", True)))
        return None

    def __repr__(self) -> str:  # debugging aid only
        return (f"TraceContext({self.trace_id}/{self.span_id}"
                f"{' sampled' if self.sampled else ''})")


class _Config:
    __slots__ = ("sample", "slow_ms", "capacity")

    def __init__(self):
        self.sample = int(os.environ.get("FLUID_TRACE_SAMPLE", "0"))
        self.slow_ms = float(os.environ.get("FLUID_TRACE_SLOW_MS", "50"))
        self.capacity = 4096


_cfg = _Config()
# Fleet identity: every exported span names the process that recorded
# it, so the observatory's cross-process join can tell alfred's ingest
# span from the tpu-deli worker's ticket span. Workers set this at
# startup (server/main.py); unset falls back to the OS pid.
_process_name: Optional[str] = None
# Sampling counters are PER SITE FAMILY (op roots vs stage roots): one
# shared modulo counter phase-locks against a steady submit->flush
# cadence and can systematically over- or never-sample one family.
_op_counter = itertools.count()       # CPython-atomic
_root_counter = itertools.count()
_span_seq = itertools.count(1)        # process-unique span id suffix
# Trace ids are a random process prefix + a counter: unique in-process,
# collision-improbable across processes, and ~10x cheaper than an
# os.urandom syscall per trace (the sample=1 overhead budget's largest
# single line item before this).
_trace_prefix = os.urandom(4).hex()
_trace_seq = itertools.count(1)
# Wall-clock epoch for span timestamps derived from perf_counter once:
# one clock read per span instead of two.
_epoch = time.time() - time.perf_counter()


def configure(sample: Optional[int] = None,
              slow_ms: Optional[float] = None,
              capacity: Optional[int] = None) -> None:
    """Set the sampling rate (0 = tracing off, 1 = every op, N = 1-in-N),
    the always-record slow threshold, and/or the recorder capacity."""
    if sample is not None:
        _cfg.sample = int(sample)
    if slow_ms is not None:
        _cfg.slow_ms = float(slow_ms)
    if capacity is not None:
        recorder.resize(int(capacity))


def enabled() -> bool:
    return _cfg.sample > 0


def set_process_name(name: Optional[str]) -> None:
    """Tag every span exported from this process (fleet join identity)."""
    global _process_name
    _process_name = name


def process_name() -> str:
    return _process_name or f"pid{os.getpid()}"


def _new_trace_id() -> str:
    return f"{_trace_prefix}{next(_trace_seq) & 0xFFFFFFFFFF:010x}"


def _new_span_id() -> str:
    return f"{next(_span_seq):x}"


def _op_sampled_now() -> bool:
    return (next(_op_counter) % _cfg.sample) == 0


def _root_sampled_now() -> bool:
    return (next(_root_counter) % _cfg.sample) == 0


# Ring-entry layout: spans live as flat tuples on the write path (one
# allocation, no dict churn inside the <2% overhead budget) and
# materialize as dicts only when read.
_SPAN_FIELDS = ("name", "ts", "dur", "tid", "trace_id", "span_id",
                "parent_id", "attrs", "sampled")


class FlightRecorder:
    """Bounded ring buffer of finished spans. The write path holds the
    lock only to bump an index and store one reference (no allocation,
    no ordering work); overflow overwrites the oldest entry — a flight
    recorder keeps the last N seconds, not the full history."""

    def __init__(self, capacity: int = 4096):
        self._lock = threading.Lock()
        self.resize(capacity)

    def resize(self, capacity: int) -> None:
        with self._lock:
            self._buf: List[Optional[tuple]] = [None] * max(capacity, 1)
            self._next = 0
            self.dropped = 0  # overwritten since last drain

    def record(self, span: tuple) -> None:
        """span: a tuple in _SPAN_FIELDS order."""
        with self._lock:
            i = self._next % len(self._buf)
            if self._buf[i] is not None:
                self.dropped += 1
            self._buf[i] = span
            self._next += 1

    def _ordered(self) -> List[tuple]:
        n = len(self._buf)
        start = self._next % n
        return self._buf[start:] + self._buf[:start]

    def snapshot(self) -> List[dict]:
        """Recorded spans as dicts, oldest first, without clearing."""
        with self._lock:
            ordered = self._ordered()
        return [dict(zip(_SPAN_FIELDS, s)) for s in ordered
                if s is not None]

    def drain(self) -> List[dict]:
        """Snapshot + clear (the /trace endpoint's read)."""
        with self._lock:
            ordered = self._ordered()
            self._buf = [None] * len(self._buf)
            self._next = 0
            self.dropped = 0
        return [dict(zip(_SPAN_FIELDS, s)) for s in ordered
                if s is not None]

    def __len__(self) -> int:
        with self._lock:
            return sum(1 for s in self._buf if s is not None)


recorder = FlightRecorder()

# The ambient span (for parent resolution across nested stages within a
# thread/task); explicit parent= always wins.
_current: contextvars.ContextVar[Optional["Span"]] = \
    contextvars.ContextVar("fluid_trace_span", default=None)

# Pending op-root handoff between a client-local edit (mergetree/client)
# and the driver submit that ships the resulting op — same thread,
# different layers, no shared call signature.
_tls = threading.local()


class _NullSpan:
    """Shared no-op for the tracing-off path (and unsampled fast exits)."""

    __slots__ = ()
    ctx = None

    def end(self, **_attrs) -> None:
        pass

    cancel = end

    def set(self, **_attrs) -> None:
        pass

    def __enter__(self) -> "_NullSpan":
        return self

    def __exit__(self, *_exc) -> None:
        pass


NULL_SPAN = _NullSpan()


class _HistTimer:
    """Tracing is off but the stage histogram must still fill (the SLO
    and /metrics.prom surfaces are always on): a bare timer that feeds
    counters.observe on end."""

    __slots__ = ("_hist", "_t0", "_done")
    ctx = None

    def __init__(self, hist: str):
        self._hist = hist
        self._t0 = time.perf_counter()
        self._done = False

    def end(self, **_attrs) -> None:
        if self._done:
            return
        self._done = True
        counters.observe(self._hist,
                         (time.perf_counter() - self._t0) * 1000.0)

    def cancel(self, **_attrs) -> None:
        self.end()

    def set(self, **_attrs) -> None:
        pass

    def __enter__(self) -> "_HistTimer":
        return self

    def __exit__(self, *_exc) -> None:
        self.end()


class Span:
    """One timed operation. End via ``end()`` or context-manager exit
    (fluidlint's SPAN_LEAK rule enforces one of the two on op-pipeline
    modules). Recording happens at end: when the context is sampled, or
    when the duration crosses the slow threshold (always-sample-on-slow).
    """

    __slots__ = ("name", "ctx", "attrs", "hist", "_t0",
                 "_done", "_token")

    def __init__(self, name: str, ctx: TraceContext,
                 hist: Optional[str] = None,
                 attrs: Optional[Dict[str, Any]] = None):
        self.name = name
        self.ctx = ctx
        self.hist = hist
        self.attrs = attrs
        self._t0 = time.perf_counter()
        self._done = False
        self._token = None

    def set(self, **attrs) -> None:
        if self.attrs is None:
            self.attrs = {}
        self.attrs.update(attrs)

    def end(self, **attrs) -> None:
        if self._done:
            return
        self._done = True
        dur_ms = (time.perf_counter() - self._t0) * 1000.0
        if attrs:
            self.set(**attrs)
        if self._token is not None:
            _current.reset(self._token)
            self._token = None
        record = self.ctx.sampled or dur_ms >= _cfg.slow_ms
        if self.hist is not None:
            counters.observe(self.hist, dur_ms,
                             trace_id=self.ctx.trace_id if record
                             else None)
        if record:
            # Tuple in _SPAN_FIELDS order; ts/dur in µs (chrome
            # convention), sampled=False marks a slow-capture.
            recorder.record((
                self.name, (_epoch + self._t0) * 1e6, dur_ms * 1000.0,
                threading.get_ident() & 0xFFFF, self.ctx.trace_id,
                self.ctx.span_id, self.ctx.parent_id, self.attrs or {},
                self.ctx.sampled))

    def cancel(self, **attrs) -> None:
        self.end(error=True, **attrs)

    def __enter__(self) -> "Span":
        self._token = _current.set(self)
        return self

    def __exit__(self, exc_type, _exc, _tb) -> None:
        if exc_type is not None:
            self.cancel()
        else:
            self.end()


def current() -> Optional[TraceContext]:
    sp = _current.get()
    return sp.ctx if sp is not None else None


def span(name: str, parent: Optional[TraceContext] = None,
         root: bool = False, hist: Optional[str] = None,
         **attrs):
    """Open a span.

    parent: explicit wire/context parent (wins over the ambient span).
    root:   with no parent anywhere, mint a fresh head-sampled trace
            (stage entry points: the serving flush, scribe summarize);
            without root, no-parent means no span (per-op stages only
            trace ops that carry a context).
    hist:   also feed this latency histogram (always, even tracing-off).
    """
    if not enabled():
        return _HistTimer(hist) if hist is not None else NULL_SPAN
    ctx = parent
    if ctx is None:
        cur = _current.get()
        ctx = cur.ctx if cur is not None else None
    if ctx is None:
        if not root:
            return _HistTimer(hist) if hist is not None else NULL_SPAN
        ctx = TraceContext(_new_trace_id(), _new_span_id(),
                           sampled=_root_sampled_now())
    else:
        ctx = TraceContext(ctx.trace_id, _new_span_id(),
                           parent_id=ctx.span_id, sampled=ctx.sampled)
    # Unsampled spans still time themselves: always-sample-on-slow needs
    # the duration to decide at end().
    return Span(name, ctx, hist=hist, attrs=attrs or None)


def record_span(name: str, parent: Optional[TraceContext],
                t0: float, t1: float, wall0: Optional[float] = None,
                hist: Optional[str] = None, **attrs) -> None:
    """Record a pre-measured interval (perf_counter endpoints) as a
    finished span — for stages measured across call sites (the deferred
    readback join, per-op ticket stamps inside a batched window)."""
    dur_ms = (t1 - t0) * 1000.0
    # Exemplars only for spans that actually land in the recorder (same
    # gate as Span.end): a bucket exemplar whose trace never appears in
    # /trace would dangle.
    will_record = (enabled() and parent is not None
                   and (parent.sampled or dur_ms >= _cfg.slow_ms))
    if hist is not None:
        counters.observe(hist, dur_ms,
                         trace_id=parent.trace_id if will_record
                         else None)
    if not will_record:
        return
    wall_start = wall0 if wall0 is not None else _epoch + t0
    recorder.record((
        name, wall_start * 1e6, dur_ms * 1000.0,
        threading.get_ident() & 0xFFFF, parent.trace_id,
        _new_span_id(), parent.span_id, attrs or {}, parent.sampled))


# -- op-root handoff (client edit -> driver submit) -------------------------

# Parked between edit and submit: a context, None (no decision yet), or
# this sentinel — the edit's sampler draw said NO, and the submit
# boundary must respect that instead of rolling the dice again (a second
# draw would double the effective sample rate for edited ops and mint
# driver-rooted traces with the client.local_edit span missing).
_UNSAMPLED = object()


def new_op_trace() -> Optional[TraceContext]:
    """Head-sample a fresh root for one client-local op. The decision
    (context or decided-unsampled) is parked thread-locally so the
    driver submit that ships the op (same thread, layers apart) adopts
    it via take_op_trace()/ensure_op_context()."""
    if not enabled():
        return None
    if not _op_sampled_now():
        _tls.op_ctx = _UNSAMPLED
        return None
    ctx = TraceContext(_new_trace_id(), _new_span_id(), sampled=True)
    _tls.op_ctx = ctx
    return ctx


def _take_op_decision():
    decision = getattr(_tls, "op_ctx", None)
    _tls.op_ctx = None
    return decision


def take_op_trace() -> Optional[TraceContext]:
    """Adopt (and clear) the pending op root, if the edit minted one."""
    decision = _take_op_decision()
    return None if decision is _UNSAMPLED else decision


def ensure_op_context() -> Optional[TraceContext]:
    """The submit boundary's context resolution: the edit's parked
    decision (context OR decided-unsampled), else the ambient span, else
    a freshly head-sampled root (ops that enter at the driver without a
    client-edit span — protocol messages, direct submits). None when
    tracing is off or the sampler skips."""
    decision = _take_op_decision()
    if decision is _UNSAMPLED:
        return None
    if decision is not None:
        return decision
    ctx = current()
    if ctx is not None:
        return ctx
    if not enabled() or not _op_sampled_now():
        return None
    return TraceContext(_new_trace_id(), _new_span_id(), sampled=True)


def root_context() -> Optional[TraceContext]:
    """Head-sample a fresh root for a system-initiated message (ghost
    evictions, scribe acks outside any ambient span): these enter the
    raw log without a client edit, and an unstamped system message is a
    hole in the fleet-joined timeline. Uses the stage-root sampling
    counter so op sampling phase stays undisturbed."""
    if not enabled() or not _root_sampled_now():
        return None
    return TraceContext(_new_trace_id(), _new_span_id(), sampled=True)


# -- wire propagation -------------------------------------------------------

def stamp_message(msg, ctx: Optional[TraceContext]) -> None:
    """Attach the context to a DocumentMessage's metadata (no-op when
    tracing is off, ctx is None, or the message is already stamped)."""
    if ctx is None:
        return
    meta = msg.metadata
    if meta is None:
        msg.metadata = {TRACE_KEY: ctx.to_wire()}
    elif isinstance(meta, dict) and TRACE_KEY not in meta:
        meta[TRACE_KEY] = ctx.to_wire()


def message_context(msg) -> Optional[TraceContext]:
    """The wire context a (Document|SequencedDocument)Message carries."""
    if not enabled():
        return None
    meta = getattr(msg, "metadata", None)
    if isinstance(meta, dict):
        return TraceContext.from_wire(meta.get(TRACE_KEY))
    return None


def first_message_context(messages) -> Optional[TraceContext]:
    """The first stamped context in a batch (window/boxcar parents)."""
    if not enabled():
        return None
    for msg in messages:
        ctx = message_context(msg)
        if ctx is not None:
            return ctx
    return None


# -- export -----------------------------------------------------------------

def chrome_trace(spans: Optional[List[dict]] = None) -> dict:
    """Chrome trace-event JSON (the ``/trace`` payload): one complete
    ("ph": "X") event per span; perfetto and chrome://tracing open it
    as-is. Span identity rides in args so a capture can be re-grouped
    by trace_id offline; process identity (pid + args.proc) lets the
    fleet observatory join rings drained from several workers into one
    timeline without ambiguity."""
    events = []
    pid = os.getpid()
    proc = process_name()
    for s in (recorder.snapshot() if spans is None else spans):
        events.append({
            "name": s["name"],
            "cat": "slow" if not s.get("sampled", True) else "fluid",
            "ph": "X",
            "ts": s["ts"],
            "dur": s["dur"],
            "pid": s.get("pid", pid),
            "tid": s.get("tid", 0),
            "args": dict(s.get("attrs") or {},
                         trace_id=s["trace_id"], span_id=s["span_id"],
                         parent_id=s.get("parent_id"),
                         proc=s.get("proc", proc)),
        })
    return {"traceEvents": events, "displayTimeUnit": "ms"}


def chrome_trace_json(spans: Optional[List[dict]] = None) -> str:
    return json.dumps(chrome_trace(spans))


def reset() -> None:
    """Test isolation only: drop recorded spans and disable tracing."""
    global _process_name
    _cfg.sample = 0
    _cfg.slow_ms = 50.0
    recorder.resize(len(recorder._buf))
    _tls.op_ctx = None
    _process_name = None
