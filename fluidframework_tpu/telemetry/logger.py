"""Telemetry logger hierarchy + performance-event spans.

Capability parity with reference packages/utils/telemetry-utils/src/
{logger.ts:238-356, debugLogger.ts:18, mockLogger.ts:14}: every layer takes
an ITelemetryLogger; ChildLogger namespaces + merges static properties;
MultiSinkLogger fans events to several sinks; PerformanceEvent wraps an
operation in start/end/cancel events with duration; MockLogger records
events for test assertions (see telemetry/mock.py).

Events are plain dicts with at least {"category", "eventName"}; errors are
folded in via tagged properties the way logger.ts prepareErrorObject does.
"""

from __future__ import annotations

import logging
import time
import traceback
from typing import Any, Dict, List, Optional, Sequence

# Event categories (reference ITelemetryBaseEvent.category).
GENERIC = "generic"
ERROR = "error"
PERFORMANCE = "performance"


class TelemetryLogger:
    """Base logger: namespacing + property merging + error folding
    (reference TelemetryLogger, logger.ts:238)."""

    EVENT_NAME_SEPARATOR = ":"

    def __init__(self, namespace: Optional[str] = None,
                 properties: Optional[Dict[str, Any]] = None):
        self.namespace = namespace
        self.properties = dict(properties or {})

    # -- sink --------------------------------------------------------------
    def send(self, event: Dict[str, Any]) -> None:
        raise NotImplementedError

    # -- api ---------------------------------------------------------------
    def send_telemetry_event(self, event: Dict[str, Any],
                             error: Optional[BaseException] = None) -> None:
        self._send(dict(event, category=event.get("category", GENERIC)),
                   error)

    def send_error_event(self, event: Dict[str, Any],
                         error: Optional[BaseException] = None) -> None:
        self._send(dict(event, category=ERROR), error)

    def send_performance_event(self, event: Dict[str, Any],
                               error: Optional[BaseException] = None) -> None:
        self._send(dict(event, category=PERFORMANCE), error)

    def debug_assert(self, condition: bool,
                     event: Optional[Dict[str, Any]] = None) -> None:
        if not condition:
            self.send_error_event(dict(event or {},
                                       eventName="DebugAssert"))

    # -- internals ---------------------------------------------------------
    def prepare_event(self, event: Dict[str, Any]) -> Dict[str, Any]:
        """Merge static properties and apply this logger's namespace prefix
        (reference TelemetryLogger.prepareEvent); each ChildLogger in the
        chain prepares again, so namespaces accumulate root-ward."""
        prepared = dict(self.properties)
        prepared.update(event)
        if self.namespace:
            prepared["eventName"] = (self.namespace
                                     + self.EVENT_NAME_SEPARATOR
                                     + prepared.get("eventName", ""))
        return prepared

    def _send(self, event: Dict[str, Any],
              error: Optional[BaseException]) -> None:
        if error is not None:
            event = dict(event)
            event.setdefault("error", str(error))
            event.setdefault("errorType", type(error).__name__)
            tb = getattr(error, "__traceback__", None)
            if tb is not None:
                event.setdefault(
                    "stack", "".join(traceback.format_tb(tb))[-2000:])
        self.send(event)


class DebugLogger(TelemetryLogger):
    """Routes events to the stdlib ``logging`` tree (the reference routes to
    the npm `debug` package; logging is the Python moral equivalent).
    Error-category events escalate to logging.ERROR."""

    def __init__(self, namespace: str = "fluid",
                 properties: Optional[Dict[str, Any]] = None):
        super().__init__(None, properties)
        self._log = logging.getLogger(namespace)

    @staticmethod
    def create(namespace: str = "fluid",
               properties: Optional[Dict[str, Any]] = None,
               ) -> "DebugLogger":
        return DebugLogger(namespace, properties)

    def send(self, event: Dict[str, Any]) -> None:
        event = self.prepare_event(event)
        level = (logging.ERROR if event.get("category") == ERROR
                 else logging.DEBUG)
        payload = {k: v for k, v in event.items() if k != "eventName"}
        self._log.log(level, "%s %s", event.get("eventName", ""), payload)


class ChildLogger(TelemetryLogger):
    """Namespaced child over a parent logger (logger.ts ChildLogger.create):
    events flow to the root sink with namespaces joined by ':'."""

    def __init__(self, base: TelemetryLogger, namespace: Optional[str],
                 properties: Optional[Dict[str, Any]] = None):
        super().__init__(namespace, properties)
        self.base = base

    @staticmethod
    def create(base: Optional[TelemetryLogger],
               namespace: Optional[str] = None,
               properties: Optional[Dict[str, Any]] = None) -> "ChildLogger":
        return ChildLogger(base or DebugLogger(), namespace, properties)

    def send(self, event: Dict[str, Any]) -> None:
        self.base.send(self.prepare_event(event))


class MultiSinkLogger(TelemetryLogger):
    """Fans each event out to every registered sink (logger.ts:318)."""

    def __init__(self, namespace: Optional[str] = None):
        super().__init__(namespace)
        self.loggers: List[TelemetryLogger] = []

    def add_logger(self, logger: Optional[TelemetryLogger]) -> None:
        if logger is not None:
            self.loggers.append(logger)

    def send(self, event: Dict[str, Any]) -> None:
        event = self.prepare_event(event)
        for logger in self.loggers:
            logger.send(event)


class PerformanceEvent:
    """Start/end/cancel span with duration, mirroring logger.ts:356.

    Usage::

        with PerformanceEvent.timed_event(logger, {"eventName": "Load"}) as e:
            ...; e.report_progress({"phase": "snapshot"})
    """

    def __init__(self, logger: TelemetryLogger, event: Dict[str, Any],
                 emit_start: bool = True):
        self.logger = logger
        self.event = dict(event)
        self.start_time = time.perf_counter()
        self._reported = False
        if emit_start:
            self._report("start")

    @staticmethod
    def start(logger: TelemetryLogger, event: Dict[str, Any]
              ) -> "PerformanceEvent":
        return PerformanceEvent(logger, event)

    @staticmethod
    def timed_event(logger: TelemetryLogger, event: Dict[str, Any]
                    ) -> "PerformanceEvent":
        return PerformanceEvent(logger, event, emit_start=False)

    @property
    def duration_ms(self) -> float:
        return (time.perf_counter() - self.start_time) * 1000.0

    def report_progress(self, props: Optional[Dict[str, Any]] = None,
                        event_name_suffix: str = "update") -> None:
        self._report(event_name_suffix, props)

    def end(self, props: Optional[Dict[str, Any]] = None) -> None:
        if not self._reported:
            self._reported = True
            self._report("end", props)

    def cancel(self, props: Optional[Dict[str, Any]] = None,
               error: Optional[BaseException] = None) -> None:
        if not self._reported:
            self._reported = True
            self._report("cancel", props, error)

    def __enter__(self) -> "PerformanceEvent":
        return self

    def __exit__(self, exc_type, exc, tb) -> None:
        if exc is not None:
            self.cancel(error=exc)
        else:
            self.end()

    def _report(self, suffix: str,
                props: Optional[Dict[str, Any]] = None,
                error: Optional[BaseException] = None) -> None:
        event = dict(self.event)
        if props:
            event.update(props)
        event["eventName"] = f"{event.get('eventName', '')}_{suffix}"
        if suffix != "start":
            event["duration"] = self.duration_ms
        self.logger.send_performance_event(event, error)


class OpRoundTripTelemetry:
    """Measures local-op submit -> ack round trips + sequence-number lag
    (reference container-runtime/src/connectionTelemetry.ts). Sampled: one
    in-flight op is tracked at a time; the next sample starts after ack."""

    SAMPLE_EVERY = 100

    def __init__(self, client_id_fn, logger: TelemetryLogger):
        self._client_id_fn = client_id_fn
        self.logger = logger
        self._tracked_seq: Optional[int] = None  # client sequence number
        self._tracked_start = 0.0
        self._since_sample = 0

    def on_submit(self, client_seq: int) -> None:
        self._since_sample += 1
        if (self._tracked_seq is None
                and self._since_sample >= self.SAMPLE_EVERY):
            self._tracked_seq = client_seq
            self._tracked_start = time.perf_counter()
            self._since_sample = 0

    def on_sequenced(self, msg) -> None:
        if (self._tracked_seq is not None
                and msg.client_id == self._client_id_fn()
                and msg.client_sequence_number == self._tracked_seq):
            self.logger.send_performance_event({
                "eventName": "OpRoundtripTime",
                "sequenceNumber": msg.sequence_number,
                "clientSequenceNumber": msg.client_sequence_number,
                "duration": (time.perf_counter()
                             - self._tracked_start) * 1000.0,
            })
            self._tracked_seq = None
