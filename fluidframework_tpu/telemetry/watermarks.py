"""Per-tier watermark table: Kafka-style consumer lag for the whole
pipeline (docs/observability.md v3).

Every tier stamps a per-(tenant, partition) watermark as work passes
through it:

  raw_end       newest raw-log offset appended (records domain)
  raw_ingested  raw-log offset committed by the sequencer tier
  ticketed      ops assigned sequence numbers (ops domain)
  broadcast     sequenced ops delivered to rooms
  summarized    ops covered by a committed summary
  catchup       ops covered by a published catch-up artifact
  adopted       ops covered by a reader's adopted artifact

Lag is a *difference of watermarks* along a declared edge — never a
per-op measurement — so the steady-state cost is O(partitions) state
and the hot paths pay at most one dict high-water update:

  ingest     raw_end  - raw_ingested   (records; matches partition_stats)
  broadcast  ticketed - broadcast      (ops)
  summarize  ticketed - summarized     (ops)
  catchup    ticketed - catchup        (ops)
  adopt      catchup  - adopted        (ops)

The downstream tiers hang off `ticketed` as parallel consumers of the
sequenced stream (the consumer-group shape), except `adopt`, which
chains off `catchup` (readers can only adopt what was published).

Replay safety: chaos restarts replay the uncommitted raw window, so a
cumulative "advance by batch size" counter would double-count. The
ops-domain tiers therefore keep a per-document sequence-number
high-water mark (`advance_doc`): replayed ops fold in max(0, seq -
prev) = 0, making every watermark exact and run-twice deterministic
under partition crashes. Offset-domain tiers are plain monotonic
maxima for the same reason.

Export rides the existing cardinality guard: `export_gauges()` writes
`lag.<edge>.p<N>` through counters.bounded — surfaced by the monitor
as `fluid_lag_*` — plus a per-edge total and an op-age gauge (seconds
since the downstream tier last advanced while lag is nonzero; 0 when
caught up). The clock is injectable so the virtual-clock capacity soak
can grade ages deterministically.
"""

from __future__ import annotations

import threading
import time
from typing import Callable, Dict, Optional, Tuple

from . import counters

# -- tier names (watermark producers) ----------------------------------
RAW_END = "raw_end"
RAW_INGESTED = "raw_ingested"
TICKETED = "ticketed"
BROADCAST = "broadcast"
SUMMARIZED = "summarized"
CATCHUP = "catchup"
ADOPTED = "adopted"

TIERS = (RAW_END, RAW_INGESTED, TICKETED, BROADCAST, SUMMARIZED,
         CATCHUP, ADOPTED)

# Tiers whose watermark is a sum of per-document sequence-number
# high-water marks (replay-safe under partition-crash chaos).
_DOC_TIERS = frozenset((TICKETED, BROADCAST, SUMMARIZED, CATCHUP,
                        ADOPTED))

# -- lag edges: (edge name, upstream tier, downstream tier) ------------
LAG_EDGES = (
    ("ingest", RAW_END, RAW_INGESTED),
    ("broadcast", TICKETED, BROADCAST),
    ("summarize", TICKETED, SUMMARIZED),
    ("catchup", TICKETED, CATCHUP),
    ("adopt", CATCHUP, ADOPTED),
)

_Key = Tuple[str, str, int]  # (tier, tenant, partition)


class WatermarkTable:
    """Thread-safe watermark store; one process-global instance below."""

    def __init__(self, clock: Callable[[], float] = time.monotonic):
        self._lock = threading.Lock()
        self._clock = clock
        self._marks: Dict[_Key, float] = {}
        # Per-doc high-water marks backing the ops-domain tiers.
        self._docs: Dict[_Key, Dict[str, int]] = {}
        # Clock time of the last advance per (tier, tenant, partition):
        # the op-age signal while an edge is behind.
        self._touched: Dict[_Key, float] = {}

    # -- producers -----------------------------------------------------
    def advance(self, tier: str, partition: int, value: float,
                tenant: str = "local") -> None:
        """Monotonic watermark for offset-domain tiers (raw_end /
        raw_ingested): replays re-present old offsets and fold to 0."""
        key = (tier, tenant, int(partition))
        value = float(value)
        with self._lock:
            if value > self._marks.get(key, float("-inf")):
                self._marks[key] = value
                self._touched[key] = self._clock()

    def advance_doc(self, tier: str, partition: int, document_id: str,
                    seq: int, tenant: str = "local") -> None:
        """Ops-domain watermark: fold this document's sequence-number
        high-water into the partition aggregate. Replayed (already
        counted) sequence numbers contribute nothing."""
        key = (tier, tenant, int(partition))
        seq = int(seq)
        with self._lock:
            docs = self._docs.get(key)
            if docs is None:
                docs = self._docs[key] = {}
            prev = docs.get(document_id, 0)
            if seq > prev:
                docs[document_id] = seq
                self._marks[key] = self._marks.get(key, 0.0) + (seq - prev)
                self._touched[key] = self._clock()

    # -- readers -------------------------------------------------------
    def mark(self, tier: str, partition: int,
             tenant: str = "local") -> float:
        with self._lock:
            return self._marks.get((tier, tenant, int(partition)), 0.0)

    def lags(self) -> Dict[str, Dict[Tuple[str, int], float]]:
        """Per-edge, per-(tenant, partition) lag. A partition appears
        when EITHER end of the edge has stamped it; a missing
        downstream mark reads as 0 (nothing consumed yet)."""
        with self._lock:
            out: Dict[str, Dict[Tuple[str, int], float]] = {}
            for edge, up, down in LAG_EDGES:
                per: Dict[Tuple[str, int], float] = {}
                for (tier, tenant, part), val in self._marks.items():
                    if tier != up:
                        continue
                    got = self._marks.get((down, tenant, part), 0.0)
                    per[(tenant, part)] = max(0.0, val - got)
                out[edge] = per
            return out

    def total_lag(self, edge: str) -> float:
        per = self.lags().get(edge, {})
        return float(sum(per.values()))

    def ages(self, now: Optional[float] = None) -> Dict[str, float]:
        """Seconds each edge has been behind: 0 when lag is 0, else
        clock-now minus the downstream tier's last advance (or the
        upstream's first stamp if the consumer never ran)."""
        lags = self.lags()
        with self._lock:
            if now is None:
                now = self._clock()
            out: Dict[str, float] = {}
            for edge, up, down in LAG_EDGES:
                worst = 0.0
                for (tenant, part), lag in lags[edge].items():
                    if lag <= 0:
                        continue
                    t0 = self._touched.get(
                        (down, tenant, part),
                        self._touched.get((up, tenant, part), now))
                    worst = max(worst, now - t0)
                out[edge] = worst
            return out

    # -- export --------------------------------------------------------
    def export_gauges(self) -> None:
        """Write the lag surface through the cardinality guard:
        lag.<edge>.p<N> per partition (capped at the bounded() family
        limit), lag.<edge>.total, and lag_age_s.<edge>. The monitor's
        /metrics.prom pass renders these as fluid_lag_* gauges."""
        lags = self.lags()
        for edge, per in lags.items():
            for (_tenant, part), lag in sorted(per.items()):
                counters.gauge(
                    counters.bounded(f"lag.{edge}", f"p{part}"), lag)
            counters.gauge(f"lag.{edge}.total",
                           float(sum(per.values())))
        for edge, age in self.ages().items():
            counters.gauge(f"lag_age_s.{edge}", age)

    def snapshot(self) -> dict:
        """JSON-safe dump for /health and /fleet/lag: raw tier marks,
        per-edge lags keyed '<tenant>/p<partition>', totals, ages."""
        lags = self.lags()
        ages = self.ages()
        with self._lock:
            tiers: Dict[str, Dict[str, float]] = {}
            for (tier, tenant, part), val in sorted(self._marks.items()):
                tiers.setdefault(tier, {})[f"{tenant}/p{part}"] = val
        edges = {}
        for edge, per in lags.items():
            edges[edge] = {
                "perPartition": {f"{tenant}/p{part}": lag
                                 for (tenant, part), lag
                                 in sorted(per.items())},
                "total": float(sum(per.values())),
                "ageS": ages.get(edge, 0.0),
            }
        return {"tiers": tiers, "lags": edges}

    # -- lifecycle -----------------------------------------------------
    def set_clock(self, clock: Callable[[], float]) -> None:
        with self._lock:
            self._clock = clock

    def reset(self) -> None:
        with self._lock:
            self._marks.clear()
            self._docs.clear()
            self._touched.clear()
            self._clock = time.monotonic


# Process-global table: tiers stamp it directly, the monitor and the
# fleet observatory read it. Tests isolate via reset().
table = WatermarkTable()

advance = table.advance
advance_doc = table.advance_doc
lags = table.lags
total_lag = table.total_lag
ages = table.ages
export_gauges = table.export_gauges
snapshot = table.snapshot
set_clock = table.set_clock


def reset() -> None:
    table.reset()
