"""Runtime lockset verification — fluidlint v3's dynamic half.

The static race detector (analysis/concurrency_model.py) PROVES the
lock discipline it can see and TRUSTS the annotations it cannot
(``# fluidlint: guarded-by=…``, the ``disable``d racy-by-design
probes). This module closes the loop the way ``JitRetraceProbe`` closes
the RETRACE_HAZARD loop: a debug-mode monkey-wrap asserts the
statically inferred (or explicitly declared) locksets while the real
code runs under the soak/chaos suites, so the model and the code cannot
silently drift apart.

Usage::

    from fluidframework_tpu.testing.lockcheck import instrument

    check = instrument(store, {"_deferred_frees": "_guard_lock",
                               "_extract_guards": "_guard_lock"})
    ...  # drive the store, including its worker threads
    check.assert_clean()   # raises listing every unguarded access
    check.uninstrument()

``instrument`` wraps the named lock attributes in ownership-tracking
proxies (``acquire``/``release``/``with`` all count, per thread,
re-entrantly) and patches the class's ``__getattribute__``/
``__setattr__`` so every touch of a guarded attribute checks that the
declared lock is held by the touching thread. Violations are recorded
(or raised immediately with ``strict=True``) with the offending
attribute, thread, and call site.

``static_guards(cls)`` derives the guard map from the single-module
concurrency model, so a test can assert exactly what fluidlint
inferred. Everything here is import-cheap and debug-only: production
code never imports this module.
"""

from __future__ import annotations

import threading
import traceback
from dataclasses import dataclass
from typing import Dict, List, Optional, Type

_GUARDS_SLOT = "_lockcheck_registry"
_PATCHED: Dict[type, dict] = {}  # class -> {orig get/set, refcount}


class LockDisciplineError(AssertionError):
    """Raised by strict mode / assert_clean on an unguarded access."""


@dataclass
class Violation:
    cls: str
    attr: str
    lock: str
    op: str        # "get" | "set"
    thread: str
    site: str      # "file.py:123 in caller"

    def render(self) -> str:
        return (f"{self.cls}.{self.attr} {self.op} on thread "
                f"{self.thread} without holding {self.lock} ({self.site})")


class TrackedLock:
    """Ownership-tracking proxy over a Lock/RLock/Condition: records
    which threads currently hold it (re-entrantly) while delegating the
    actual blocking to the wrapped primitive."""

    def __init__(self, inner):
        self._inner = inner
        self._holds: Dict[int, int] = {}
        self._meta = threading.Lock()

    # -- the lock protocol -------------------------------------------------
    def acquire(self, *args, **kwargs) -> bool:
        ok = self._inner.acquire(*args, **kwargs)
        if ok:
            self._note(+1)
        return ok

    def release(self) -> None:
        self._note(-1)
        self._inner.release()

    def __enter__(self):
        self.acquire()
        return self

    def __exit__(self, *exc) -> None:
        self.release()

    def locked(self) -> bool:
        return self._inner.locked()

    def _note(self, delta: int) -> None:
        ident = threading.get_ident()
        with self._meta:
            n = self._holds.get(ident, 0) + delta
            if n <= 0:
                self._holds.pop(ident, None)
            else:
                self._holds[ident] = n

    def held_by_current_thread(self) -> bool:
        return self._holds.get(threading.get_ident(), 0) > 0

    # Condition passthrough (wait/notify keep working when a Condition
    # is wrapped; ownership still tracks through acquire/release).
    def __getattr__(self, name):
        return getattr(self._inner, name)


class LockCheck:
    """One instrumented instance's registry: guard map, wrapped locks,
    recorded violations."""

    def __init__(self, obj, guards: Dict[str, str], strict: bool):
        self.obj = obj
        self.guards = dict(guards)
        self.strict = strict
        self.violations: List[Violation] = []
        self._checking = threading.local()
        self._locks: Dict[str, TrackedLock] = {}
        for lock_attr in sorted(set(guards.values())):
            inner = object.__getattribute__(obj, lock_attr)
            tracked = inner if isinstance(inner, TrackedLock) \
                else TrackedLock(inner)
            object.__setattr__(obj, lock_attr, tracked)
            self._locks[lock_attr] = tracked

    # -- the check ---------------------------------------------------------
    def check(self, attr: str, op: str) -> None:
        if getattr(self._checking, "active", False):
            return  # re-entrant introspection during recording
        lock_attr = self.guards[attr]
        tracked = self._locks[lock_attr]
        if tracked.held_by_current_thread():
            return
        self._checking.active = True
        try:
            site = "<unknown>"
            # Last two frames are check() and the class wrapper; the
            # filename filter then lands on the ACCESSING frame itself
            # (not its caller) even if wrapper nesting changes.
            for frame in reversed(traceback.extract_stack(limit=8)[:-2]):
                if frame.filename != __file__:
                    site = (f"{frame.filename.rsplit('/', 1)[-1]}:"
                            f"{frame.lineno} in {frame.name}")
                    break
            v = Violation(cls=type(self.obj).__name__, attr=attr,
                          lock=lock_attr, op=op,
                          thread=threading.current_thread().name,
                          site=site)
            self.violations.append(v)
        finally:
            self._checking.active = False
        if self.strict:
            raise LockDisciplineError(v.render())

    # -- results -----------------------------------------------------------
    def assert_clean(self) -> None:
        if self.violations:
            lines = "\n  ".join(v.render() for v in self.violations)
            raise LockDisciplineError(
                f"{len(self.violations)} unguarded access(es):\n  {lines}")

    def uninstrument(self) -> None:
        """Restore the instance's plain locks and drop this instance
        from the class patch (the class unpatches with the last one)."""
        for lock_attr, tracked in self._locks.items():
            object.__setattr__(self.obj, lock_attr, tracked._inner)
        d = object.__getattribute__(self.obj, "__dict__")
        d.pop(_GUARDS_SLOT, None)
        _unpatch_class(type(self.obj))


def instrument(obj, guards: Optional[Dict[str, str]] = None, *,
               strict: bool = False) -> LockCheck:
    """Wrap ``obj`` so every access to a guarded attribute asserts its
    declared lock is held by the accessing thread.

    ``guards`` maps attribute name -> lock attribute name; omit it to
    use ``static_guards(type(obj))`` — the locksets fluidlint inferred.
    ``strict=True`` raises at the first violation instead of recording.
    """
    if guards is None:
        guards = static_guards(type(obj))
    if not guards:
        raise ValueError(
            f"no guards given and none inferred for {type(obj).__name__}")
    check = LockCheck(obj, guards, strict)
    object.__getattribute__(obj, "__dict__")[_GUARDS_SLOT] = check
    _patch_class(type(obj))
    return check


# -- class patching ----------------------------------------------------------


def _patch_class(cls: type) -> None:
    entry = _PATCHED.get(cls)
    if entry is not None:
        entry["refs"] += 1
        return
    orig_get = cls.__getattribute__
    orig_set = cls.__setattr__

    def checked_getattribute(self, name):
        if name != "__dict__":
            d = object.__getattribute__(self, "__dict__")
            reg = d.get(_GUARDS_SLOT)
            if reg is not None and name in reg.guards:
                reg.check(name, "get")
        return orig_get(self, name)

    def checked_setattr(self, name, value):
        d = object.__getattribute__(self, "__dict__")
        reg = d.get(_GUARDS_SLOT)
        if reg is not None and name in reg.guards:
            reg.check(name, "set")
        return orig_set(self, name, value)

    cls.__getattribute__ = checked_getattribute  # type: ignore[assignment]
    cls.__setattr__ = checked_setattr            # type: ignore[assignment]
    _PATCHED[cls] = {"get": orig_get, "set": orig_set, "refs": 1}


def _unpatch_class(cls: type) -> None:
    entry = _PATCHED.get(cls)
    if entry is None:
        return
    entry["refs"] -= 1
    if entry["refs"] <= 0:
        cls.__getattribute__ = entry["get"]  # type: ignore[assignment]
        cls.__setattr__ = entry["set"]       # type: ignore[assignment]
        del _PATCHED[cls]


# -- static-model bridge ------------------------------------------------------


_STATIC_GUARDS_CACHE: Dict[type, Dict[str, str]] = {}


def static_guards(cls: Type) -> Dict[str, str]:
    """attr -> lock-attr guard map fluidlint infers for ``cls`` from
    its defining module (single-module concurrency model): the shared
    attributes whose lockset intersection is exactly one same-class
    lock. The runtime wrap then asserts precisely what the static pass
    proved — drift in either direction fails a test. Memoized per
    class: the soak suites instrument per trial, and the model build
    (~1s for the sequencer module) is invariant within a process."""
    cached = _STATIC_GUARDS_CACHE.get(cls)
    if cached is not None:
        return dict(cached)
    import ast
    import inspect

    from ..analysis.callgraph import module_name_for_path
    from ..analysis.engine import ModuleContext, ProgramContext, _rel_path
    from pathlib import Path

    src_file = inspect.getsourcefile(cls)
    if src_file is None:  # pragma: no cover - C extension class
        return {}
    rel = _rel_path(Path(src_file))
    source = Path(src_file).read_text()
    ctx = ModuleContext(rel, source, ast.parse(source))
    model = ProgramContext([ctx]).concurrency()
    guards = model.inferred_guards(
        f"{module_name_for_path(rel)}:{cls.__name__}")
    _STATIC_GUARDS_CACHE[cls] = dict(guards)
    return guards
