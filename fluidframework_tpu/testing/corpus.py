"""Recorded-session corpora: capture, pin, replay.

The reference regression-tests against CAPTURED op logs, replaying them
and byte-comparing snapshots across versions (reference
packages/test/snapshots/src/replayMultipleFiles.ts:1, LFS corpus per its
README). This module is the TPU-native equivalent without external
data: multi-client sessions drive the REAL alfred websocket + REST
stack (server/tinylicious.py -> LocalServer lambda pipeline), the
sequenced op log is fetched back through alfred's own /deltas catch-up
route, checked in under tests/corpus/, and replayed channel-level with
pinned end-state digests — any cross-version drift in sequencing or op
application semantics breaks the pin.

Corpus file format (gzip JSON lines):
  line 0: header {"doc", "workload", "seed", "channel_type", ...}
  line 1..n: alfred /deltas rows (scriptorium delta records) in seq order
"""

from __future__ import annotations

import gzip
import hashlib
import json
import os
from typing import Any, Dict, List, Tuple

CORPUS_DIR = os.path.join(os.path.dirname(os.path.dirname(
    os.path.dirname(os.path.abspath(__file__)))), "tests", "corpus")

# MessageType values the replayer handles (protocol/messages.py); all
# other row types (leaves, noops, summary acks) only advance sequence
# numbers, which ride in on the next op row's seq.
_OP = "op"
_JOIN = "join"


def write_corpus(path: str, header: dict, rows: List[dict]) -> None:
    with gzip.open(path, "wt", encoding="utf-8") as f:
        f.write(json.dumps(header, sort_keys=True) + "\n")
        for row in rows:
            f.write(json.dumps(row, sort_keys=True) + "\n")


def read_corpus(path: str) -> Tuple[dict, List[dict]]:
    with gzip.open(path, "rt", encoding="utf-8") as f:
        lines = [line for line in f if line.strip()]
    return json.loads(lines[0]), [json.loads(ln) for ln in lines[1:]]


def make_channel(channel_type: str):
    if channel_type == "sequence":
        from ..dds.sequence import SharedString
        return SharedString("replay")
    if channel_type == "items":
        from ..dds.sequence import SharedNumberSequence
        return SharedNumberSequence("replay")
    if channel_type == "matrix":
        from ..dds.matrix import SharedMatrix
        return SharedMatrix("replay")
    if channel_type == "directory":
        from ..dds.directory import SharedDirectory
        return SharedDirectory("replay")
    raise ValueError(f"unknown corpus channel type {channel_type!r}")


def channel_state(channel_type: str, channel) -> Any:
    """Canonical end state for digesting/pinning."""
    if channel_type == "sequence":
        return {
            "text": channel.get_text(),
            "segments": [
                {k: v for k, v in e.items() if k != "text"}
                | {"text": e.get("text", "")}
                for e in channel.client.tree.snapshot_segments()],
        }
    if channel_type == "items":
        return channel.get_items()
    if channel_type == "matrix":
        return channel.extract()
    if channel_type == "directory":
        return channel.root.to_dict()
    raise ValueError(channel_type)


def digest(state: Any) -> str:
    return hashlib.sha256(
        json.dumps(state, sort_keys=True, default=str)
        .encode("utf-8")).hexdigest()


def channel_ops(header: dict, rows: List[dict],
                channel_address: str | None = None):
    """The canonical row walk: yields (contents, seq, ref_seq, ordinal,
    min_seq) for the channel's op rows, interning quorum ordinals from
    join rows exactly as a catching-up replica would. Every consumer of
    a corpus (replay, bulk conformance, bench) shares this one filter so
    op subsets can never drift apart."""
    channel_address = channel_address or header.get("channel", "text")
    ordinals: Dict[str, int] = {}

    def ordinal(client_id) -> int:
        if client_id is None:
            return -1
        if client_id not in ordinals:
            ordinals[client_id] = len(ordinals)
        return ordinals[client_id]

    for row in rows:
        mtype = row.get("type")
        if mtype == _JOIN:
            data = row.get("data")
            try:
                detail = json.loads(data) if isinstance(data, str) else data
                ordinal(detail.get("clientId"))
            except (ValueError, AttributeError):
                pass
            continue
        if mtype != _OP:
            continue
        contents = row.get("contents")
        if isinstance(contents, str):
            contents = json.loads(contents)
        if not isinstance(contents, dict):
            continue
        envelope = contents.get("contents")
        if not isinstance(envelope, dict) or \
                envelope.get("address") != channel_address:
            continue
        yield (envelope.get("contents"), row["sequence_number"],
               row["reference_sequence_number"],
               ordinal(row.get("client_id")),
               row.get("minimum_sequence_number"))


def apply_ops(channel, ops) -> None:
    """Apply materialized channel_ops tuples remote-side — the ONE
    corpus apply loop (replay and the bench's timed region share it)."""
    for contents, seq, ref_seq, ordinal, min_seq in ops:
        channel.process_core(contents, False, seq, ref_seq, ordinal,
                             min_seq)


def replay(header: dict, rows: List[dict],
           channel_address: str | None = None):
    """Replay a recorded log into a fresh replica channel: sequenced
    messages apply remote-side exactly as a catching-up client would.
    Returns the channel."""
    channel = make_channel(header["channel_type"])
    apply_ops(channel, channel_ops(header, rows, channel_address))
    return channel


def channel_digest(channel_type: str, channel) -> str:
    return digest(channel_state(channel_type, channel))


def replay_digest(path: str, channel_address: str | None = None) -> str:
    header, rows = read_corpus(path)
    channel = replay(header, rows, channel_address)
    return channel_digest(header["channel_type"], channel)


def load_pins() -> dict:
    with open(os.path.join(CORPUS_DIR, "pins.json")) as f:
        return json.load(f)
