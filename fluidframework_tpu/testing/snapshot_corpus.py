"""Snapshot-format regression corpus.

Capability parity with reference packages/test/snapshots (replays recorded
documents and byte-compares generated snapshots across code versions) and
sequence/src/test/snapshotVersion.spec.ts (pins the serialized snapshot
format against checked-in files): deterministic builders produce documents
covering every serialization path; their canonical summary bytes are
hashed and pinned in tests/snapshots/pinned.json. A pin mismatch means the
on-disk format changed — either a regression, or an intentional format
evolution that must update the pin file (and, in a live deployment, ship a
format-version bump with a loader for the old format).
"""

from __future__ import annotations

import hashlib
import json
import random
from typing import Callable, Dict

from ..dds.directory import SharedDirectory
from ..dds.map import SharedMap
from ..dds.matrix import SharedMatrix
from ..dds.sequence import SharedNumberSequence, SharedString
from ..loader.container import Container
from ..loader.drivers.local import LocalDocumentServiceFactory
from ..protocol.summary import summary_tree_to_dict
from ..server.local_server import LocalServer


def _detached(doc_id: str) -> Container:
    service = LocalDocumentServiceFactory(
        LocalServer()).create_document_service(doc_id)
    return Container.create_detached(doc_id, service)


def _commit_detached(container: Container) -> Container:
    """Fold pending detached edits into permanent channel state (the attach
    path does this via store.connect) WITHOUT attaching — attaching would
    pull in wire client ids (uuid-based) and break determinism."""
    for store in container.runtime.datastores.values():
        store.connect()
    return container


def build_text_document() -> Container:
    c = _detached("pin-text")
    ds = c.runtime.create_datastore("default")
    text = ds.create_channel("text", SharedString.TYPE)
    text.insert_text(0, "The quick brown fox jumps over the lazy dog. " * 8)
    text.insert_marker(45, {"type": "paragraph"})
    text.annotate_range(4, 9, {"fontWeight": "bold"})
    text.remove_text(10, 16)
    text.insert_text(0, "Title\n", {"header": 1})
    return _commit_detached(c)


def build_kv_document() -> Container:
    c = _detached("pin-kv")
    ds = c.runtime.create_datastore("default")
    m = ds.create_channel("map", SharedMap.TYPE)
    for i in range(16):
        m.set(f"key-{i:02d}", {"index": i, "squares": [i, i * i]})
    m.delete("key-03")
    d = ds.create_channel("dir", SharedDirectory.TYPE)
    d.set("top", "level")
    sub = d.create_sub_directory("nested")
    sub.set("deep", {"a": [1, 2, 3]})
    return _commit_detached(c)


def build_matrix_document() -> Container:
    random.seed(1234)  # permutation-vector run nonces draw from global rng
    c = _detached("pin-matrix")
    ds = c.runtime.create_datastore("default")
    mx = ds.create_channel("matrix", SharedMatrix.TYPE)
    mx.insert_rows(0, 8)
    mx.insert_cols(0, 4)
    for r in range(8):
        mx.set_cell(r, r % 4, r * 10)
    mx.remove_rows(2, 2)
    return _commit_detached(c)


def build_sequence_document() -> Container:
    c = _detached("pin-numseq")
    ds = c.runtime.create_datastore("default")
    ns = ds.create_channel("nums", SharedNumberSequence.TYPE)
    ns.insert_range(0, list(range(20)))
    ns.remove_range(5, 10)
    ns.insert_range(3, [100, 200])
    return _commit_detached(c)


def build_trace_document() -> Container:
    """A realistic editing session (keystroke bursts, backspaces, word
    deletes, pastes, format sweeps — testing/traces.py) pinned end-state:
    the corpus analog of the reference's recorded-log replay
    (packages/test/snapshots/src/replayMultipleFiles.ts)."""
    from .traces import keystroke_trace

    c = _detached("pin-trace")
    ds = c.runtime.create_datastore("default")
    text = ds.create_channel("text", SharedString.TYPE)
    for op, *_ in keystroke_trace(1500, seed=77):
        if op["type"] == 0:
            text.insert_text(op["pos1"], op["seg"]["text"],
                             op["seg"].get("props"))
        elif op["type"] == 1:
            text.remove_text(op["pos1"], op["pos2"])
        else:
            text.annotate_range(op["pos1"], op["pos2"], op["props"])
    return _commit_detached(c)


BUILDERS: Dict[str, Callable[[], Container]] = {
    "text": build_text_document,
    "kv": build_kv_document,
    "matrix": build_matrix_document,
    "number-sequence": build_sequence_document,
    "keystroke-trace": build_trace_document,
}


def canonical(container: Container) -> str:
    return json.dumps(summary_tree_to_dict(container._assemble_summary()),
                      sort_keys=True)


def corpus_digests() -> Dict[str, str]:
    return {name: hashlib.sha256(canonical(build()).encode()).hexdigest()
            for name, build in BUILDERS.items()}


def write_pins(path: str) -> Dict[str, str]:
    digests = corpus_digests()
    with open(path, "w") as f:
        json.dump(digests, f, indent=1, sort_keys=True)
    return digests


if __name__ == "__main__":
    import sys
    out = sys.argv[1] if len(sys.argv) > 1 else "tests/snapshots/pinned.json"
    for name, digest in write_pins(out).items():
        print(f"{name}: {digest}")
