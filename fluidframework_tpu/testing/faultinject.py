"""Seeded deterministic fault injection for the server pipeline.

The soak/chaos suites and the overload bench need failure modes beyond
in-process chaos seeds: lost/duplicated/delayed broker deliveries,
connection resets, slow-device stalls, and clock skew. This module
provides them with one hard guarantee: **every decision is drawn from a
single seeded RNG in call order and appended to ``plan.trace``**, so a
scenario replayed with the same seed and the same call sequence
reproduces bit-identically — the suites assert
``plan_a.fingerprint() == plan_b.fingerprint()`` (and the overload
smoke stamps the verdict into its BENCH record).

Pieces:

  FaultPlan          the seeded decision source (probabilities + trace)
  FaultyMessageLog   MessageLog wrapper injecting broker-delivery faults
                     (drop / duplicate / delay-by-k-sends) on selected
                     topics; delegates everything else
  SkewedClock        monotonic-like clock with constant offset + drift
                     (admission-controller clock injection)
  stall()            slow-device stall helper for the sequencer's
                     ``stall_hook``

Faults are injected on the PRODUCE side (``send``), which models the
broker losing/reordering deliveries while keeping consumer offset
arithmetic exact — a dropped message simply never enters the partition,
a duplicate appends twice, a delayed message appends k sends later (or
at ``flush_delayed()``).
"""

from __future__ import annotations

import hashlib
import random
import time
from typing import Callable, List, Optional, Tuple

from ..telemetry.counters import increment

DELIVER = "deliver"
DROP = "drop"
DUP = "dup"
DELAY = "delay"


class FaultPlan:
    """Deterministic, seeded fault schedule. All probabilities are
    evaluated in a FIXED draw order per decision, so two plans with the
    same seed and parameters make identical choices forever."""

    def __init__(self, seed: int, drop: float = 0.0, dup: float = 0.0,
                 delay: float = 0.0, max_delay_sends: int = 3,
                 reset: float = 0.0, stall: float = 0.0,
                 stall_range_ms: Tuple[float, float] = (0.5, 4.0),
                 skew_s: float = 0.0):
        self.seed = seed
        self.rng = random.Random(seed)
        self.drop = drop
        self.dup = dup
        self.delay = delay
        self.max_delay_sends = max(1, int(max_delay_sends))
        self.reset = reset
        self.stall = stall
        self.stall_range_ms = stall_range_ms
        self.skew_s = skew_s
        self.trace: List[Tuple[str, str]] = []

    def _record(self, site: str, action: str) -> None:
        self.trace.append((site, action))
        increment(f"faultinject.{action}")

    # -- decision draws (one rng consumption path per call) -----------------
    def delivery(self) -> Tuple[str, int]:
        """(action, delay_sends) for the next broker delivery."""
        r = self.rng.random()
        if r < self.drop:
            self._record("delivery", DROP)
            return DROP, 0
        if r < self.drop + self.dup:
            self._record("delivery", DUP)
            return DUP, 0
        if r < self.drop + self.dup + self.delay:
            k = self.rng.randrange(1, self.max_delay_sends + 1)
            self._record("delivery", f"{DELAY}:{k}")
            return DELAY, k
        self._record("delivery", DELIVER)
        return DELIVER, 0

    def should_reset(self) -> bool:
        """Connection-reset decision (the reconnect-avalanche driver)."""
        hit = self.rng.random() < self.reset
        self._record("reset", "reset" if hit else "ok")
        return hit

    def stall_s(self) -> float:
        """Slow-device stall duration for the next flush (0.0 = none)."""
        if self.rng.random() >= self.stall:
            self._record("stall", "none")
            return 0.0
        lo, hi = self.stall_range_ms
        ms = lo + self.rng.random() * (hi - lo)
        self._record("stall", f"stall:{ms:.3f}ms")
        return ms / 1000.0

    def pick(self, n: int, site: str = "pick") -> int:
        """Deterministic index choice (which client resets, which doc a
        burst targets) — recorded like every other decision."""
        i = self.rng.randrange(n)
        self._record(site, str(i))
        return i

    def fingerprint(self) -> str:
        """Stable digest of every decision made so far — the
        bit-identity witness two same-seed runs must agree on."""
        h = hashlib.sha256()
        for site, action in self.trace:
            h.update(site.encode())
            h.update(b"\x00")
            h.update(action.encode())
            h.update(b"\x01")
        return h.hexdigest()


class FaultyMessageLog:
    """MessageLog wrapper injecting plan-driven broker faults on
    ``send`` for the listed topics (default: the raw ingest topic).
    Reads/commits/offsets delegate untouched, so partition pumps and
    checkpoint replay behave exactly as against the real log."""

    def __init__(self, inner, plan: FaultPlan,
                 topics: Tuple[str, ...] = ("rawdeltas",)):
        self.inner = inner
        self.plan = plan
        self.fault_topics = frozenset(topics)
        # Delayed deliveries: (due_send_ordinal, topic, partition, key,
        # value) with partition None for keyed sends, released in due
        # order before later sends (deterministic).
        self._held: List[Tuple[int, str, Optional[int], str, object]] = []
        self._sends = 0

    # -- producer (the injection point) -------------------------------------
    def send(self, topic: str, key: str, value):
        return self._faulty_send(topic, None, key, value)

    def send_to(self, topic: str, partition: int, key: str, value):
        """Explicit-partition produce rides the SAME fault schedule as
        keyed sends — the sharded ingest tier (server/sharding.py)
        routes documents itself, and its traffic must stay inside the
        chaos envelope, not silently bypass it via __getattr__."""
        return self._faulty_send(topic, int(partition), key, value)

    def send_to_many(self, topic: str, partition: int, items):
        """Batched produce decomposes to one fault draw PER record —
        send_to_many(t, p, xs) must sit in exactly the same chaos
        envelope as len(xs) send_to calls, or batch-path callers would
        silently dodge injected drops/dups/delays (and break run-twice
        fingerprint identity between batched and unbatched drivers)."""
        return [self._faulty_send(topic, int(partition), key, value)
                for key, value in items]

    def _faulty_send(self, topic: str, partition: Optional[int], key: str,
                     value):
        if topic not in self.fault_topics:
            return self._deliver(topic, partition, key, value)
        self._sends += 1
        self._release_due()
        action, k = self.plan.delivery()
        if action == DROP:
            return None
        if action == DUP:
            self._deliver(topic, partition, key, value)
            return self._deliver(topic, partition, key, value)
        if action == DELAY:
            self._held.append((self._sends + k, topic, partition, key,
                               value))
            return None
        return self._deliver(topic, partition, key, value)

    def _deliver(self, topic: str, partition: Optional[int], key: str,
                 value):
        if partition is None:
            return self.inner.send(topic, key, value)
        return self.inner.send_to(topic, partition, key, value)

    def _release_due(self) -> None:
        if not self._held:
            return
        due = [h for h in self._held if h[0] <= self._sends]
        if not due:
            return
        self._held = [h for h in self._held if h[0] > self._sends]
        for _, topic, partition, key, value in due:
            self._deliver(topic, partition, key, value)

    def flush_delayed(self) -> int:
        """Deliver every still-held message (scenario teardown: nothing
        may stay lost-in-flight before the convergence assert)."""
        held, self._held = self._held, []
        for _, topic, partition, key, value in held:
            self._deliver(topic, partition, key, value)
        return len(held)

    @property
    def held_count(self) -> int:
        return len(self._held)

    # -- everything else delegates ------------------------------------------
    def __getattr__(self, item):
        return getattr(self.inner, item)


class SkewedClock:
    """A monotonic-like clock with constant offset and linear drift —
    what a fleet node with a bad NTP sync looks like to the admission
    controller. Deterministic when ``base`` is (tests inject a virtual
    counter)."""

    def __init__(self, skew_s: float = 0.0, drift: float = 0.0,
                 base: Callable[[], float] = time.monotonic):
        self.skew_s = skew_s
        self.drift = drift
        self.base = base
        self._t0 = base()

    def __call__(self) -> float:
        t = self.base()
        return t + self.skew_s + self.drift * (t - self._t0)


def crash_partition(plan: FaultPlan, manager,
                    site: str = "partition-crash"):
    """Partition-worker crash chaos: deterministically pick one of a
    PartitionManager's pumps (or none) from the plan and crash-restart
    it — the lambda is rebuilt from its checkpoint store and the pump
    replays from the last committed offset, exactly the recovery the
    sharded ingest tier promises (docs/ingest_sharding.md). The draw is
    recorded in the plan trace, so run-twice fingerprints pin both WHEN
    a crash happened and WHICH partition it hit. Returns the crashed
    partition index, or None for the no-crash draw."""
    pumps = sorted(manager.pumps)
    idx = plan.pick(len(pumps) + 1, site=site)
    if idx == len(pumps):
        return None  # the no-crash slot — crashes stay occasional
    manager.pumps[pumps[idx]].restart()
    return pumps[idx]


def stall(plan: FaultPlan,
          sleep: Callable[[float], None] = time.sleep) -> float:
    """Slow-device stall hook body: draw a stall from the plan and sleep
    it (tests pass a recording `sleep` to keep wall time at zero).
    Attach as ``sequencer.stall_hook = lambda: faultinject.stall(plan)``.
    Returns the stall applied (seconds)."""
    s = plan.stall_s()
    if s > 0:
        sleep(s)
    return s
