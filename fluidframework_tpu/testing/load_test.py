"""Service load/stress rig.

Capability parity with reference packages/test/service-load-test
(`nodeStressTest.ts:24-33`, `loadTestDataStore.ts`): a configurable
profile — documents × clients-per-doc × ops, with an op mix across DDS
types — driven against any service through its driver factory; reports
throughput and verifies full cross-client convergence per document at the
end (the rig doubles as an eventual-consistency checker, SURVEY.md §5
race detection)."""

from __future__ import annotations

import random
import time
from dataclasses import dataclass, field
from typing import Callable, Dict, List, Optional

from ..capacity.workload import OpMix, closed_loop_schedule
from ..dds.counter import SharedCounter
from ..dds.map import SharedMap
from ..dds.sequence import SharedString
from ..loader.container import Container, Loader


@dataclass
class LoadProfile:
    """Mirrors the reference's profile knobs (docs, clients, op budget)."""

    documents: int = 2
    clients_per_document: int = 2
    ops_per_client: int = 50
    seed: int = 0
    # Op mix weights: (map set, string insert, string remove, counter inc)
    weights: tuple = (4, 3, 1, 2)
    reconnect_probability: float = 0.0  # per-op chance to drop + resubmit
    # True: string edits follow the keystroke model (bursts at a moving
    # cursor, backspaces, word deletes, pastes, format sweeps —
    # testing/traces.py) instead of uniform-random positions; the
    # position-locality distribution real editors produce.
    keystroke_trace: bool = False


@dataclass
class LoadResult:
    total_ops: int = 0
    elapsed_s: float = 0.0
    documents: int = 0
    divergences: List[str] = field(default_factory=list)

    @property
    def ops_per_second(self) -> float:
        return self.total_ops / self.elapsed_s if self.elapsed_s else 0.0

    @property
    def converged(self) -> bool:
        return not self.divergences


class LoadRunner:
    """`loader_factory()` must yield a FRESH Loader per client (each client
    is its own wire identity), all bound to the same service."""

    def __init__(self, loader_factory: Callable[[], Loader]):
        self.loader_factory = loader_factory
        self._cursors: Dict[tuple, int] = {}  # (doc, client) -> position

    def _setup_document(self, doc_id: str, n_clients: int
                        ) -> List[Container]:
        creator = self.loader_factory()
        c0 = creator.create_detached(doc_id)
        ds = c0.runtime.create_datastore("load")
        ds.create_channel("map", SharedMap.TYPE)
        ds.create_channel("text", SharedString.TYPE)
        ds.create_channel("counter", SharedCounter.TYPE)
        c0.attach()
        containers = [c0]
        for _ in range(n_clients - 1):
            containers.append(self.loader_factory().resolve(doc_id))
        return containers

    def _one_op(self, rng: random.Random, client_index: int, op_index: int,
                container: Container, profile: LoadProfile) -> None:
        ds = container.runtime.get_datastore("load")
        # The one op-mix implementation in the tree (capacity/workload.py):
        # consumes the profile RNG exactly as the historical inline
        # rng.choices did, so seeded replays pick identical kinds.
        kind = OpMix(profile.weights).draw(rng)
        if kind == "map":
            # JSON-canonical values only: the writer keeps the submitted
            # object while replicas see its wire round-trip (a tuple would
            # come back as a list — same as the reference, which stores the
            # local JS object as-is).
            ds.get_channel("map").set(
                f"k{rng.randrange(32)}", [client_index, op_index])
        elif kind == "insert":
            text = ds.get_channel("text")
            pos = rng.randrange(text.get_length() + 1)
            text.insert_text(pos, f"c{client_index}.{op_index};")
        elif kind == "remove":
            text = ds.get_channel("text")
            length = text.get_length()
            if length > 2:
                start = rng.randrange(length - 1)
                text.remove_text(start,
                                 min(length, start + rng.randrange(1, 4)))
        else:
            ds.get_channel("counter").increment(rng.randrange(1, 5))

    def _trace_op(self, rng: random.Random, doc_id: str, client_index: int,
                  container: Container) -> None:
        """One keystroke-model edit against the live channel (the editing
        shape of traces.keystroke_trace, driven interactively)."""
        from .traces import WORDS

        text = container.runtime.get_datastore("load").get_channel("text")
        length = text.get_length()
        key = (doc_id, client_index)
        cur = min(self._cursors.get(key, 0), length)
        roll = rng.random()
        if roll < 0.74:  # keystroke
            word = rng.choice(WORDS)
            ch = word[rng.randrange(len(word))] if rng.random() < 0.85 \
                else " "
            text.insert_text(cur, ch)
            cur += 1
        elif roll < 0.84:  # backspace
            if cur > 0:
                text.remove_text(cur - 1, cur)
                cur -= 1
        elif roll < 0.90:  # word/selection delete
            if length >= 4:
                span = min(rng.randrange(2, 25), length)
                start = max(0, min(cur - span // 2, length - span))
                text.remove_text(start, start + span)
                cur = start
        elif roll < 0.94:  # paste
            n = rng.randrange(20, 121)
            blob = " ".join(rng.choice(WORDS)
                            for _ in range(max(1, n // 6)))[:n]
            text.insert_text(cur, blob)
            cur += len(blob)
        elif roll < 0.98:  # format sweep
            if length >= 2:
                span = min(rng.randrange(5, 81), length)
                start = max(0, min(cur - span // 2, length - span))
                text.annotate_range(start, start + span,
                                    {"fmt": rng.randrange(4)})
        else:  # cursor jump
            cur = rng.randrange(length + 1) if length else 0
        self._cursors[key] = cur

    def run(self, profile: Optional[LoadProfile] = None) -> LoadResult:
        profile = profile or LoadProfile()
        result = LoadResult(documents=profile.documents)
        rng = random.Random(profile.seed)
        docs: Dict[str, List[Container]] = {}
        for d in range(profile.documents):
            doc_id = f"load-doc-{d}"
            docs[doc_id] = self._setup_document(
                doc_id, profile.clients_per_document)
        started = time.perf_counter()
        # The shared closed-loop schedule (capacity/workload.py): the
        # same (doc, op, client) nesting order this rig has always
        # driven, now defined once for rig and soak alike.
        doc_list = list(docs.items())
        for d, op_index, client_index in closed_loop_schedule(
                profile.documents, profile.clients_per_document,
                profile.ops_per_client):
            doc_id, containers = doc_list[d]
            container = containers[client_index]
            if (profile.reconnect_probability
                    and rng.random() < profile.reconnect_probability):
                container.reconnect()
            if profile.keystroke_trace:
                self._trace_op(rng, doc_id, client_index, container)
            else:
                self._one_op(rng, client_index, op_index, container,
                             profile)
            result.total_ops += 1
        result.elapsed_s = time.perf_counter() - started
        # -- convergence audit (the race detector role) ---------------------
        for doc_id, containers in docs.items():
            views = []
            for container in containers:
                ds = container.runtime.get_datastore("load")
                m = ds.get_channel("map")
                views.append({
                    "map": {k: m.get(k) for k in sorted(m.keys())},
                    "text": ds.get_channel("text").get_text(),
                    "counter": ds.get_channel("counter").value,
                })
            for i, view in enumerate(views[1:], start=1):
                if view != views[0]:
                    result.divergences.append(
                        f"{doc_id}: client {i} diverged from client 0")
        return result
