"""Historian smoke: spin up the local topology WITH the cache tier and
prove the end-to-end contract in one command (`make historian-smoke`).

Topology: tinylicious alfred + standalone HistorianService (proxy mode)
+ ServiceMonitor, a container created and attached through the network
driver's historian endpoint, then reloaded. Asserts:
  1. the reload serves its summary blobs from the tier (hit rate > 0,
     visible through monitor.py's /metrics),
  2. a summary commit invalidated the tier's ref pointer (write-through),
  3. killing the historian degrades the next load to direct GitStore
     reads without failing.
Exit code 0 = all held.
"""

from __future__ import annotations

import json
import sys
import urllib.request


def main() -> int:
    from ..dds.map import SharedMap
    from ..loader.container import Loader
    from ..loader.drivers.routerlicious import NetworkDocumentServiceFactory
    from ..server.historian import HistorianService
    from ..server.monitor import ServiceMonitor
    from ..server.tinylicious import DEFAULT_TENANT, Tinylicious

    def load(tiny, hist, doc_id):
        factory = NetworkDocumentServiceFactory(
            tiny.url, DEFAULT_TENANT, historian_url=hist.url)
        return Loader(factory).resolve(doc_id)

    with Tinylicious() as tiny:
        hist = HistorianService(upstream_url=tiny.url).start()
        tiny.attach_historian(hist.url)
        monitor = ServiceMonitor()
        monitor.watch_historian("historian", hist)
        monitor.start()
        print(f"historian-smoke: alfred={tiny.url} historian={hist.url} "
              f"monitor={monitor.url}")

        factory = NetworkDocumentServiceFactory(
            tiny.url, DEFAULT_TENANT, historian_url=hist.url)
        loader = Loader(factory)
        c1 = loader.create_detached("smoke")
        ds = c1.runtime.create_datastore("default")
        m = ds.create_channel("root", SharedMap.TYPE)
        with c1.op_lock:
            m.set("k", "v1")
        c1.attach()  # write-through upload + warm-on-summary prefetch

        c2 = load(tiny, hist, "smoke")
        assert c2.runtime.get_datastore("default") \
            .get_channel("root").get("k") == "v1"
        report = json.loads(urllib.request.urlopen(
            monitor.url + "/metrics").read())
        probe = report["probes"]["historian"]
        hit_rate = probe["objects"]["hitRate"]
        print(f"historian-smoke: reload hit rate "
              f"{hit_rate:.2f} ({probe['objects']['hits']} hits, "
              f"{probe['objects']['misses']} misses, "
              f"{probe['prefetchedObjects']} prefetched)")
        assert probe["objects"]["hits"] > 0, "reload never hit the cache"
        assert hit_rate > 0, "hit rate not visible through monitor"
        assert probe["invalidations"] >= 1, \
            "summary commit never invalidated the ref pointer"

        hist.stop()
        c3 = load(tiny, hist, "smoke")
        assert c3.runtime.get_datastore("default") \
            .get_channel("root").get("k") == "v1"
        print("historian-smoke: degradation to direct GitStore OK")
        for c in (c1, c2, c3):
            c.close()
        monitor.stop()
    print("historian-smoke: PASS")
    return 0


if __name__ == "__main__":
    sys.exit(main())
