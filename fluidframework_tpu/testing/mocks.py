"""MockSequencedEnvironment: a mini ordering service + N container runtimes.

Capability parity with reference test-runtime-utils
(mocks.ts:108 MockContainerRuntimeFactory — "collects submitted ops, stamps
seq numbers, redelivers to all connected mocks" — and mocksForReconnection
.ts:18,83): join ops, per-client FIFO queues, minimum-sequence-number
tracking deli-style (min over in-flight refSeqs), disconnect/reconnect with
op loss and resubmission.
"""

from __future__ import annotations

import random
from typing import Dict, List, Optional, Tuple

from ..protocol.messages import MessageType, SequencedDocumentMessage
from ..runtime.container_runtime import ContainerRuntime
from ..runtime.datastore_runtime import ChannelRegistry


class _ClientState:
    def __init__(self, client_id: str, runtime: ContainerRuntime):
        self.client_id = client_id
        self.runtime = runtime
        self.connected = True
        self.queue: List[Tuple[str, dict, int, int]] = []  # type, contents, csn, refseq
        self.buffered: List[SequencedDocumentMessage] = []
        self.csn = 0
        self.last_seen_seq = 0


class MockSequencedEnvironment:
    def __init__(self, registry: Optional[ChannelRegistry] = None):
        self.registry = registry
        self.clients: Dict[str, _ClientState] = {}
        self.seq = 0
        self._id_counter = 0

    # -- clients -----------------------------------------------------------
    def create_runtime(self, client_id: Optional[str] = None
                       ) -> ContainerRuntime:
        self._id_counter += 1
        client_id = client_id or f"client-{self._id_counter}"
        runtime = ContainerRuntime(registry=self.registry)
        state = _ClientState(client_id, runtime)
        self.clients[client_id] = state

        def submit_fn(mtype, contents, before_send=None, _state=state):
            _state.csn += 1
            if before_send is not None:
                before_send(_state.csn)
            _state.queue.append(
                (mtype, contents, _state.csn, _state.last_seen_seq))
            return _state.csn

        runtime.set_local_client(client_id)
        runtime.attach(submit_fn)
        # Join op enters the sequenced stream.
        state.queue.insert(0, (MessageType.CLIENT_JOIN,
                               {"clientId": client_id}, 0, 0))
        return runtime

    # -- connection churn ---------------------------------------------------
    def disconnect(self, runtime: ContainerRuntime) -> None:
        state = self._state_of(runtime)
        state.connected = False
        state.queue.clear()  # in-flight ops are lost
        runtime.set_connected(False)

    def reconnect(self, runtime: ContainerRuntime) -> None:
        state = self._state_of(runtime)
        # Catch up on everything missed while away.
        for msg in state.buffered:
            runtime.process(msg)
            state.last_seen_seq = msg.sequence_number
        state.buffered.clear()
        state.connected = True
        # New wire identity (new join), like a real reconnect.
        self._id_counter += 1
        new_id = f"{state.client_id}#r{self._id_counter}"
        del self.clients[state.client_id]
        state.client_id = new_id
        self.clients[new_id] = state
        state.queue.append((MessageType.CLIENT_JOIN,
                            {"clientId": new_id}, 0, state.last_seen_seq))
        runtime.set_connected(True, new_id)  # triggers resubmission

    def _state_of(self, runtime: ContainerRuntime) -> _ClientState:
        for state in self.clients.values():
            if state.runtime is runtime:
                return state
        raise KeyError("unknown runtime")

    # -- sequencing ---------------------------------------------------------
    def _min_seq(self) -> int:
        """Deli MSN rule: min over connected clients of (refSeq of oldest
        in-flight op, else last seen seq)."""
        floors = []
        for state in self.clients.values():
            if not state.connected:
                continue
            if state.queue:
                floors.append(min(entry[3] for entry in state.queue))
            else:
                floors.append(state.last_seen_seq)
        return min(floors) if floors else self.seq

    def process_some(self, rng: random.Random, limit: int = 10**9) -> int:
        """Sequence up to `limit` queued ops in a random per-client-order-
        preserving interleave; deliver to connected, buffer for others."""
        processed = 0
        while processed < limit:
            live = [s for s in self.clients.values()
                    if s.queue and s.connected]
            if not live:
                break
            state = rng.choice(live)
            mtype, contents, csn, ref_seq = state.queue.pop(0)
            self.seq += 1
            msg = SequencedDocumentMessage(
                client_id=state.client_id,
                sequence_number=self.seq,
                minimum_sequence_number=min(self._min_seq(), self.seq - 1),
                client_sequence_number=csn,
                reference_sequence_number=ref_seq,
                type=mtype,
                contents=contents,
            )
            for target in self.clients.values():
                if target.connected:
                    target.runtime.process(msg)
                    target.last_seen_seq = self.seq
                else:
                    target.buffered.append(msg)
            processed += 1
        return processed

    def process_all(self, rng: Optional[random.Random] = None) -> int:
        return self.process_some(rng or random.Random(0))
