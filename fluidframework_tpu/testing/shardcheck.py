"""Runtime sharding verification — fluidlint v4's dynamic half.

The static placement model (analysis/placement_model.py) PROVES the
placements it can see and goes quiet where placement is conditional
(``mesh is None`` gates, cross-module pool adoption) — a documented
soundness trade. This module closes the loop the way
``testing/lockcheck.py`` closes the race-detector's: assert at dispatch
time that the ACTUAL ``.sharding`` of every serving pytree leaf matches
the spec the partition-rule table
(``mergetree/partition_rules.py``) statically predicts, while the real
code runs under the mesh tests and soak — so the rule table and the
runtime cannot silently drift apart.

Usage::

    from fluidframework_tpu.testing import shardcheck

    shardcheck.assert_placement(store.pool, mesh,
                                POOL_PARTITION_RULES, where="pool")
    checked = shardcheck.verify_store(lam.merge, mesh)   # whole store

    step = shardcheck.instrument(step, mesh, POOL_PARTITION_RULES)
    step(pool, ids)          # raises ShardingMismatch before dispatch
    step.checks              # how many leaves were actually verified

Everything here is import-cheap and debug-only: production code never
imports this module; the mesh tests, the SOAK trials, and
``dryrun_multichip`` (which stamps the verdict into MULTICHIP_LAST.json)
do.
"""

from __future__ import annotations

import functools
from typing import Any, Dict, List, Optional, Sequence

from jax.sharding import NamedSharding

from ..mergetree.partition_rules import (LANE_PARTITION_RULES,
                                         POOL_PARTITION_RULES,
                                         PartitionRule, _spec_for,
                                         named_leaves)


class ShardingMismatch(AssertionError):
    """A leaf's actual sharding diverged from its rule-table spec."""


def assert_placement(tree: Any, mesh, rules: Sequence[PartitionRule],
                     where: str = "") -> int:
    """Assert every jax-array leaf of ``tree`` is placed exactly as the
    rule table predicts on ``mesh``; returns the number of leaves
    checked. Leaves without a ``.sharding`` (numpy staging planes, host
    scalars) are skipped — the table governs device placement only."""
    failures: List[str] = []
    checked = 0
    for name, leaf in named_leaves(tree):
        sharding = getattr(leaf, "sharding", None)
        if sharding is None:
            continue
        expected = NamedSharding(mesh, _spec_for(rules, name, leaf))
        checked += 1
        try:
            ok = sharding.is_equivalent_to(expected, leaf.ndim)
        except (TypeError, ValueError):
            ok = False
        if not ok:
            label = f"{where}/{name}" if where else name
            failures.append(f"  {label}: actual {sharding} != "
                            f"predicted {expected}")
    if failures:
        raise ShardingMismatch(
            "sharding drifted from the partition-rule table "
            f"({len(failures)}/{checked} leaves):\n"
            + "\n".join(failures))
    return checked


def verify_store(merge_store, mesh=None) -> int:
    """Verify a MergeLaneStore's device-resident planes against the
    rule tables: the paged pool under POOL_PARTITION_RULES, every
    bucket grid under LANE_PARTITION_RULES. Returns leaves checked
    (0 when the store carries no mesh — nothing to predict)."""
    mesh = mesh if mesh is not None else getattr(merge_store, "mesh",
                                                 None)
    if mesh is None:
        return 0
    checked = 0
    pages = getattr(merge_store, "pages", None)
    if pages is not None:
        checked += assert_placement(pages.pool, mesh,
                                    POOL_PARTITION_RULES, where="pool")
    for bucket in getattr(merge_store, "buckets", []):
        checked += assert_placement(
            bucket.state, mesh, LANE_PARTITION_RULES,
            where=f"bucket{bucket.capacity}")
    return checked


def instrument(fn, mesh, rules: Sequence[PartitionRule],
               tree_args: Sequence[int] = (0,)):
    """Wrap a dispatch callable so the pytree arguments at positions
    ``tree_args`` are verified against ``rules`` BEFORE every call —
    the statically predicted spec meets the actual input ``.sharding``
    exactly where a wrong placement would compile into silent
    collectives. The wrapper counts verified leaves in ``.checks``."""

    @functools.wraps(fn)
    def checked(*args, **kwargs):
        for pos in tree_args:
            if pos < len(args):
                checked.checks += assert_placement(
                    args[pos], mesh, rules, where=f"arg{pos}")
        return fn(*args, **kwargs)

    checked.checks = 0
    return checked


def placement_report(merge_store, mesh=None) -> Dict[str, Any]:
    """The machine-readable verdict dryrun_multichip stamps:
    {"ok": bool, "checked": N, "error": str|None} plus the resolved
    spec table for the paged pool when one exists."""
    report: Dict[str, Any] = {"ok": True, "checked": 0, "error": None}
    try:
        report["checked"] = verify_store(merge_store, mesh)
    except (ShardingMismatch, ValueError) as exc:
        report["ok"] = False
        report["error"] = str(exc).splitlines()[0]
    pages = getattr(merge_store, "pages", None)
    if pages is not None and getattr(pages, "mesh", None) is not None:
        report["pool_specs"] = pages.placement_spec_table()
    return report
