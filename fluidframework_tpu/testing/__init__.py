"""Test doubles (reference packages/runtime/test-runtime-utils parity):
an in-process sequencing service + runtime wiring for DDS tests, including
reconnection injection (mocksForReconnection.ts)."""

from .mocks import MockSequencedEnvironment
