"""Realistic editing-trace synthesis for benchmarks and replay tests.

The reference replays real captured op logs (ProseMirror/Monaco sessions:
packages/test/snapshots/src/replayMultipleFiles.ts:1 over an LFS corpus)
and stress profiles (packages/test/service-load-test/src/nodeStressTest.ts:
24-33). Real editor traffic is nothing like uniform-random ops: it is
keystroke bursts at a slowly-moving cursor, backspace runs, word/line
deletions, cursor jumps with strong locality, occasional format
(annotate) sweeps, and rare large paste/cut blocks. This module
synthesizes that shape deterministically, keystroke by keystroke, so the
kernel is measured on the position-locality distribution serving actually
sees rather than the uniform dense streams it finds easiest.
"""

from __future__ import annotations

import random
from typing import List, Tuple

# Wire op types (mergetree/client.py, reference ops.ts:29).
OP_INSERT, OP_REMOVE, OP_ANNOTATE = 0, 1, 2

WORDS = ("the quick brown fox jumps over a lazy dog while typing "
         "structured paragraphs of filler prose for replay traces").split()


def keystroke_trace(n_ops: int, seed: int = 0, n_clients: int = 1,
                    window: int = 128) -> List[Tuple[dict, int, int, int,
                                                     int]]:
    """A sequenced single-document editing trace:
    [(wire_op, seq, ref_seq, client_ordinal, msn)].

    Emission model (per op, roughly matching captured editor sessions):
      74% keystroke insert (1 char at the cursor; bursts extend words)
      10% backspace (remove 1 char before the cursor)
       6% word/selection delete (remove 2-24 chars near the cursor)
       4% paste (insert 20-200 chars at the cursor)
       4% format sweep (annotate 5-80 chars near the cursor)
       2% cursor jump (no op emitted; moves the locality anchor)

    Multi-client mode interleaves independent cursors with a shared
    sequencing order and per-client ref_seq lag, the concurrent-editor
    shape of the service-load profiles."""
    rng = random.Random(seed)
    length = 0
    cursors = [0] * n_clients
    out: List[Tuple[dict, int, int, int, int]] = []
    seq = 0
    burst_left = 0
    burst_client = 0
    while len(out) < n_ops:
        if burst_left > 0:
            c = burst_client
            burst_left -= 1
            roll = 0.0  # keystroke continues the burst
        else:
            c = rng.randrange(n_clients)
            roll = rng.random()
            if roll >= 0.98:  # cursor jump: move anchor, no op
                cursors[c] = rng.randrange(length + 1) if length else 0
                continue
            if roll < 0.74:  # start a word burst
                burst_left = rng.randrange(2, 9)
                burst_client = c
        cur = min(cursors[c], length)
        if roll < 0.74:  # keystroke
            word = rng.choice(WORDS)
            ch = word[rng.randrange(len(word))] if rng.random() < 0.85 \
                else " "
            op = {"type": OP_INSERT, "pos1": cur, "seg": {"text": ch}}
            length += 1
            cursors[c] = cur + 1
        elif roll < 0.84:  # backspace
            if cur == 0 or length == 0:
                burst_left = 0
                continue
            op = {"type": OP_REMOVE, "pos1": cur - 1, "pos2": cur}
            length -= 1
            cursors[c] = cur - 1
        elif roll < 0.90:  # word/selection delete
            if length < 4:
                continue
            span = min(rng.randrange(2, 25), length)
            start = max(0, min(cur - span // 2, length - span))
            op = {"type": OP_REMOVE, "pos1": start, "pos2": start + span}
            length -= span
            cursors[c] = start
        elif roll < 0.94:  # paste
            n = rng.randrange(20, 201)
            text = " ".join(rng.choice(WORDS)
                            for _ in range(max(1, n // 6)))[:n]
            op = {"type": OP_INSERT, "pos1": cur, "seg": {"text": text}}
            length += len(text)
            cursors[c] = cur + len(text)
        else:  # format sweep
            if length < 2:
                continue
            span = min(rng.randrange(5, 81), length)
            start = max(0, min(cur - span // 2, length - span))
            op = {"type": OP_ANNOTATE, "pos1": start, "pos2": start + span,
                  "props": {"fmt": rng.randrange(4)}}
        seq += 1
        # Concurrent editors lag each other by a small ref_seq window.
        lag = 0 if n_clients == 1 else rng.randrange(0, 4)
        out.append((op, seq, max(0, seq - 1 - lag), 1 + c,
                    max(0, seq - window)))
    return out


def matrix_storm(rows: int, cols: int, n_ops: int, seed: int = 0):
    """Spreadsheet op storm for a rows×cols SharedMatrix (BASELINE config
    #3): 6% row inserts, 4% col inserts, 2% row/col removes, 88% cell
    sets with row/col locality (edits cluster around a moving active
    cell, the way spreadsheet sessions behave).

    Yields ("insert_rows"|"insert_cols"|"remove_rows"|"remove_cols"|
    "set", args...) host commands for a driver loop; the dimensions are
    tracked so every command is valid at emission time."""
    rng = random.Random(seed)
    r, c = rows, cols
    active_r, active_c = 0, 0
    out = []
    for i in range(n_ops):
        roll = rng.random()
        if roll < 0.06:
            at = rng.randrange(r + 1)
            out.append(("insert_rows", at, 1))
            r += 1
        elif roll < 0.10:
            at = rng.randrange(c + 1)
            out.append(("insert_cols", at, 1))
            c += 1
        elif roll < 0.11 and r > 8:
            at = rng.randrange(r - 1)
            out.append(("remove_rows", at, 1))
            r -= 1
        elif roll < 0.12 and c > 8:
            at = rng.randrange(c - 1)
            out.append(("remove_cols", at, 1))
            c -= 1
        else:
            if rng.random() < 0.8:  # locality: stay near the active cell
                active_r = min(max(active_r + rng.randrange(-2, 3), 0),
                               r - 1)
                active_c = min(max(active_c + rng.randrange(-2, 3), 0),
                               c - 1)
            else:  # jump
                active_r, active_c = rng.randrange(r), rng.randrange(c)
            out.append(("set", active_r, active_c, i))
    return out


def directory_merge_script(n_ops: int, n_clients: int = 4, depth: int = 3,
                           fanout: int = 5, seed: int = 0):
    """Nested-subtree merge workload for SharedDirectory (BASELINE config
    #4): concurrent editors write keys into a tree of subdirectories
    (depth×fanout), with per-client working-directory locality, subtree
    creation, key deletes, and occasional whole-subtree clears.

    Returns [(client, path_tuple, command, *args)]."""
    rng = random.Random(seed)
    paths = [()]
    last = [()]
    for _ in range(depth):
        last = [p + (f"d{i}",) for p in last for i in range(fanout)]
        paths += last
    homes = [rng.choice(paths) for _ in range(n_clients)]
    out = []
    for i in range(n_ops):
        c = rng.randrange(n_clients)
        if rng.random() < 0.85:  # work near home
            path = homes[c]
        else:
            path = rng.choice(paths)
            homes[c] = path
        roll = rng.random()
        if roll < 0.80:
            out.append((c, path, "set", f"k{rng.randrange(32)}", i))
        elif roll < 0.90:
            out.append((c, path, "delete", f"k{rng.randrange(32)}"))
        elif roll < 0.97:
            sub = f"s{rng.randrange(8)}"
            out.append((c, path, "set_subdir_key", sub,
                        f"k{rng.randrange(8)}", i))
        else:
            out.append((c, path, "clear"))
    return out
