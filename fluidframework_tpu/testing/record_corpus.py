"""Record real multi-client sessions into pinned replay corpora.

Drives seeded workloads (testing/traces.py) from multiple clients over
the REAL alfred websocket + REST stack (server/tinylicious.py — the
LocalServer lambda pipeline behind actual sockets), then fetches the
sequenced op log back through alfred's own /deltas catch-up route and
writes it under tests/corpus/ with a pinned end-state digest
(testing/corpus.py). The replay digest is cross-checked against the
LIVE clients' end state at record time, so the checked-in pin holds the
replay harness and the recording session to the same truth.

Reference analog: the captured-log snapshot regression corpus,
packages/test/snapshots/src/replayMultipleFiles.ts:1.

Usage: python -m fluidframework_tpu.testing.record_corpus [outdir]
"""

from __future__ import annotations

import json
import os
import random
import sys
import time


def _wait_until(predicate, timeout=30.0, interval=0.01):
    deadline = time.time() + timeout
    while time.time() < deadline:
        if predicate():
            return True
        time.sleep(interval)
    return predicate()


def _session(server, doc_id: str, channel: str, channel_type: str,
             n_clients: int):
    from ..loader.container import Loader
    from ..loader.drivers.routerlicious import NetworkDocumentServiceFactory
    from ..server.tinylicious import DEFAULT_TENANT

    factory = NetworkDocumentServiceFactory(server.url, DEFAULT_TENANT)
    loader = Loader(factory)
    c1 = loader.create_detached(doc_id)
    ds = c1.runtime.create_datastore("default")
    types = {
        "sequence": "https://graph.microsoft.com/types/mergeTree/string",
        "items": "https://graph.microsoft.com/types/mergeTree"
                 "/numberSequence",
        "matrix": "https://graph.microsoft.com/types/sharedmatrix",
        "directory": "https://graph.microsoft.com/types/directory",
    }
    ch1 = ds.create_channel(channel, types[channel_type])
    c1.attach()
    containers = [c1]
    channels = [ch1]
    for _ in range(n_clients - 1):
        c = loader.resolve(doc_id)
        containers.append(c)
        channels.append(
            c.runtime.get_datastore("default").get_channel(channel))
    return containers, channels


def _settle(containers, check, timeout=30.0):
    assert _wait_until(check, timeout), "session did not converge"
    for c in containers:
        c.close()


def record_text(server, outdir: str, n_ops: int = 1500,
                seed: int = 2026) -> dict:
    """Two-editor keystroke-style text session with annotate sweeps."""
    containers, (t1, t2) = _session(server, "corpus-text", "text",
                                    "sequence", 2)
    rng = random.Random(seed)
    editors = [(containers[0], t1), (containers[1], t2)]
    for i in range(n_ops):
        c, t = editors[i % 2 if rng.random() < 0.7 else rng.randrange(2)]
        with c.op_lock:
            n = t.get_length()
            r = rng.random()
            if r < 0.7 or n < 10:
                pos = min(n, max(0, int(rng.gauss(n * 0.7, 4))))
                t.insert_text(pos, rng.choice("abcdefgh ,.!\n"))
            elif r < 0.85:
                a = rng.randrange(n - 2)
                t.remove_text(a, min(n, a + rng.randrange(1, 6)))
            else:
                a = rng.randrange(n - 2)
                t.annotate_range(a, min(n, a + rng.randrange(1, 9)),
                                 {"style": i % 5})
    _settle(containers, lambda: t1.get_text() == t2.get_text())
    return {"doc": "corpus-text", "channel": "text",
            "channel_type": "sequence", "workload": "keystroke",
            "seed": seed, "clients": 2,
            "live_state": {"text": t1.get_text()}}


def record_matrix(server, outdir: str, n_ops: int = 1200,
                  seed: int = 7) -> dict:
    from .traces import matrix_storm

    containers, (m1, m2) = _session(server, "corpus-matrix", "grid",
                                    "matrix", 2)
    with containers[0].op_lock:
        m1.insert_rows(0, 24)
        m1.insert_cols(0, 12)
    _wait_until(lambda: (m2.row_count, m2.col_count) == (24, 12))
    script = matrix_storm(24, 12, n_ops, seed=seed)
    rng = random.Random(seed + 1)
    mats = [(containers[0], m1), (containers[1], m2)]
    for cmd in script:
        c, m = mats[rng.randrange(2)]
        with c.op_lock:
            # The script tracks dimensions for a SERIAL session; across
            # two async clients a view can lag, so commands clamp to the
            # acting client's live dimensions (the log stays realistic —
            # that is what concurrent editors actually submit).
            r, co = m.row_count, m.col_count
            if cmd[0] == "set":
                if r and co:
                    m.set_cell(min(cmd[1], r - 1), min(cmd[2], co - 1),
                               cmd[3])
            elif cmd[0] == "insert_rows":
                m.insert_rows(min(cmd[1], r), cmd[2])
            elif cmd[0] == "insert_cols":
                m.insert_cols(min(cmd[1], co), cmd[2])
            elif cmd[0] == "remove_rows" and r > 2:
                m.remove_rows(min(cmd[1], r - 1), 1)
            elif cmd[0] == "remove_cols" and co > 2:
                m.remove_cols(min(cmd[1], co - 1), 1)
    _settle(containers,
            lambda: m1.extract() == m2.extract())
    return {"doc": "corpus-matrix", "channel": "grid",
            "channel_type": "matrix", "workload": "matrix_storm",
            "seed": seed, "clients": 2,
            "live_state": m1.extract()}


def record_directory(server, outdir: str, n_ops: int = 1200,
                     seed: int = 9) -> dict:
    from .traces import directory_merge_script

    containers, channels = _session(server, "corpus-dir", "dir",
                                    "directory", 4)
    script = directory_merge_script(n_ops, n_clients=4, seed=seed)

    def workdir(d, path):
        node = d.root
        for part in path:
            sub = node.get_sub_directory(part)
            if sub is None:
                sub = node.create_sub_directory(part)
            node = sub
        return node

    for cmd in script:
        cidx, path = cmd[0], cmd[1]
        c, d = containers[cidx], channels[cidx]
        with c.op_lock:
            wd = workdir(d, path)
            if cmd[2] == "set":
                wd.set(cmd[3], cmd[4])
            elif cmd[2] == "delete":
                wd.delete(cmd[3])
            elif cmd[2] == "set_subdir_key":
                sub = wd.get_sub_directory(cmd[3]) or \
                    wd.create_sub_directory(cmd[3])
                sub.set(cmd[4], cmd[5])
            else:
                wd.clear()
    d0 = channels[0]
    _settle(containers,
            lambda: all(d.root.to_dict() == d0.root.to_dict()
                        for d in channels))
    return {"doc": "corpus-dir", "channel": "dir",
            "channel_type": "directory", "workload": "directory_merge",
            "seed": seed, "clients": 4,
            "live_state": d0.root.to_dict()}


def record_items(server, outdir: str, n_ops: int = 1200,
                 seed: int = 13) -> dict:
    """Two-client number-sequence session: value-run inserts and range
    removes (the items-lane workload, round 5)."""
    containers, (s1, s2) = _session(
        server, "corpus-items", "nums", "items", 2)
    rng = random.Random(seed)
    seqs = [(containers[0], s1), (containers[1], s2)]
    for i in range(n_ops):
        c, s = seqs[rng.randrange(2)]
        with c.op_lock:
            n = s.get_item_count()
            if rng.random() < 0.72 or n < 6:
                at = rng.randrange(n + 1)
                s.insert_range(at, [i, i + 0.5])
            else:
                a = rng.randrange(n - 2)
                s.remove_range(a, min(n, a + rng.randrange(1, 4)))
    _settle(containers, lambda: s1.get_items() == s2.get_items())
    return {"doc": "corpus-items", "channel": "nums",
            "channel_type": "items", "workload": "number_sequence",
            "seed": seed, "clients": 2,
            "live_state": s1.get_items()}


def main(outdir: str | None = None, only: set | None = None) -> None:
    from ..core.platform import force_host_platform
    force_host_platform(8)

    from ..server.tinylicious import DEFAULT_TENANT, Tinylicious
    from ..loader.drivers.routerlicious import RestWrapper
    from . import corpus as C

    outdir = outdir or C.CORPUS_DIR
    os.makedirs(outdir, exist_ok=True)
    pins_path = os.path.join(outdir, "pins.json")
    pins = {}
    if only and os.path.exists(pins_path):
        with open(pins_path) as f:
            pins = json.load(f)  # partial re-record keeps other pins
    recorders = (record_text, record_matrix, record_directory,
                 record_items)
    names = {r.__name__.removeprefix("record_") for r in recorders}
    if only and only - names:
        raise SystemExit(f"unknown --only names {sorted(only - names)}; "
                         f"choose from {sorted(names)}")
    with Tinylicious() as server:
        rest = RestWrapper(server.url)
        for rec in recorders:
            name = rec.__name__.removeprefix("record_")
            if only and name not in only:
                continue
            header = rec(server, outdir)
            rows = rest.get(
                f"/deltas/{DEFAULT_TENANT}/{header['doc']}")["deltas"]
            live_state = header.pop("live_state")
            path = os.path.join(outdir, f"{header['workload']}.jsonl.gz")
            C.write_corpus(path, header, rows)
            # The pin must hold BOTH the recording and the replay
            # harness to the same truth: a fresh replica replaying the
            # checked-in log must reach the live clients' end state.
            hdr, rrows = C.read_corpus(path)
            chan = C.replay(hdr, rrows)
            replay_state = C.channel_state(hdr["channel_type"], chan)
            if hdr["channel_type"] == "sequence":
                assert replay_state["text"] == live_state["text"], \
                    "replayed text diverges from the live session"
            else:
                assert replay_state == live_state, \
                    f"{header['workload']}: replay diverges from live"
            pins[header["workload"]] = {
                "file": os.path.basename(path),
                "digest": C.digest(replay_state),
                "ops": len(rows),
                "recorded": time.strftime("%Y-%m-%d"),
            }
            print(f"recorded {header['workload']}: {len(rows)} rows -> "
                  f"{pins[header['workload']]['digest'][:16]}...")
    with open(pins_path, "w") as f:
        json.dump(pins, f, indent=2, sort_keys=True)
    print(f"pins written to {pins_path}")


if __name__ == "__main__":
    only = {a.removeprefix("--only=") for a in sys.argv[1:]
            if a.startswith("--only=")}
    dirs = [a for a in sys.argv[1:] if not a.startswith("--only=")]
    main(dirs[0] if dirs else None, only or None)
