"""Wire protocol: message types, summary trees, quorum, protocol state machine.

Capability parity with the reference's `protocol-definitions` + `protocol-base`
packages (reference: server/routerlicious/packages/protocol-definitions/src/protocol.ts,
protocol-base/src/{quorum,protocol}.ts).
"""

from .messages import (
    MessageType,
    ITrace,
    DocumentMessage,
    SequencedDocumentMessage,
    NackContent,
    Nack,
    SignalMessage,
    Boxcar,
    NACK_BAD_REF_SEQ,
    NACK_DUPLICATE,
)
from .summary import (
    SummaryType,
    SummaryTree,
    SummaryBlob,
    SummaryHandle,
    SummaryAttachment,
    summary_tree_to_dict,
    summary_tree_from_dict,
)
from .quorum import Quorum, QuorumProposal, SequencedClient
from .protocol_handler import ProtocolOpHandler, ProtocolState, ProtocolError
