"""Protocol op handler: the system-op state machine shared by client and server.

Capability parity with reference
`server/routerlicious/packages/protocol-base/src/protocol.ts:50`:
tracks (minimumSequenceNumber, sequenceNumber), routes join/leave/propose/
reject system ops into the Quorum, and exposes snapshot/load of protocol
state (attributes + quorum) for summaries. The client Container and the
server Scribe lambda both run one of these over the sequenced op stream.
"""

from __future__ import annotations

import json
from dataclasses import dataclass
from typing import Optional

from .messages import MessageType, SequencedDocumentMessage
from .quorum import Quorum


class ProtocolError(Exception):
    """Sequenced-stream invariant violation (gap, bad msn) — fatal for the
    replica; the delta manager must refetch rather than continue."""


@dataclass
class ProtocolState:
    sequence_number: int
    minimum_sequence_number: int
    quorum_snapshot: dict


class ProtocolOpHandler:
    def __init__(
        self,
        minimum_sequence_number: int = 0,
        sequence_number: int = 0,
        quorum: Optional[Quorum] = None,
    ):
        self.minimum_sequence_number = minimum_sequence_number
        self.sequence_number = sequence_number
        self.quorum = quorum if quorum is not None else Quorum()

    def process_message(self, message: SequencedDocumentMessage) -> None:
        if message.sequence_number <= self.sequence_number:
            return  # duplicate / already-processed (idempotent replay)
        if message.sequence_number != self.sequence_number + 1:
            raise ProtocolError(
                f"protocol gap: have {self.sequence_number}, "
                f"got {message.sequence_number}")
        if message.minimum_sequence_number >= message.sequence_number:
            raise ProtocolError(
                f"invalid msn {message.minimum_sequence_number} >= "
                f"seq {message.sequence_number}")
        self.sequence_number = message.sequence_number

        mtype = message.type
        if mtype == MessageType.CLIENT_JOIN:
            detail = _system_data(message)
            client_id = detail.get("clientId")
            self.quorum.add_member(
                client_id, message.sequence_number, detail.get("detail"))
        elif mtype == MessageType.CLIENT_LEAVE:
            detail = _system_data(message)
            client_id = detail if isinstance(detail, str) else detail.get("clientId")
            self.quorum.remove_member(client_id)
        elif mtype == MessageType.PROPOSE:
            contents = message.contents
            if isinstance(contents, str):
                contents = json.loads(contents)
            self.quorum.add_proposal(
                contents["key"], contents["value"], message.sequence_number)
        elif mtype == MessageType.REJECT:
            self.quorum.reject_proposal(message.client_id, int(message.contents))

        # MSN advance (msn < seq is validated above, so a proposal in this
        # very message can never self-approve).
        if message.minimum_sequence_number > self.minimum_sequence_number:
            self.minimum_sequence_number = message.minimum_sequence_number
            self.quorum.update_minimum_sequence_number(
                message.minimum_sequence_number)

    # -- snapshot/load -----------------------------------------------------
    def snapshot(self) -> ProtocolState:
        return ProtocolState(
            sequence_number=self.sequence_number,
            minimum_sequence_number=self.minimum_sequence_number,
            quorum_snapshot=self.quorum.snapshot(),
        )

    @staticmethod
    def load(state: ProtocolState) -> "ProtocolOpHandler":
        return ProtocolOpHandler(
            minimum_sequence_number=state.minimum_sequence_number,
            sequence_number=state.sequence_number,
            quorum=Quorum.load(state.quorum_snapshot),
        )


def _system_data(message: SequencedDocumentMessage):
    """Join/leave details ride the system `data` field as JSON (reference
    IDocumentSystemMessage.data); fall back to contents for in-process use."""
    if message.data is not None:
        return json.loads(message.data)
    return message.contents
