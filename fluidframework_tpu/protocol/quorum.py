"""Quorum: membership + consensus-by-msn proposals.

Capability parity with reference
`server/routerlicious/packages/protocol-base/src/quorum.ts:70-307`:
- membership: ClientJoin/ClientLeave system ops add/remove sequenced clients;
- proposals: a Propose op creates a pending proposal; it is *approved* once
  the minimum sequence number passes its sequence number with no Reject ops
  (quorum.ts:284-307), i.e. every connected client has seen it and none
  objected. Used for code upgrades and (in our runtime) config consensus.

Same state machine runs client-side (Container) and server-side (Scribe).
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Any, Callable, Dict, List, Optional


@dataclass
class SequencedClient:
    client_id: str
    sequence_number: int  # seq of the join op
    details: Any = None   # capabilities / user info


@dataclass
class QuorumProposal:
    sequence_number: int
    key: str
    value: Any
    approval_sequence_number: Optional[int] = None  # set on approval
    rejections: List[str] = field(default_factory=list)

    @property
    def approved(self) -> bool:
        return self.approval_sequence_number is not None


class Quorum:
    """Tracks members, pending proposals, and approved values."""

    def __init__(
        self,
        members: Optional[Dict[str, SequencedClient]] = None,
        proposals: Optional[Dict[int, QuorumProposal]] = None,
        values: Optional[Dict[str, Any]] = None,
    ):
        self.members: Dict[str, SequencedClient] = dict(members or {})
        self.proposals: Dict[int, QuorumProposal] = dict(proposals or {})
        self.values: Dict[str, Any] = dict(values or {})
        self._listeners: Dict[str, List[Callable]] = {}

    # -- events ------------------------------------------------------------
    def on(self, event: str, fn: Callable) -> None:
        self._listeners.setdefault(event, []).append(fn)

    def off(self, event: str, fn: Callable) -> None:
        """Removal path for on(): quorum outlives individual observers
        (summarizer clients come and go), so observers must detach."""
        listeners = self._listeners.get(event)
        if listeners and fn in listeners:
            listeners.remove(fn)

    def _emit(self, event: str, *args) -> None:
        # Iterate a copy: a listener may off() itself mid-emit (the
        # one-shot pattern); mutating the live list would skip siblings.
        for fn in list(self._listeners.get(event, [])):
            fn(*args)

    # -- membership --------------------------------------------------------
    def add_member(self, client_id: str, sequence_number: int, details: Any = None):
        client = SequencedClient(client_id, sequence_number, details)
        self.members[client_id] = client
        self._emit("addMember", client_id, client)

    def remove_member(self, client_id: str) -> None:
        if client_id in self.members:
            del self.members[client_id]
            self._emit("removeMember", client_id)

    def get_member(self, client_id: str) -> Optional[SequencedClient]:
        return self.members.get(client_id)

    # -- proposals ---------------------------------------------------------
    def add_proposal(self, key: str, value: Any, sequence_number: int) -> None:
        self.proposals[sequence_number] = QuorumProposal(sequence_number, key, value)
        self._emit("addProposal", key, value, sequence_number)

    def reject_proposal(self, client_id: str, proposal_seq: int) -> None:
        prop = self.proposals.get(proposal_seq)
        if prop is not None and not prop.approved:
            prop.rejections.append(client_id)
            self._emit("rejectProposal", proposal_seq, prop.key, prop.value, client_id)

    def update_minimum_sequence_number(self, msn: int) -> None:
        """Approve / drop pending proposals the MSN has passed (quorum.ts:284-307)."""
        for seq in sorted(self.proposals):
            prop = self.proposals[seq]
            if prop.approved or seq > msn:
                continue
            if prop.rejections:
                del self.proposals[seq]
                self._emit("dropProposal", prop.key, prop.value, seq)
            else:
                prop.approval_sequence_number = msn
                self.values[prop.key] = prop.value
                del self.proposals[seq]
                self._emit("approveProposal", seq, prop.key, prop.value, msn)

    def get(self, key: str) -> Any:
        return self.values.get(key)

    def has(self, key: str) -> bool:
        return key in self.values

    # -- snapshot ----------------------------------------------------------
    def snapshot(self) -> dict:
        return {
            "members": [
                [cid, {"sequenceNumber": m.sequence_number, "details": m.details}]
                for cid, m in sorted(self.members.items())
            ],
            "proposals": [
                [seq, {"key": p.key, "value": p.value, "rejections": list(p.rejections)}]
                for seq, p in sorted(self.proposals.items())
            ],
            "values": [[k, v] for k, v in sorted(self.values.items())],
        }

    @staticmethod
    def load(snap: dict) -> "Quorum":
        q = Quorum()
        for cid, m in snap.get("members", []):
            q.members[cid] = SequencedClient(cid, m["sequenceNumber"], m.get("details"))
        for seq, p in snap.get("proposals", []):
            prop = QuorumProposal(seq, p["key"], p["value"])
            prop.rejections = list(p.get("rejections", []))
            q.proposals[seq] = prop
        for k, v in snap.get("values", []):
            q.values[k] = v
        return q
