"""Summary (snapshot) tree types — the durable checkpoint format.

Capability parity with reference
`server/routerlicious/packages/protocol-definitions/src/summary.ts:51`:
a git-like tree of blobs/trees/handles. A *handle* points at an unchanged
subtree of the previous summary so incremental summaries only upload deltas.

The content-addressed store that persists these lives in
`fluidframework_tpu.server.storage` (gitrest/historian equivalent).
"""

from __future__ import annotations

import hashlib
from dataclasses import dataclass, field
from typing import Dict, Union


class SummaryType:
    TREE = "tree"
    BLOB = "blob"
    HANDLE = "handle"
    ATTACHMENT = "attachment"


@dataclass
class SummaryBlob:
    content: Union[str, bytes]
    type: str = SummaryType.BLOB


class LazySummaryBlob(SummaryBlob):
    """A blob whose content fetches on first access (lazy snapshot load:
    the reference defers 10k-char body chunks, snapshotV1.ts:33-40 +
    sequence.ts:489). isinstance(x, SummaryBlob) holds; `.content` is a
    property resolved through the fetch callable, so consumers that never
    touch a chunk never pay its transfer."""

    def __init__(self, fetch):
        # No super().__init__: `content` stays a CLASS-level property
        # (the dataclass would write an instance attribute over it).
        self._fetch = fetch
        self._content = None
        self.type = SummaryType.BLOB

    @property
    def content(self):
        if self._content is None:
            self._content = self._fetch()
        return self._content


@dataclass
class SummaryHandle:
    """Reference to a path in the *previous* summary (incremental summaries)."""

    handle: str  # path like "/dataStores/ds1/root"
    handle_type: str = SummaryType.TREE
    type: str = SummaryType.HANDLE


@dataclass
class SummaryAttachment:
    """Reference to an already-uploaded blob by storage id (blob manager)."""

    id: str
    type: str = SummaryType.ATTACHMENT


@dataclass
class SummaryTree:
    entries: Dict[str, "SummaryObject"] = field(default_factory=dict)
    type: str = SummaryType.TREE
    unreferenced: bool = False  # GC mark (reference ISummaryTree.unreferenced)

    def add_blob(self, key: str, content: Union[str, bytes]) -> "SummaryTree":
        self.entries[key] = SummaryBlob(content)
        return self

    def add_tree(self, key: str) -> "SummaryTree":
        tree = SummaryTree()
        self.entries[key] = tree
        return tree

    def add_handle(self, key: str, handle: str,
                   handle_type: str = SummaryType.TREE) -> "SummaryTree":
        self.entries[key] = SummaryHandle(handle, handle_type)
        return self


SummaryObject = Union[SummaryTree, SummaryBlob, SummaryHandle, SummaryAttachment]


def summary_tree_to_dict(node: SummaryObject):
    """Plain-dict encoding (serialization form for storage/drivers)."""
    if isinstance(node, SummaryTree):
        return {
            "type": SummaryType.TREE,
            "entries": {k: summary_tree_to_dict(v) for k, v in node.entries.items()},
            **({"unreferenced": True} if node.unreferenced else {}),
        }
    if isinstance(node, SummaryBlob):
        content = node.content
        if isinstance(content, bytes):
            return {"type": SummaryType.BLOB, "content": content.hex(), "encoding": "hex"}
        return {"type": SummaryType.BLOB, "content": content, "encoding": "utf-8"}
    if isinstance(node, SummaryHandle):
        return {"type": SummaryType.HANDLE, "handle": node.handle,
                "handleType": node.handle_type}
    if isinstance(node, SummaryAttachment):
        return {"type": SummaryType.ATTACHMENT, "id": node.id}
    raise TypeError(f"not a summary object: {type(node)!r}")


def summary_tree_from_dict(data) -> SummaryObject:
    t = data["type"]
    if t == SummaryType.TREE:
        tree = SummaryTree(unreferenced=bool(data.get("unreferenced")))
        tree.entries = {k: summary_tree_from_dict(v) for k, v in data["entries"].items()}
        return tree
    if t == SummaryType.BLOB:
        if data.get("encoding") == "hex":
            return SummaryBlob(bytes.fromhex(data["content"]))
        return SummaryBlob(data["content"])
    if t == SummaryType.HANDLE:
        return SummaryHandle(data["handle"], data.get("handleType", SummaryType.TREE))
    if t == SummaryType.ATTACHMENT:
        return SummaryAttachment(data["id"])
    raise ValueError(f"unknown summary type {t!r}")


def blob_sha(content: Union[str, bytes]) -> str:
    """Content address for blobs (git-style but sha256 of raw content)."""
    if isinstance(content, str):
        content = content.encode("utf-8")
    return hashlib.sha256(content).hexdigest()
