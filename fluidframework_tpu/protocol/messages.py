"""Wire message types for the total-order broadcast protocol.

Capability parity with reference
`server/routerlicious/packages/protocol-definitions/src/protocol.ts:6-180`:
the unsequenced client->server `IDocumentMessage`, the server-stamped
`ISequencedDocumentMessage`, nacks, signals, and boxcar batching
(`services-core/src/lambdas.ts:75-120`).

Design notes (TPU-first): these dataclasses are the *host-side* view. The
hot path never loops over them one by one — `server.ticket_kernel` and
`mergetree.kernel` consume packed int32 tensors built by
`mergetree.oppack.pack_ops`; these objects are the interchange /
serialization form at the edges (drivers, storage, tests).
"""

from __future__ import annotations

import json
import time
from dataclasses import dataclass, field, asdict
from typing import Any, List, Optional


class MessageType:
    """Op types carried over the ordered log (reference protocol.ts:6-48)."""

    NO_OP = "noop"
    CLIENT_JOIN = "join"
    CLIENT_LEAVE = "leave"
    PROPOSE = "propose"
    REJECT = "reject"
    SUMMARIZE = "summarize"
    SUMMARY_ACK = "summaryAck"
    SUMMARY_NACK = "summaryNack"
    OPERATION = "op"
    CHUNKED_OP = "chunkedOp"
    SAVE = "saveOp"
    NO_CLIENT = "noClient"
    REMOTE_HELP = "remoteHelp"
    ROUND_TRIP = "tripComplete"
    CONTROL = "control"

    SYSTEM_TYPES = frozenset(
        {CLIENT_JOIN, CLIENT_LEAVE, PROPOSE, REJECT, NO_CLIENT,
         SUMMARY_ACK, SUMMARY_NACK}
    )


@dataclass
class ITrace:
    """Per-hop latency trace stamped by each service (protocol.ts:50-62)."""

    service: str
    action: str
    timestamp: float

    @staticmethod
    def now(service: str, action: str) -> "ITrace":
        return ITrace(service, action, time.time() * 1000.0)


@dataclass
class DocumentMessage:
    """A client-submitted, not-yet-sequenced op (reference IDocumentMessage)."""

    client_sequence_number: int
    reference_sequence_number: int
    type: str
    contents: Any = None
    metadata: Any = None
    server_metadata: Any = None
    traces: List[ITrace] = field(default_factory=list)
    # System messages carry an extra opaque data payload (IDocumentSystemMessage).
    data: Optional[str] = None

    def to_json(self) -> str:
        return json.dumps(asdict(self), default=str)


@dataclass
class SequencedDocumentMessage:
    """An op stamped by the sequencer (reference ISequencedDocumentMessage).

    `client_id` is None for server-generated messages (e.g. NoClient).
    """

    client_id: Optional[str]
    sequence_number: int
    minimum_sequence_number: int
    client_sequence_number: int
    reference_sequence_number: int
    type: str
    contents: Any = None
    metadata: Any = None
    server_metadata: Any = None
    timestamp: float = 0.0
    term: int = 1
    traces: List[ITrace] = field(default_factory=list)
    data: Optional[str] = None
    # Content added by the sequencer itself (ISequencedDocumentAugmentedMessage).
    additional_content: Optional[str] = None

    def to_json(self) -> str:
        return json.dumps(asdict(self), default=str)

    @staticmethod
    def from_document_message(
        msg: DocumentMessage,
        client_id: Optional[str],
        sequence_number: int,
        minimum_sequence_number: int,
        timestamp: Optional[float] = None,
    ) -> "SequencedDocumentMessage":
        return SequencedDocumentMessage(
            client_id=client_id,
            sequence_number=sequence_number,
            minimum_sequence_number=minimum_sequence_number,
            client_sequence_number=msg.client_sequence_number,
            reference_sequence_number=msg.reference_sequence_number,
            type=msg.type,
            contents=msg.contents,
            metadata=msg.metadata,
            server_metadata=msg.server_metadata,
            timestamp=time.time() * 1000.0 if timestamp is None else timestamp,
            traces=list(msg.traces),
            data=msg.data,
        )


# Nack reason codes (reference INackContent semantics: deli/lambda.ts nacks).
NACK_BAD_REF_SEQ = 400
NACK_DUPLICATE = 409
NACK_TOO_LARGE = 413
NACK_THROTTLED = 429
NACK_NOT_WRITER = 403
# Admission-control DEGRADE: the server is refusing ingest entirely
# until pressure drains (server/admission.py). Drivers honor the
# retry_after exactly like a 429 — resubmitting sooner cannot succeed.
NACK_SERVICE_UNAVAILABLE = 503


@dataclass
class NackContent:
    code: int
    message: str = ""
    retry_after_s: Optional[float] = None


@dataclass
class Nack:
    """Rejection of a submitted op (reference INack, protocol.ts:64-74)."""

    operation: Optional[DocumentMessage]
    sequence_number: int
    content: NackContent


def op_size(msg: "DocumentMessage") -> int:
    """CHEAP lower bound on one client message's payload size — the
    op-size ceiling (NACK_TOO_LARGE) screens with this at the in-process
    front door without re-serializing every op. Follows the envelope
    "contents" chain (store -> channel -> op) summing string payloads at
    each level, which covers every shape that actually gets big: text
    inserts, LWW values, chunked-op pieces, system `data`. It is a
    screen, not an exact measure — the network ingress additionally
    applies `op_size_exact` to wire-parsed messages."""
    def _bytes(s: str) -> int:
        # The wire serializer is json.dumps with ensure_ascii, so every
        # non-ASCII char costs 6+ bytes (\\uXXXX). unicode_escape is a
        # cheap LOWER bound of that (4-10 bytes/char escaped, ASCII ~1:1)
        # and far tighter than char count, keeping the front-door screen
        # close to what the websocket ingress will bill exactly.
        try:
            return len(s.encode("unicode_escape"))
        except UnicodeEncodeError:  # defensive: bill 1 byte/char
            return len(s)

    n = _bytes(msg.data) if isinstance(msg.data, str) else 0
    node = msg.contents
    depth = 0
    while isinstance(node, dict) and depth < 8:
        for key, value in node.items():
            # The followed "contents" tail is measured at ITS level (or as
            # the final string) — counting it here too would double-bill.
            if key != "contents" and isinstance(value, str):
                n += _bytes(value)
        node = node.get("contents")
        depth += 1
    if isinstance(node, str):
        n += _bytes(node)
    return n


def op_size_exact(msg: "DocumentMessage") -> int:
    """Exact serialized payload size (full dumps) — the network ingress
    measure, where one extra serialization is noise next to the socket
    I/O. Unserializable in-process payloads screen as 0 (they never
    arrive via the wire)."""
    try:
        # json.dumps default ensure_ascii escapes non-ASCII, so its char
        # count IS its byte count — and `data` is serialized inside the
        # same dumps on the wire (wire.py), so it is billed escaped too.
        n = len(json.dumps(msg.contents)) if msg.contents is not None else 0
        if msg.data is not None:
            n += len(json.dumps(msg.data)) - 2  # minus the quotes
        return n
    except (TypeError, ValueError):
        return 0


@dataclass
class SignalMessage:
    """Transient, unsequenced client-to-clients message (reference ISignalMessage)."""

    client_id: Optional[str]
    content: Any


@dataclass
class Boxcar:
    """A batch of raw client messages for one document riding one log record.

    Reference: IBoxcarMessage + extractBoxcar (services-core/src/lambdas.ts:75-120).
    Boxcarring amortizes log-append overhead; the TPU sequencer goes further and
    tickets whole boxcars as one tensor op (server/ticket_kernel.py).
    """

    tenant_id: str
    document_id: str
    client_id: Optional[str]
    contents: List[DocumentMessage] = field(default_factory=list)


def extract_boxcar(record: Any) -> Boxcar:
    """Normalize a raw log record into a Boxcar (single messages get wrapped)."""
    if isinstance(record, Boxcar):
        return record
    if isinstance(record, DocumentMessage):
        return Boxcar(tenant_id="", document_id="", client_id=None, contents=[record])
    raise TypeError(f"cannot extract boxcar from {type(record)!r}")
