"""AgentScheduler: distributed task assignment among connected clients.

Capability parity with reference packages/framework/agent-scheduler/src/
scheduler.ts:34,106 — tasks are claimed through a ConsensusRegisterCollection
(first sequenced write wins); each client registers the tasks it can run;
when the current assignee leaves, volunteers race to re-claim and exactly
one wins. The flagship consumer is summarizer election's cousin: background
work like intelligence agents (SURVEY.md §2.6 task parallelism).
"""

from __future__ import annotations

from typing import Callable, Dict, List, Optional

from ..dds.register_collection import READ_ATOMIC, ConsensusRegisterCollection

UNASSIGNED = ""


class AgentScheduler:
    def __init__(self, container, registers: ConsensusRegisterCollection):
        self.container = container
        self.registers = registers
        # task id -> worker callback we volunteered with
        self._volunteered: Dict[str, Callable[[], None]] = {}
        self._running: Dict[str, bool] = {}
        registers.on("atomicChanged", self._on_register_changed)
        container.audience.on("removeMember", self._on_member_left)

    # -- api ---------------------------------------------------------------
    def pick(self, task_id: str, worker: Callable[[], None]) -> None:
        """Volunteer for a task: the first client whose claim sequences wins
        and runs `worker`; others stand by for takeover (scheduler.pick)."""
        self._volunteered[task_id] = worker
        current = self.registers.read(task_id, READ_ATOMIC)
        if current in (None, UNASSIGNED):
            self._claim(task_id)
        # else: standing by; takeover happens on removeMember

    def release(self, task_id: str) -> None:
        """Stop volunteering; if we hold the task, give it up."""
        self._volunteered.pop(task_id, None)
        if self.picked(task_id):
            self._running.pop(task_id, None)
            self.registers.write(task_id, UNASSIGNED)

    def picked(self, task_id: str) -> bool:
        return (self.registers.read(task_id, READ_ATOMIC)
                == self._client_id())

    def picked_tasks(self) -> List[str]:
        return [t for t in self.registers.keys() if self.picked(t)]

    # -- internals ---------------------------------------------------------
    def _client_id(self) -> Optional[str]:
        return self.container.delta_manager.client_id

    def _claim(self, task_id: str) -> None:
        me = self._client_id()
        if me is not None:
            self.registers.write(task_id, me)

    def _on_register_changed(self, key: str, value, local: bool) -> None:
        if key not in self._volunteered:
            return
        if value == self._client_id() and not self._running.get(key):
            self._running[key] = True
            self._volunteered[key]()
        elif value in (None, UNASSIGNED) and not local:
            self._claim(key)  # released: race to re-claim

    def _on_member_left(self, client_id: str) -> None:
        for task_id in list(self._volunteered):
            if self.registers.read(task_id, READ_ATOMIC) == client_id:
                self._claim(task_id)
