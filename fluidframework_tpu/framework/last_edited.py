"""Last-edited tracker.

Capability parity with reference packages/framework/last-edited-experimental
(`lastEditedTracker.ts`, `setup.ts`): tracks who edited the container last
and when, stored in a SharedSummaryBlock (no ops of its own — the detail
rides summaries only), updated from an "op" listener on the container that
discards non-edit messages, resolving the editing user through the quorum.
"""

from __future__ import annotations

from typing import Any, Callable, Optional

from ..dds.summary_block import SharedSummaryBlock
from ..protocol.messages import MessageType, SequencedDocumentMessage

LAST_EDITED_KEY = "lastEditDetails"


def should_discard_message_default(message: SequencedDocumentMessage) -> bool:
    """Only real edits count (reference shouldDiscardMessageDefault: keep
    Attach + FluidDataStoreOp, discard the rest)."""
    return message.type not in (MessageType.OPERATION,
                                MessageType.CHUNKED_OP)


class LastEditedTracker:
    """Reference LastEditedTracker over a SharedSummaryBlock."""

    def __init__(self, summary_block: SharedSummaryBlock):
        self.summary_block = summary_block

    @property
    def IFluidLastEditedTracker(self) -> "LastEditedTracker":
        return self

    def get_last_edit_details(self) -> Optional[dict]:
        return self.summary_block.get(LAST_EDITED_KEY)

    def update_last_edit_details(self, details: dict) -> None:
        self.summary_block.set(LAST_EDITED_KEY, details)


def setup_last_edited_tracking(
        tracker: LastEditedTracker, container,
        should_discard: Callable[[SequencedDocumentMessage], bool]
        = should_discard_message_default) -> None:
    """Wire a container's op stream into the tracker (reference
    setupLastEditedTrackerForContainer): per kept message, resolve the
    sender in the quorum for user details and record (user, timestamp)."""

    def on_op(message: SequencedDocumentMessage, *_rest: Any) -> None:
        if should_discard(message):
            return
        member = container.protocol.quorum.get_member(message.client_id)
        if member is None:
            return
        details = member.details if isinstance(member.details, dict) else {}
        tracker.update_last_edit_details({
            "clientId": message.client_id,
            "user": details.get("user", {}),
            "timestamp": message.timestamp,
            "sequenceNumber": message.sequence_number,
        })

    container.on("op", on_op)
