"""Framework layer: aqueduct data objects, runtime factories, undo-redo,
interceptions, agent scheduler, DI, request routing.

Parity: reference packages/framework/* (SURVEY.md §2.4)."""

from .agent_scheduler import AgentScheduler
from .container_factories import (
    BaseContainerRuntimeFactory,
    ContainerRuntimeFactoryWithDefaultDataStore,
)
from .data_object import DataObject, DataObjectFactory, PureDataObject
from .last_edited import (LastEditedTracker, setup_last_edited_tracking)
from .lazy_data_object import (LazyLoadedDataObject,
                               LazyLoadedDataObjectFactory)
from .views import (MountableView, SyncedDataObject, ViewAdapter,
                    use_synced_state)
from .interceptions import (
    create_shared_map_with_interception,
    create_shared_string_with_interception,
)
from .request_handler import (
    RequestHandlerChain,
    RequestParser,
    datastore_route_handler,
)
from .synthesize import DependencyContainer
from .undo_redo import (
    SharedMapUndoRedoHandler,
    SharedMatrixUndoRedoHandler,
    SharedSegmentSequenceUndoRedoHandler,
    UndoRedoStackManager,
)

__all__ = [
    "AgentScheduler",
    "BaseContainerRuntimeFactory",
    "ContainerRuntimeFactoryWithDefaultDataStore",
    "DataObject", "DataObjectFactory", "PureDataObject",
    "create_shared_map_with_interception",
    "create_shared_string_with_interception",
    "RequestHandlerChain", "RequestParser", "datastore_route_handler",
    "DependencyContainer",
    "SharedMapUndoRedoHandler", "SharedMatrixUndoRedoHandler",
    "SharedSegmentSequenceUndoRedoHandler", "UndoRedoStackManager",
    "LastEditedTracker", "setup_last_edited_tracking",
    "LazyLoadedDataObject", "LazyLoadedDataObjectFactory",
    "MountableView", "SyncedDataObject", "ViewAdapter", "use_synced_state",
]
