"""Undo-redo: stack-of-stacks revertible manager + DDS handlers.

Capability parity with reference packages/framework/undo-redo (README:1-13):
- UndoRedoStackManager groups local changes into operations (open/close);
  undo pops an operation and reverts it, with the reverts themselves
  captured onto the redo stack (and vice versa).
- SharedMapUndoRedoHandler / SharedSegmentSequenceUndoRedoHandler subscribe
  to local DDS events and push revertibles.
"""

from __future__ import annotations

from typing import Any, Callable, List, Optional


class UndoRedoStackManager:
    MODE_NONE, MODE_UNDO, MODE_REDO = 0, 1, 2

    def __init__(self):
        self.undo_stack: List[List[Callable[[], None]]] = []
        self.redo_stack: List[List[Callable[[], None]]] = []
        self._open = False
        self._mode = self.MODE_NONE

    # -- operation grouping ------------------------------------------------
    def open_current_operation(self) -> None:
        """Group subsequent pushes into one undoable operation until
        close_current_operation (reference openCurrentOperation)."""
        self._current_stack().append([])
        self._open = True

    def close_current_operation(self) -> None:
        self._open = False

    def push(self, revert: Callable[[], None]) -> None:
        """Record a revertible for the most recent local change."""
        stack = self._current_stack()
        if self._mode == self.MODE_NONE and not self._open:
            stack.append([revert])
        else:
            if not stack:
                stack.append([])
            stack[-1].append(revert)
        if self._mode == self.MODE_NONE:
            # A fresh local change invalidates the redo future.
            self.redo_stack.clear()

    # -- undo / redo -------------------------------------------------------
    def undo_operation(self) -> bool:
        if not self.undo_stack:
            return False
        operation = self.undo_stack.pop()
        self._mode = self.MODE_UNDO
        self.redo_stack.append([])
        try:
            for revert in reversed(operation):
                revert()
        finally:
            self._mode = self.MODE_NONE
        return True

    def redo_operation(self) -> bool:
        if not self.redo_stack:
            return False
        operation = self.redo_stack.pop()
        self._mode = self.MODE_REDO
        self.undo_stack.append([])
        try:
            for revert in reversed(operation):
                revert()
        finally:
            self._mode = self.MODE_NONE
        return True

    # -- internals ---------------------------------------------------------
    def _current_stack(self) -> List[List[Callable[[], None]]]:
        if self._mode == self.MODE_UNDO:
            return self.redo_stack
        return self.undo_stack


class SharedMapUndoRedoHandler:
    """Pushes a revertible per local SharedMap change (reference
    sharedMapUndoRedoHandler). previous==MISSING reverts to delete."""

    def __init__(self, manager: UndoRedoStackManager):
        self.manager = manager

    def attach(self, shared_map) -> None:
        from ..dds.map import MISSING

        def on_value_changed(key, local, previous=MISSING):
            if not local:
                return

            def revert():
                if previous is MISSING:
                    shared_map.delete(key)
                else:
                    shared_map.set(key, previous)

            self.manager.push(revert)

        shared_map.on("valueChanged", on_value_changed)


class SharedSegmentSequenceUndoRedoHandler:
    """Pushes revertibles for local sequence deltas: insert -> remove,
    remove -> reinsert captured text, annotate -> restore propertyDeltas
    (reference sequenceHandler)."""

    def __init__(self, manager: UndoRedoStackManager):
        self.manager = manager

    def attach(self, sequence) -> None:
        def on_delta(args, local):
            if not local:
                return
            op = args.get("op")
            if op == "insert":
                pos, text = args["pos"], args["text"]

                def revert_insert():
                    sequence.remove_text(pos, pos + len(text))

                self.manager.push(revert_insert)
            elif op == "remove" and "text" in args:
                start, text = args["start"], args["text"]

                def revert_remove():
                    sequence.insert_text(start, text)

                self.manager.push(revert_remove)
            elif op == "annotate" and args.get("propertyDeltas") is not None:
                deltas = args["propertyDeltas"]

                def revert_annotate():
                    for s, e, old in deltas:
                        sequence.annotate_range(s, e, dict(old))

                self.manager.push(revert_annotate)

        sequence.on("sequenceDelta", on_delta)


class SharedMatrixUndoRedoHandler:
    """Pushes revertibles for local SharedMatrix changes (reference
    matrix/src/undoprovider.ts): cell set -> restore previous value;
    row/col insert -> remove them; row/col remove -> reinsert + restore the
    captured cells by surviving-axis stable ids."""

    def __init__(self, manager: UndoRedoStackManager):
        self.manager = manager

    def attach(self, matrix) -> None:
        def on_cell(row, col, value, local, previous=None):
            if not local or row is None:
                return

            def revert_cell():
                matrix.set_cell(row, col, previous)

            self.manager.push(revert_cell)

        def on_axis(pos, count, local, captured=None, *, axis):
            if not local:
                return
            if count > 0:
                def revert_insert():
                    if axis == "rows":
                        matrix.remove_rows(pos, count)
                    else:
                        matrix.remove_cols(pos, count)
                self.manager.push(revert_insert)
            elif captured is not None:
                def revert_remove():
                    if axis == "rows":
                        matrix.restore_rows(pos, captured)
                    else:
                        matrix.restore_cols(pos, captured)
                self.manager.push(revert_remove)

        matrix.on("cellChanged", on_cell)
        matrix.on("rowsChanged",
                  lambda pos, count, local, captured=None:
                  on_axis(pos, count, local, captured, axis="rows"))
        matrix.on("colsChanged",
                  lambda pos, count, local, captured=None:
                  on_axis(pos, count, local, captured, axis="cols"))
