"""Lazy-loaded data objects.

Capability parity with reference packages/framework/data-object-base
(`lazyLoadedDataObject.ts`, `lazyLoadedDataObjectFactory.ts`): a data
object whose expensive initialization (channel realization, view setup) is
deferred until first use — the container loads its summary without paying
for stores nobody has requested yet (the reference's lazy
FluidDataStoreContext.realize analog at the framework layer).
"""

from __future__ import annotations

from typing import Any, Callable, Optional

from .data_object import DataObjectFactory, PureDataObject


class LazyLoadedDataObject(PureDataObject):
    """Subclasses implement `realize()` (first-use init) instead of the
    eager initializing hooks. `instance()` triggers realization."""

    def __init__(self, store):
        super().__init__(store)
        self._realized = False

    def realize(self) -> None:
        """First-use initialization hook."""

    def instance(self) -> "LazyLoadedDataObject":
        if not self._realized:
            self._realized = True
            self.realize()
        return self

    @property
    def realized(self) -> bool:
        return self._realized


class LazyLoadedDataObjectFactory(DataObjectFactory):
    """Creates the store eagerly (it must exist in the summary) but defers
    the data object's realize() until the first `get`."""

    def __init__(self, type_name: str, data_object_class=LazyLoadedDataObject):
        super().__init__(type_name, data_object_class)
        self._cache: dict = {}

    def get(self, container_runtime, store_id: str) -> LazyLoadedDataObject:
        key = (id(container_runtime), store_id)
        if key not in self._cache:
            obj = self.data_object_class(
                container_runtime.get_datastore(store_id))
            self._cache[key] = obj
        return self._cache[key].instance()
