"""View layer: uniform render adapters + reactive state bindings.

Capability parity with reference packages/framework/{view-interfaces,
view-adapters, react}: the reference defines IFluidHTMLView /
IFluidMountableView (feature-detected render surfaces), HTMLViewAdapter /
MountableView (wrap *any* view-providing object uniformly and keep it
mounted across host moves), and the react bindings (useStateFluid /
SyncedDataObject — local view state two-way-synced with DDS state).

There is no DOM here; the render target is a host-provided sink callable.
The contracts are preserved: feature detection over duck-typed
`render()` / `IFluidRenderable`, adapter-managed subscriptions with
re-render on every remote or local change, and `use_synced_state` —
a (value, setter) pair bound to a SharedMap key that re-renders observers
on convergence, the functional-react analog.
"""

from __future__ import annotations

from typing import Any, Callable, Dict, List, Optional, Tuple

# DDS change events an adapter watches (per-type; feature-detected).
_CHANGE_EVENTS = ("valueChanged", "sequenceDelta", "clear", "cellChanged",
                  "incremented", "containedValueChanged")


class IFluidRenderable:
    """Marker base: objects with a `render() -> Any` view surface
    (reference IFluidHTMLView.render)."""

    def render(self) -> Any:
        raise NotImplementedError


def get_renderable(obj: Any) -> Optional[Callable[[], Any]]:
    """Feature detection (reference IProvide pattern): an object offers a
    view if it implements render(), or exposes one via `IFluidRenderable`."""
    provided = getattr(obj, "IFluidRenderable", None)
    if provided is not None and provided is not obj:
        return get_renderable(provided)
    render = getattr(obj, "render", None)
    return render if callable(render) else None


class ViewAdapter:
    """Wraps any view-providing data object; keeps a host sink updated
    (reference HTMLViewAdapter: probes the object's view capability and
    re-renders into the element on every change)."""

    def __init__(self, obj: Any):
        self.render_fn = get_renderable(obj)
        if self.render_fn is None:
            raise TypeError(f"{type(obj).__name__} provides no view surface")
        self.obj = obj
        self.sink: Optional[Callable[[Any], None]] = None
        self._subscribed: List[Any] = []

    # -- mount lifecycle (IFluidMountableView mount/unmount) ---------------
    def mount(self, sink: Callable[[Any], None]) -> None:
        self.sink = sink
        self._subscribe()
        self.refresh()

    def unmount(self) -> None:
        self.sink = None
        # Subscriptions stay (events are cheap); a remount reuses them.

    def refresh(self) -> None:
        if self.sink is not None:
            self.sink(self.render_fn())

    def _subscribe(self) -> None:
        """Watch the object's channels for changes (the adapter analog of
        DOM re-render on DDS events)."""
        if self._subscribed:
            return
        channels = []
        root = getattr(self.obj, "root", None)
        if root is not None:
            channels.append(root)
        runtime = getattr(self.obj, "runtime", None)
        if runtime is not None and hasattr(runtime, "channels"):
            channels.extend(runtime.channels.values())
        store = getattr(self.obj, "store", None)
        if store is not None and hasattr(store, "channels"):
            channels.extend(store.channels.values())
        for channel in channels:
            if channel in self._subscribed:
                continue
            for event in _CHANGE_EVENTS:
                channel.on(event, self._on_change)
            self._subscribed.append(channel)

    def _on_change(self, *args) -> None:
        self.refresh()


class MountableView:
    """Transferable mount wrapper (reference MountableView): created once,
    mounted/unmounted/remounted across host surfaces without rebuilding the
    adapter."""

    def __init__(self, obj: Any):
        self.adapter = ViewAdapter(obj)
        self.mounted_at: Optional[str] = None

    def mount(self, surface_id: str, sink: Callable[[Any], None]) -> None:
        if self.mounted_at is not None:
            raise RuntimeError(f"already mounted at {self.mounted_at}")
        self.mounted_at = surface_id
        self.adapter.mount(sink)

    def unmount(self) -> None:
        self.mounted_at = None
        self.adapter.unmount()


def use_synced_state(shared_map, key: str, default: Any = None,
                     on_change: Optional[Callable[[Any], None]] = None
                     ) -> Tuple[Callable[[], Any], Callable[[Any], None]]:
    """Functional state binding (reference useStateFluid): returns
    (get_value, set_value) where set writes through to the DDS and
    `on_change(new_value)` fires for every local or remote update of the
    key — the setState re-render signal."""
    if on_change is not None:
        def _watch(changed_key, local, previous):
            if changed_key == key:
                on_change(shared_map.get(key, default))
        shared_map.on("valueChanged", _watch)

    def get_value():
        return shared_map.get(key, default)

    def set_value(value):
        shared_map.set(key, value)

    return get_value, set_value


class SyncedDataObject:
    """Reference react/syncedDataObject.ts: a data object whose declared
    state keys live in its root directory and surface as synced bindings."""

    def __init__(self, data_object, config: Dict[str, Any]):
        from ..dds.directory import SharedDirectory
        self.data_object = data_object
        self.config = dict(config)
        self._listeners: List[Callable[[str, Any], None]] = []
        # Directory valueChanged carries (path, key, local); map carries
        # (key, local, previous).
        self._root_is_dir = isinstance(data_object.root, SharedDirectory)
        data_object.root.on("valueChanged", self._on_value)

    def _on_value(self, *args) -> None:
        key = args[1] if self._root_is_dir else args[0]
        if key in self.config:
            for fn in self._listeners:
                fn(key, self.get(key))

    def on_state_change(self, fn: Callable[[str, Any], None]) -> None:
        self._listeners.append(fn)

    def get(self, key: str) -> Any:
        return self.data_object.root.get(key, self.config.get(key))

    def set(self, key: str, value: Any) -> None:
        if key not in self.config:
            raise KeyError(f"undeclared synced state key {key!r}")
        self.data_object.root.set(key, value)
