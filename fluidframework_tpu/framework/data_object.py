"""Aqueduct data objects: the developer-facing sugar over runtime + DDS.

Capability parity with reference packages/framework/aqueduct/src/
data-objects/{pureDataObject.ts:46, dataObject.ts:34} and
data-object-factories: a DataObject owns one datastore, exposes a root
SharedDirectory, and runs the initializingFirstTime / initializingFromExisting
/ hasInitialized lifecycle exactly once per in-memory instance.
"""

from __future__ import annotations

from typing import Any, Dict, Optional, Type

from ..core.events import TypedEventEmitter
from ..dds.directory import SharedDirectory
from ..dds.shared_object import FluidHandle
from ..runtime.datastore_runtime import DataStoreRuntime


class PureDataObject(TypedEventEmitter):
    """Base component with the init lifecycle but no mandated root DDS."""

    def __init__(self, store: DataStoreRuntime):
        super().__init__()
        self.store = store
        self._initialized = False

    @property
    def id(self) -> str:
        return self.store.id

    @property
    def handle(self) -> FluidHandle:
        return FluidHandle(f"/{self.store.id}", self)

    @property
    def runtime(self):
        return self.store.container

    # -- lifecycle (subclass hooks) ----------------------------------------
    def initialize(self, existing: bool) -> None:
        if self._initialized:
            return
        self._initialized = True
        if existing:
            self.initializing_from_existing()
        else:
            self.initializing_first_time()
        self.has_initialized()

    def initializing_first_time(self) -> None:
        """Create-time setup: build channels, seed initial state."""

    def initializing_from_existing(self) -> None:
        """Load-time setup: grab existing channels."""

    def has_initialized(self) -> None:
        """Runs after either path: wire event listeners etc."""


class DataObject(PureDataObject):
    """PureDataObject + a root SharedDirectory (dataObject.ts:34)."""

    ROOT_ID = "root"

    def __init__(self, store: DataStoreRuntime):
        super().__init__(store)
        self._root: Optional[SharedDirectory] = None

    @property
    def root(self) -> SharedDirectory:
        assert self._root is not None, "not initialized"
        return self._root

    def initialize(self, existing: bool) -> None:
        if not self._initialized:
            if existing:
                self._root = self.store.get_channel(self.ROOT_ID)
            else:
                self._root = self.store.create_channel(self.ROOT_ID,
                                                       SharedDirectory.TYPE)
        super().initialize(existing)


class DataObjectFactory:
    """Creates/loads DataObject instances over datastores
    (reference aqueduct DataObjectFactory)."""

    def __init__(self, type_name: str,
                 data_object_class: Type[PureDataObject]):
        self.type = type_name
        self.data_object_class = data_object_class

    def create_instance(self, container_runtime, store_id: str,
                        root: bool = True) -> PureDataObject:
        store = container_runtime.create_datastore(store_id, root=root)
        obj = self.data_object_class(store)
        obj.initialize(existing=False)
        return obj

    def load_instance(self, container_runtime, store_id: str
                      ) -> PureDataObject:
        store = container_runtime.get_datastore(store_id)
        obj = self.data_object_class(store)
        obj.initialize(existing=True)
        return obj
