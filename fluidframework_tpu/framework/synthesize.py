"""Dependency synthesis: a tiny DI container.

Capability parity with reference packages/framework/synthesize: providers
register by key (type name); scopes synthesize an object exposing the
requested optional/required providers; parent containers chain.
"""

from __future__ import annotations

from typing import Any, Callable, Dict, Optional


class DependencyContainer:
    def __init__(self, parent: Optional["DependencyContainer"] = None):
        self.parent = parent
        self._providers: Dict[str, Callable[[], Any]] = {}

    def register(self, key: str, provider: Any) -> None:
        """provider: instance or zero-arg factory."""
        self._providers[key] = (provider if callable(provider)
                                else lambda: provider)

    def has(self, key: str) -> bool:
        return key in self._providers or (
            self.parent is not None and self.parent.has(key))

    def resolve(self, key: str) -> Any:
        if key in self._providers:
            return self._providers[key]()
        if self.parent is not None:
            return self.parent.resolve(key)
        raise KeyError(f"no provider for {key!r}")

    def synthesize(self, optional: tuple = (), required: tuple = ()
                   ) -> "SynthesizedScope":
        for key in required:
            if not self.has(key):
                raise KeyError(f"missing required provider {key!r}")
        return SynthesizedScope(self, optional, required)


class SynthesizedScope:
    def __init__(self, container: DependencyContainer,
                 optional: tuple, required: tuple):
        self._container = container
        self._keys = set(optional) | set(required)

    def __getattr__(self, key: str) -> Any:
        if key.startswith("_"):
            raise AttributeError(key)
        if key not in self._keys:
            raise AttributeError(f"{key!r} not in synthesized scope")
        if not self._container.has(key):
            return None  # optional, unprovided
        return self._container.resolve(key)
