"""DDS interception wrappers.

Capability parity with reference packages/framework/dds-interceptions
(README:1-8): wrap a DDS so every local mutation passes through a callback
that can rewrite its arguments — e.g. stamping attribution properties on
SharedString edits or augmenting SharedMap values — without the consumer
knowing."""

from __future__ import annotations

from typing import Any, Callable, Dict, Optional


def create_shared_string_with_interception(
        shared_string,
        props_interceptor: Callable[[Optional[dict]], Optional[dict]]):
    """Returns a facade whose insert/annotate calls run their props through
    `props_interceptor` (reference createSharedStringWithInterception)."""

    class _Intercepted:
        def __getattr__(self, name):
            return getattr(shared_string, name)

        def insert_text(self, pos, text, props=None):
            shared_string.insert_text(pos, text, props_interceptor(props))

        def insert_marker(self, pos, props=None):
            shared_string.insert_marker(pos, props_interceptor(props))

        def annotate_range(self, start, end, props):
            shared_string.annotate_range(start, end,
                                         props_interceptor(props) or {})

    return _Intercepted()


def create_shared_map_with_interception(
        shared_map,
        set_interceptor: Callable[[str, Any], Any]):
    """Returns a facade whose set() values run through `set_interceptor`
    (reference createDirectoryWithInterception family)."""

    class _Intercepted:
        def __getattr__(self, name):
            return getattr(shared_map, name)

        def set(self, key, value):
            return shared_map.set(key, set_interceptor(key, value))

    return _Intercepted()
