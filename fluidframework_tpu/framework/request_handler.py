"""Request routing: URL -> component resolution.

Capability parity with reference packages/runtime/runtime-utils
RequestParser + packages/framework/request-handler: parse "/store/channel"
paths, route through handler chains (first handler that resolves wins).
"""

from __future__ import annotations

from typing import Any, Callable, List, Optional
from urllib.parse import parse_qs, urlparse


class RequestParser:
    def __init__(self, url: str):
        parsed = urlparse(url)
        self.url = url
        self.path_parts: List[str] = [p for p in parsed.path.split("/") if p]
        self.query = {k: v[0] for k, v in parse_qs(parsed.query).items()}

    def is_leaf(self, elements: int) -> bool:
        return len(self.path_parts) == elements

    def sub_request(self, start: int) -> "RequestParser":
        return RequestParser("/" + "/".join(self.path_parts[start:]))


RouteHandler = Callable[[RequestParser, Any], Optional[Any]]


class RequestHandlerChain:
    """First handler returning non-None wins (reference
    buildRuntimeRequestHandler)."""

    def __init__(self, *handlers: RouteHandler):
        self.handlers: List[RouteHandler] = list(handlers)

    def add(self, handler: RouteHandler) -> None:
        self.handlers.append(handler)

    def request(self, url: str, context: Any = None) -> Any:
        parser = RequestParser(url)
        for handler in self.handlers:
            result = handler(parser, context)
            if result is not None:
                return result
        raise KeyError(f"no handler resolved {url!r}")


def datastore_route_handler(runtime) -> RouteHandler:
    """Routes /storeId[/channelId] into the runtime's stores/channels."""

    def handler(parser: RequestParser, _context):
        if not parser.path_parts:
            return None
        store_id = parser.path_parts[0]
        if store_id not in runtime.datastores:
            return None
        store = runtime.datastores[store_id]
        if parser.is_leaf(1):
            return store
        channel_id = parser.path_parts[1]
        if channel_id in store.channels:
            return store.channels[channel_id]
        return None

    return handler
