"""Container runtime factories: the code-loading entry points.

Capability parity with reference aqueduct/src/container-runtime-factories/
{baseContainerRuntimeFactory.ts, containerRuntimeFactoryWithDefaultDataStore.ts:25}:
a factory owns the registry of DataObjectFactories and materializes the
default data store on first create; request routing resolves "/" to the
default data object (the reference's request handler chain).
"""

from __future__ import annotations

from typing import Dict, List, Optional

from .data_object import DataObjectFactory, PureDataObject
from .request_handler import RequestParser


class BaseContainerRuntimeFactory:
    def __init__(self, registry_entries: Optional[List[DataObjectFactory]]
                 = None):
        self.registry: Dict[str, DataObjectFactory] = {
            f.type: f for f in (registry_entries or [])}
        # Per-container instance cache: one factory serves many containers.
        self._instances: Dict[int, Dict[str, PureDataObject]] = {}

    def _container_instances(self, container) -> Dict[str, PureDataObject]:
        return self._instances.setdefault(id(container.runtime), {})

    def register(self, factory: DataObjectFactory) -> None:
        self.registry[factory.type] = factory

    # -- lifecycle hooks (subclasses) --------------------------------------
    def instantiate_first_time(self, container) -> None:
        """Create-time: build initial data stores."""

    def instantiate_from_existing(self, container) -> None:
        """Load-time: rehydrate data objects from existing stores."""

    def initialize(self, container, existing: bool) -> None:
        if existing:
            self.instantiate_from_existing(container)
        else:
            self.instantiate_first_time(container)

    # -- request routing ---------------------------------------------------
    def request(self, container, url: str):
        parser = RequestParser(url)
        instances = self._container_instances(container)
        store_id = parser.path_parts[0] if parser.path_parts else None
        if store_id in instances:
            return instances[store_id]
        raise KeyError(f"no route for {url!r}")


class ContainerRuntimeFactoryWithDefaultDataStore(BaseContainerRuntimeFactory):
    DEFAULT_ID = "default"

    def __init__(self, default_factory: DataObjectFactory,
                 registry_entries: Optional[List[DataObjectFactory]] = None):
        super().__init__([default_factory, *(registry_entries or [])])
        self.default_factory = default_factory

    def instantiate_first_time(self, container) -> None:
        obj = self.default_factory.create_instance(container.runtime,
                                                   self.DEFAULT_ID)
        self._container_instances(container)[self.DEFAULT_ID] = obj

    def instantiate_from_existing(self, container) -> None:
        obj = self.default_factory.load_instance(container.runtime,
                                                 self.DEFAULT_ID)
        self._container_instances(container)[self.DEFAULT_ID] = obj

    def get_default_object(self, container) -> PureDataObject:
        return self._container_instances(container)[self.DEFAULT_ID]

    def request(self, container, url: str = "/"):
        parser = RequestParser(url)
        if not parser.path_parts:
            return self.get_default_object(container)
        return super().request(container, url)

    # -- sugar: create or load a container and hand back the default object
    def create_detached(self, loader, document_id: str):
        container = loader.create_detached(document_id)
        self.initialize(container, existing=False)
        return container, self.get_default_object(container)

    def load(self, loader, document_id: str):
        container = loader.resolve(document_id)
        self.initialize(container, existing=True)
        return container, self.get_default_object(container)
