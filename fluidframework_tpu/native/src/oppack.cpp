// Native op packer: per-document HostOp streams -> packed int32 columns.
//
// The ingest path's hot host loop (mergetree/oppack.py pack_ops) converts
// ~1M Python ints per 100k ops; pure-Python/numpy conversion measured ~18x
// slower than the device applies the same ops (PERF.md ingest note). This
// walks the object graph once with the CPython C API (HostOp is a
// NamedTuple, i.e. a tuple: PyTuple_GET_ITEM + PyLong_AsLong per field)
// and writes straight into a caller-owned [n_fields, B, T] int32 buffer.
//
// Loaded with ctypes.PyDLL (GIL held throughout: we touch Python objects).
// Returns 0 on success; d+1 when document d overflows t steps; a negative
// code when the input shape is not the expected list-of-lists-of-tuples
// (callers fall back to the Python path).

#include <Python.h>

#include <cstdint>

extern "C" long pack_into(PyObject* streams, int32_t* dst, long b, long t,
                          long nf) {
    PyObject* fast_streams =
        PySequence_Fast(streams, "streams must be a sequence");
    if (fast_streams == nullptr) {
        PyErr_Clear();
        return -1;
    }
    if (PySequence_Fast_GET_SIZE(fast_streams) != b) {
        Py_DECREF(fast_streams);
        return -2;
    }
    long rc = 0;
    for (long d = 0; d < b && rc == 0; ++d) {
        PyObject* stream = PySequence_Fast_GET_ITEM(fast_streams, d);
        PyObject* fs = PySequence_Fast(stream, "stream must be a sequence");
        if (fs == nullptr) {
            PyErr_Clear();
            rc = -1;
            break;
        }
        const long n = PySequence_Fast_GET_SIZE(fs);
        if (n > t) {
            Py_DECREF(fs);
            rc = d + 1;  // overflow report: which document
            break;
        }
        for (long i = 0; i < n && rc == 0; ++i) {
            PyObject* op = PySequence_Fast_GET_ITEM(fs, i);
            if (!PyTuple_Check(op) || PyTuple_GET_SIZE(op) != nf) {
                rc = -3;
                break;
            }
            for (long f = 0; f < nf; ++f) {
                const long v = PyLong_AsLong(PyTuple_GET_ITEM(op, f));
                if (v == -1 && PyErr_Occurred()) {
                    PyErr_Clear();
                    rc = -4;
                    break;
                }
                if (v < INT32_MIN || v > INT32_MAX) {
                    // The Python fallback raises OverflowError here; a
                    // silent wrap could alias sentinel values. Hand the
                    // input back to the fallback to get the same error.
                    rc = -5;
                    break;
                }
                dst[(f * b + d) * t + i] = static_cast<int32_t>(v);
            }
        }
        Py_DECREF(fs);
    }
    Py_DECREF(fast_streams);
    return rc;
}
