// In-memory ordered-log broker with a C ABI: topics × partitions, offset
// monotone append, consumer-group commits, keyed partitioning.
//
// This is the native engine behind fluidframework_tpu.server.log — the
// moral equivalent of the reference's librdkafka dependency (a C++ Kafka
// client binding, server/routerlicious/packages/services/package.json:40)
// for the in-process/multi-host broker the TPU partition host consumes.
// The Python MessageLog in server/log.py is the always-available fallback
// with identical semantics (its LocalKafka role).
//
// Records are opaque byte strings; Python pickles payloads across the
// boundary the same way rdkafka ships serialized frames.

#include <cstdint>
#include <cstring>
#include <map>
#include <memory>
#include <mutex>
#include <string>
#include <unordered_map>
#include <vector>

namespace {

struct Msg {
  std::string key;
  std::string val;
};

struct Partition {
  std::vector<Msg> msgs;
  mutable std::mutex mu;

  int64_t append(const char* k, size_t klen, const char* v, size_t vlen) {
    std::lock_guard<std::mutex> g(mu);
    msgs.push_back(Msg{std::string(k, klen), std::string(v, vlen)});
    return static_cast<int64_t>(msgs.size()) - 1;
  }

  int64_t end_offset() const {
    std::lock_guard<std::mutex> g(mu);
    return static_cast<int64_t>(msgs.size());
  }
};

struct Topic {
  std::vector<std::unique_ptr<Partition>> parts;
  explicit Topic(int n) {
    for (int i = 0; i < n; ++i) parts.emplace_back(new Partition);
  }
};

struct Log {
  std::unordered_map<std::string, std::unique_ptr<Topic>> topics;
  std::map<std::string, int64_t> commits;  // "group\0topic\0part" -> next
  std::mutex mu;
  int default_partitions = 1;
};

std::mutex g_mu;
std::unordered_map<int64_t, std::unique_ptr<Log>> g_logs;
int64_t g_next_handle = 1;

Log* get_log(int64_t h) {
  std::lock_guard<std::mutex> g(g_mu);
  auto it = g_logs.find(h);
  return it == g_logs.end() ? nullptr : it->second.get();
}

Topic* get_topic(Log* log, const char* name, int partitions) {
  std::lock_guard<std::mutex> g(log->mu);
  auto it = log->topics.find(name);
  if (it == log->topics.end()) {
    int n = partitions > 0 ? partitions : log->default_partitions;
    it = log->topics
             .emplace(std::string(name), std::unique_ptr<Topic>(new Topic(n)))
             .first;
  }
  return it->second.get();
}

// Stable keyed partitioning (FNV-1a), unlike Python's per-process str hash:
// a document's partition assignment survives restarts, which the per-doc
// checkpoint/resume path depends on.
uint64_t fnv1a(const char* s, size_t n) {
  uint64_t h = 1469598103934665603ull;
  for (size_t i = 0; i < n; ++i) {
    h ^= static_cast<unsigned char>(s[i]);
    h *= 1099511628211ull;
  }
  return h;
}

std::string commit_key(const char* group, const char* topic, int part) {
  std::string k(group);
  k.push_back('\0');
  k += topic;
  k.push_back('\0');
  k += std::to_string(part);
  return k;
}

}  // namespace

extern "C" {

int64_t oplog_create(int default_partitions) {
  std::lock_guard<std::mutex> g(g_mu);
  int64_t h = g_next_handle++;
  auto log = std::unique_ptr<Log>(new Log);
  log->default_partitions = default_partitions > 0 ? default_partitions : 1;
  g_logs.emplace(h, std::move(log));
  return h;
}

void oplog_destroy(int64_t h) {
  std::lock_guard<std::mutex> g(g_mu);
  g_logs.erase(h);
}

// Ensure the topic exists; returns its partition count (or -1 on bad handle).
int oplog_topic(int64_t h, const char* name, int partitions) {
  Log* log = get_log(h);
  if (!log) return -1;
  return static_cast<int>(get_topic(log, name, partitions)->parts.size());
}

int oplog_partition_for(int64_t h, const char* topic, const char* key,
                        size_t klen) {
  Log* log = get_log(h);
  if (!log) return -1;
  Topic* t = get_topic(log, topic, 0);
  return static_cast<int>(fnv1a(key, klen) % t->parts.size());
}

// partition < 0 routes by key hash. Returns the assigned offset, -1 on error.
int64_t oplog_append(int64_t h, const char* topic, int partition,
                     const char* key, size_t klen, const char* val,
                     size_t vlen) {
  Log* log = get_log(h);
  if (!log) return -1;
  Topic* t = get_topic(log, topic, 0);
  if (partition < 0)
    partition = static_cast<int>(fnv1a(key, klen) % t->parts.size());
  if (partition >= static_cast<int>(t->parts.size())) return -1;
  return t->parts[partition]->append(key, klen, val, vlen);
}

int64_t oplog_end_offset(int64_t h, const char* topic, int partition) {
  Log* log = get_log(h);
  if (!log) return -1;
  Topic* t = get_topic(log, topic, 0);
  if (partition < 0 || partition >= static_cast<int>(t->parts.size()))
    return -1;
  return t->parts[partition]->end_offset();
}

// Copy up to max_msgs whole records starting at `start` (or the group's
// committed offset when start < 0) into buf as frames:
//   u64 offset | u32 klen | u32 vlen | key bytes | val bytes
// Returns bytes written; *out_count = records copied. If the first record
// alone does not fit, returns -(bytes needed) so the caller can grow buf.
int64_t oplog_poll(int64_t h, const char* group, const char* topic,
                   int partition, int max_msgs, int64_t start, char* buf,
                   int64_t buflen, int64_t* out_count) {
  *out_count = 0;
  Log* log = get_log(h);
  if (!log) return -1;
  Topic* t = get_topic(log, topic, 0);
  if (partition < 0 || partition >= static_cast<int>(t->parts.size()))
    return -1;
  if (start < 0) {
    std::lock_guard<std::mutex> g(log->mu);
    auto it = log->commits.find(commit_key(group, topic, partition));
    start = it == log->commits.end() ? 0 : it->second;
  }
  Partition* p = t->parts[partition].get();
  std::lock_guard<std::mutex> g(p->mu);
  int64_t written = 0;
  for (int i = 0; i < max_msgs; ++i) {
    int64_t off = start + i;
    if (off >= static_cast<int64_t>(p->msgs.size())) break;
    const Msg& m = p->msgs[static_cast<size_t>(off)];
    int64_t need = 16 + static_cast<int64_t>(m.key.size() + m.val.size());
    if (written + need > buflen) {
      if (*out_count == 0) return -need;
      break;
    }
    char* dst = buf + written;
    uint64_t off_u = static_cast<uint64_t>(off);
    uint32_t kl = static_cast<uint32_t>(m.key.size());
    uint32_t vl = static_cast<uint32_t>(m.val.size());
    std::memcpy(dst, &off_u, 8);
    std::memcpy(dst + 8, &kl, 4);
    std::memcpy(dst + 12, &vl, 4);
    std::memcpy(dst + 16, m.key.data(), kl);
    std::memcpy(dst + 16 + kl, m.val.data(), vl);
    written += need;
    ++*out_count;
  }
  return written;
}

// Commit "processed through offset": the next poll starts at offset + 1.
// Commits never move backwards (replay safety).
void oplog_commit(int64_t h, const char* group, const char* topic,
                  int partition, int64_t offset) {
  Log* log = get_log(h);
  if (!log) return;
  std::lock_guard<std::mutex> g(log->mu);
  std::string k = commit_key(group, topic, partition);
  auto it = log->commits.find(k);
  if (it == log->commits.end() || offset + 1 > it->second)
    log->commits[k] = offset + 1;
}

int64_t oplog_committed(int64_t h, const char* group, const char* topic,
                        int partition) {
  Log* log = get_log(h);
  if (!log) return -1;
  std::lock_guard<std::mutex> g(log->mu);
  auto it = log->commits.find(commit_key(group, topic, partition));
  return it == log->commits.end() ? 0 : it->second;
}

}  // extern "C"
